"""Crash-recovery soak harness: recovery-under-a-budget as a gate (ISSUE 6).

ROADMAP item 4 ("snapshot + log-compaction *under load*, crash-recovery
replay time measured against a recovery-time budget") as an executable
endurance workload: sustained mixed traffic — immediate service-task work
plus *parked* instances (timer waits, message-correlation waits) that keep
long-lived state across restarts — over an aggressive snapshot cadence, with
seeded power-loss crash-restarts fired **mid-flush** (buffered journal bytes
not yet covered by an fsync are lost) and **mid-snapshot** (the newest
persisted snapshot is torn the way a crash during the pending→committed
commit would leave it). After every restart the harness asserts the
durability pillar the paper promises:

- **no acked record lost** — every client-acknowledged command is in the
  final export stream exactly once (after position dedup);
- **no duplicate exports** — within an exporter container's lifetime
  positions are strictly increasing, and a re-export after a restart
  (at-least-once catch-up) must carry byte-identical record content;
- **replay bounded by snapshot cadence** — the records replayed on recovery
  never exceed the debt actually accumulated past the snapshot the recovery
  anchored on (plus the measured per-period append bound on untampered
  rounds);
- **recovery within budget** — every rebuild completes inside
  ``recovery_budget_ms`` (the `recovery_budget_exceeded` alert stays quiet);

and captures every recovery in a flight-recorder dump, so each restart
leaves a reviewable artifact (``bench.py --soak`` uploads them from CI).

Built on the PR 1 chaos harness (seeded, deterministic: a failing run
replays from its seed) and the PR 4 observability plane (metrics store +
flight recorder).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any

from zeebe_tpu.exporters import Exporter
from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
from zeebe_tpu.protocol import ValueType, command
from zeebe_tpu.protocol.intent import (
    DeploymentIntent,
    MessageIntent,
    ProcessInstanceCreationIntent,
)
from zeebe_tpu.testing.chaos import ChaosHarness, FaultPlan


@dataclasses.dataclass
class SoakConfig:
    """Knobs for one soak run. Defaults are the CI short mode — a few
    minutes on CPU; nightly/full runs scale ``rounds`` and
    ``traffic_per_round`` up."""

    seed: int = 20260803
    rounds: int = 5                  # crash-restart rounds (≥ 5 per ISSUE 6)
    traffic_per_round: int = 18      # instance creations between crashes
    snapshot_period_ms: int = 1500   # aggressive: several snapshots per round
    recovery_budget_ms: int = 30_000
    snapshot_chain_length: int = 4   # force delta chains AND rebases
    broker_count: int = 1            # recovery = time-to-leader after a kill
    replication_factor: int = 1
    partition_id: int = 1
    # every Nth round the crash also tears the newest persisted snapshot
    # (power loss during the pending→committed commit): recovery must fall
    # back to the previous fully-valid chain, never crash
    tamper_every: int = 2
    step_ms: int = 50
    drain_ticks: int = 400           # post-restart convergence bound


class _ExportSink:
    """Cross-lifetime export ledger. Exporter *instances* die with their
    broker; the sink survives the whole soak and holds the deduplicated
    export stream plus every duplicate-semantics violation."""

    def __init__(self) -> None:
        self.by_position: dict[int, bytes] = {}
        self.total_exports = 0
        self.reexports = 0
        self.violations: list[str] = []


class SoakExporter(Exporter):
    """Strict-ordering exporter over a shared sink: within one container
    lifetime positions must be strictly increasing (a duplicate inside a
    lifetime is a bug, not at-least-once); across lifetimes a re-export is
    legal catch-up but must be byte-identical to the first export of that
    position (the sink dedups by position — divergent content would mean
    the log itself changed under an acked record)."""

    def __init__(self, sink: _ExportSink) -> None:
        self.sink = sink
        self._last_position = -1

    def export(self, record) -> None:
        sink = self.sink
        sink.total_exports += 1
        pos = record.position
        if pos <= self._last_position:
            sink.violations.append(
                f"duplicate export within container lifetime: position {pos} "
                f"after {self._last_position}")
        self._last_position = pos
        data = record.record.to_bytes()
        seen = sink.by_position.get(pos)
        if seen is None:
            sink.by_position[pos] = data
        else:
            sink.reexports += 1
            if seen != data:
                sink.violations.append(
                    f"divergent re-export at position {pos}: content changed "
                    f"across restarts")
        self.controller.update_last_exported_position(pos)


def _deploy_cmd(*models) -> Any:
    return command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
        "resources": [
            {"resourceName": f"soak-{i}.bpmn", "resource": to_bpmn_xml(m)}
            for i, m in enumerate(models)
        ],
    })


def _create_cmd(process_id: str, variables: dict) -> Any:
    return command(
        ValueType.PROCESS_INSTANCE_CREATION,
        ProcessInstanceCreationIntent.CREATE,
        {"bpmnProcessId": process_id, "version": -1, "variables": variables},
    )


def _soak_models():
    work = (
        Bpmn.create_executable_process("soak_work")
        .start_event("s").service_task("t", job_type="soak").end_event("e")
        .done()
    )
    timer = (
        Bpmn.create_executable_process("soak_timer")
        .start_event("s")
        .intermediate_catch_timer("wait", duration="PT2S")
        .end_event("e")
        .done()
    )
    msg = (
        Bpmn.create_executable_process("soak_msg")
        .start_event("s")
        .intermediate_catch_message("wait", message_name="soak-msg",
                                    correlation_key="=ck")
        .end_event("e")
        .done()
    )
    return work, timer, msg


def tamper_snapshot(cluster_directory, node_id: str, partition_id: int,
                    pick: str = "newest") -> str | None:
    """Corrupt a persisted snapshot on a (crashed) broker's disk.

    ``pick="newest"`` simulates power loss during the store's
    pending→committed commit: the newest snapshot dir loses the tail of
    one file (torn write) and a half-written pending dir is left behind —
    recovery must skip both and fall back (ISSUE 6 / ISSUE 8 crash soaks).

    ``pick="mid-chain"`` tears a DELTA in the *middle* of the incremental
    chain (neither tip nor base) instead — bit rot / latent media error on
    an old chain member. The chain validator must declare every descendant
    invalid and recovery must fall back to the newest fully-valid ancestor
    chain (ISSUE 14). Returns the torn snapshot's dir name, or None when
    no eligible victim exists (e.g. no mid-chain delta yet)."""
    from zeebe_tpu.state.snapshot import SnapshotId

    part_dir = (Path(cluster_directory) / node_id
                / f"partition-{partition_id}" / "snapshots")
    # numeric snapshot-id order, NOT name order: lexicographic sort ranks
    # "98-…" after "103-…" and would tear an older chain member (the
    # base!) instead of the tip
    snaps = sorted(
        ((snap_id, p)
         for p in (part_dir / "snapshots").iterdir() if p.is_dir()
         and (snap_id := SnapshotId.parse(p.name)) is not None),
        key=lambda pair: pair[0])
    if not snaps:
        return None
    if pick == "mid-chain":
        # a delta that is neither the newest dir (the tip) nor the chain
        # base: snaps[1:-1] with a delta.bin
        candidates = [p for _sid, p in snaps[1:-1]
                      if (p / "delta.bin").is_file()]
        if not candidates:
            return None
        victim = candidates[len(candidates) // 2]
        names = ("delta.bin",)
        leave_pending = False
    else:
        victim = snaps[-1][1]
        names = ("delta.bin", "state.bin", "durable.bin")
        leave_pending = True
    torn = False
    for name in names:
        f = victim / name
        if f.is_file():
            data = f.read_bytes()
            f.write_bytes(data[: max(len(data) // 2, 1)])
            torn = True
            break
    if not torn:
        return None
    if leave_pending:
        pending = part_dir / "pending" / "999999-1-999999-999999"
        pending.mkdir(parents=True, exist_ok=True)
        (pending / "state.bin").write_bytes(b"partial")
    return victim.name


def tamper_newest_snapshot(cluster_directory, node_id: str,
                           partition_id: int) -> str | None:
    """Back-compat alias: tear the newest snapshot (see
    :func:`tamper_snapshot`)."""
    return tamper_snapshot(cluster_directory, node_id, partition_id,
                           pick="newest")


class SoakHarness:
    """Drives the endurance workload over a seeded chaos cluster and turns
    each crash-restart into a budget-checked, flight-recorded recovery."""

    def __init__(self, cfg: SoakConfig | None = None,
                 directory: str | Path | None = None) -> None:
        import random

        self.cfg = cfg or SoakConfig()
        self.sink = _ExportSink()
        self.rng = random.Random(self.cfg.seed)
        self.chaos = ChaosHarness(
            # message-level faults stay off: crash-restarts are the fault
            # under test and the plan seed still names the whole run
            FaultPlan(seed=self.cfg.seed),
            broker_count=self.cfg.broker_count,
            partition_count=1,
            replication_factor=self.cfg.replication_factor,
            directory=directory,
            exporters_factory=lambda: {"soak": SoakExporter(self.sink)},
            step_ms=self.cfg.step_ms,
            snapshot_period_ms=self.cfg.snapshot_period_ms,
            recovery_budget_ms=self.cfg.recovery_budget_ms,
            snapshot_chain_length=self.cfg.snapshot_chain_length,
        )
        self.cluster = self.chaos.cluster
        self.acked: dict[str, int] = {}     # tag -> committed position
        self.violations: list[str] = []
        self.recoveries: list[dict] = []
        self.flight_dumps: list[str] = []
        self.snapshot_kinds: dict[str, int] = {}
        self.max_chain_len = 0
        self._msg_keys_parked: list[str] = []
        self._seq = 0

    # -- workload --------------------------------------------------------------

    def _leader(self):
        return self.cluster.leader(self.cfg.partition_id)

    def _write(self, record) -> int | None:
        return self.cluster.write_command(self.cfg.partition_id, record)

    def _create(self, process_id: str, variables: dict, tag: str) -> None:
        pos = self._write(_create_cmd(process_id, dict(variables, soakTag=tag)))
        if pos is None:
            return
        leader = self._leader()
        if leader is not None and leader.stream.last_position >= pos:
            self.acked[tag] = pos   # committed ⇒ acknowledged ⇒ durable

    def _traffic_round(self, round_no: int) -> None:
        """Mixed sustained traffic: immediate work, parked timers, parked
        message waits, and correlations that wake earlier parked waits."""
        for _ in range(self.cfg.traffic_per_round):
            self._seq += 1
            tag = f"r{round_no}-{self._seq}"
            roll = self.rng.random()
            if roll < 0.4:
                self._create("soak_work", {}, tag)
            elif roll < 0.6:
                self._create("soak_timer", {}, tag)
            elif roll < 0.8 or not self._msg_keys_parked:
                key = f"ck-{self._seq}"
                self._create("soak_msg", {"ck": key}, tag)
                self._msg_keys_parked.append(key)
            else:
                key = self._msg_keys_parked.pop(
                    self.rng.randrange(len(self._msg_keys_parked)))
                self._write(command(ValueType.MESSAGE, MessageIntent.PUBLISH, {
                    "name": "soak-msg", "correlationKey": key,
                    "timeToLive": 60_000, "messageId": "",
                    "variables": {"soakTag": tag},
                }))
            self.chaos.run_ticks(1)

    # -- crash / tamper / restart ----------------------------------------------

    def _tamper_newest_snapshot(self, node_id: str) -> str | None:
        return tamper_newest_snapshot(
            self.cluster.directory, node_id, self.cfg.partition_id)

    def _await_recovery(self, round_no: int) -> None:
        """Run until a leader re-emerges and exporters drain; cap bounded."""
        leader = None
        for _ in range(self.cfg.drain_ticks):
            self.chaos.run_ticks(1)
            leader = self._leader()
            if leader is None:
                continue
            director = leader.exporter_director
            if director is None:
                continue
            lag = leader.stream.last_position - min(
                (c.position for c in director.containers), default=0)
            if lag <= 0:
                break
        if leader is None:
            self.violations.append(
                f"round {round_no}: no leader within {self.cfg.drain_ticks} "
                f"ticks of restart (seed {self.cfg.seed})")

    def _check_recovery(self, round_no: int, tampered: str | None,
                        debt_at_crash: int, appends_per_period: int) -> None:
        leader = self._leader()
        if leader is None:
            return
        rec = leader.last_recovery
        if rec is None:
            self.violations.append(
                f"round {round_no}: restarted leader has no recovery record")
            return
        info = dict(rec, round=round_no, tamperedSnapshot=tampered,
                    debtAtCrash=debt_at_crash)
        self.recoveries.append(info)
        if not rec["withinBudget"]:
            self.violations.append(
                f"round {round_no}: recovery blew the budget "
                f"({rec['durationMs']:.1f}ms > {rec['budgetMs']}ms)")
        # replay bounded by the debt past the snapshot the recovery actually
        # anchored on; on untampered rounds that anchor is the pre-crash tip,
        # so the bound collapses to the snapshot-cadence debt itself
        anchor_bound = rec["snapshotAgeRecords"] + 8
        if rec["replayRecords"] > anchor_bound:
            self.violations.append(
                f"round {round_no}: replayed {rec['replayRecords']} records, "
                f"more than the anchored snapshot debt {anchor_bound}")
        if tampered is None and debt_at_crash > max(
                3 * appends_per_period, 64):
            self.violations.append(
                f"round {round_no}: snapshot debt at crash {debt_at_crash} "
                f"exceeds 3x the per-period append bound "
                f"({appends_per_period}/period) — the cadence/adaptive "
                f"scheduler is not keeping up")
        self.max_chain_len = max(self.max_chain_len,
                                 rec.get("chainLength") or 0)

    def _collect_flight_dumps(self, round_no: int, node_id: str,
                              since_ms: int) -> None:
        from zeebe_tpu.testing.evidence import collect_flight_dumps

        collect_flight_dumps(self.cluster.directory / node_id,
                             self.flight_dumps, since_ms,
                             f"round {round_no}", self.violations)

    # -- final invariants ------------------------------------------------------

    def _check_acked_completeness(self) -> None:
        """Every acknowledged command survived every crash: present in the
        deduplicated export stream exactly once (the sink would have flagged
        divergent duplicates already)."""
        for tag, pos in self.acked.items():
            if pos not in self.sink.by_position:
                self.violations.append(
                    f"acked record lost: tag {tag} at position {pos} never "
                    f"reached the export stream")

    def _snapshot_kind_counts(self) -> dict[str, int]:
        import re

        from zeebe_tpu.utils.metrics import REGISTRY

        out: dict[str, int] = {}
        for name, _kind, labels, value in REGISTRY.snapshot():
            if name.endswith("_snapshot_kind_total"):
                m = re.search(r'kind="([^"]+)"', labels)
                if m:
                    out[m.group(1)] = out.get(m.group(1), 0) + int(value)
        return out

    # -- the run ---------------------------------------------------------------

    def run(self) -> dict:
        cfg = self.cfg
        c = self.cluster
        try:
            c.await_leaders()
            self._write(_deploy_cmd(*_soak_models()))
            self.chaos.run_ticks(5)
            appends_per_period = 1
            for round_no in range(1, cfg.rounds + 1):
                before = (self._leader().stream.last_position
                          if self._leader() else 0)
                self._traffic_round(round_no)
                leader = self._leader()
                if leader is None:
                    self.violations.append(
                        f"round {round_no}: lost the leader during traffic")
                    break
                # per-period append bound for the cadence check: traffic this
                # round, normalized to one snapshot period
                round_ms = max(cfg.traffic_per_round * 7 * cfg.step_ms, 1)
                appended = leader.stream.last_position - before
                appends_per_period = max(
                    1 + appended * cfg.snapshot_period_ms // round_ms,
                    appends_per_period)
                chain = leader.snapshot_store.latest_valid_chain()
                tip_processed = (chain[-1].id.processed_position
                                 if chain else 0)
                debt_at_crash = leader.stream.last_position - tip_processed
                node_id = c.leader_broker(cfg.partition_id).cfg.node_id
                # mid-flush fuel: appends raced into the group-commit buffer
                # with no covering fsync — the power loss eats them (they are
                # unacked, so no invariant covers them)
                for _ in range(3):
                    try:
                        leader.client_write(_create_cmd(
                            "soak_work", {"soakTag": f"unacked-r{round_no}"}))
                    except Exception:  # noqa: BLE001 — backpressure may
                        break          # reject the fuel; the crash is next
                c.hard_crash_broker(node_id)
                self.chaos.clear_exporter_watermarks(node_id)
                tampered = None
                if cfg.tamper_every and round_no % cfg.tamper_every == 0:
                    tampered = self._tamper_newest_snapshot(node_id)
                restart_ms = self.cluster.clock()
                c.restart_broker(node_id)
                self.chaos.clear_exporter_watermarks(node_id)
                self._await_recovery(round_no)
                self._check_recovery(round_no, tampered, debt_at_crash,
                                     appends_per_period)
                self._collect_flight_dumps(round_no, node_id, restart_ms)
            # drain: fire remaining timers, wake remaining message waits
            self.chaos.quiesce(60)
            self._check_acked_completeness()
            self.chaos.check_exactly_once_materialization(cfg.partition_id)
            self.violations.extend(self.chaos.violations)
            self.violations.extend(self.sink.violations)
            self.snapshot_kinds = self._snapshot_kind_counts()
            return self.report()
        finally:
            self.chaos.close()

    def report(self) -> dict:
        recoveries = self.recoveries
        durations = [r["durationMs"] for r in recoveries]
        return {
            "seed": self.cfg.seed,
            "rounds": self.cfg.rounds,
            "restarts": len(recoveries),
            "ackedCommands": len(self.acked),
            "exports": {
                "total": self.sink.total_exports,
                "unique": len(self.sink.by_position),
                "reexports": self.sink.reexports,
            },
            "recoveries": recoveries,
            "recoveryMs": {
                "max": max(durations, default=0.0),
                "mean": (sum(durations) / len(durations)) if durations else 0.0,
            },
            "budgetMs": self.cfg.recovery_budget_ms,
            "withinBudget": all(r["withinBudget"] for r in recoveries),
            "maxChainLength": self.max_chain_len,
            "snapshotKinds": self.snapshot_kinds,
            "flightDumps": self.flight_dumps,
            "violations": self.violations,
        }


def run_soak(cfg: SoakConfig | None = None,
             directory: str | Path | None = None) -> dict:
    """One-call entry point (bench.py --soak, tests)."""
    return SoakHarness(cfg, directory=directory).run()
