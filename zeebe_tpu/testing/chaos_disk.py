"""Disk-layer chaos: seeded storage fault injection (ISSUE 14).

``chaos_tcp.py`` made the network lie; this module makes the *disk* lie.
A seeded :class:`DiskFaultPlan` is applied by a :class:`DiskChaosController`
installed into the ``zeebe_tpu.utils.storage_io`` seam — the one indirection
every storage writer (journal segments, snapshot stores, the cold tier,
backup stores) routes its ``open``/``write``/``fsync``/``replace`` calls
through — so every fault class lands exactly where real hardware would
produce it:

- **eio / enospc** — a write raises ``OSError(EIO)`` / ``OSError(ENOSPC)``
  with nothing reaching the file;
- **torn** — a write persists only a seeded-length *prefix* before raising
  (the classic crash-torn/short-write shape);
- **fsync_fail** — ``fsync`` raises ``OSError(EIO)`` (the fsyncgate shape:
  after a failed fsync the page cache state is undefined — the journal must
  fail the segment hard, not retry on the same fd);
- **fsync_stall** — ``fsync`` blocks ``stall_ms`` before succeeding (a dying
  disk's latency tail; trips the journal's slow-flush flight events);
- **bitrot** — every ``bitrot_interval_ms`` one byte of an *at-rest* file
  (journal segment, snapshot file, cold segment) is flipped in place, and
  the flip is recorded in a JSONL **ledger** so the torture checker can
  prove each one was detected-or-repaired before wrong bytes were served.

Faults apply per **path class** (``journal`` | ``snapshot`` | ``cold`` |
``backup``, see :func:`classify_path`) so a scenario can rot snapshots while
leaving journals honest. Per-member RNG streams derive from
``seed ^ crc32(member id)`` exactly like the TCP plane. Evidence discipline
matches ``chaos_tcp`` too: per-life applied-fault **counts snapshots**
(throttled file dumps, a SIGKILL loses ≤ one interval) — a
configured-but-never-applied fault class is a torture-gate violation, never
silent coverage.

Environment wiring (the worker process entry):

- ``ZEEBE_CHAOS_DISK`` — the spec, e.g.
  ``seed=7,eio=0.01,enospc=0.005,torn=0.01,fsync_fail=0.004,
  fsync_stall=0.01,stall_ms=120,bitrot_interval_ms=1500;
  classes=journal|snapshot|cold``
- the worker entry installs the parsed controller into ``storage_io`` and
  drives :meth:`DiskChaosController.tick` from its pump loop (bit-rot +
  counts dumps ride the tick, not the IO path).
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import os
import time
from pathlib import Path

from zeebe_tpu.testing.chaos_common import (
    CountsSnapshot,
    JsonlLedger,
    member_rng,
    parse_spec_fields,
)

logger = logging.getLogger("zeebe_tpu.testing.chaos_disk")

#: every fault class a plan can configure (the torture gate asserts a
#: nonzero observed count for each CONFIGURED one)
FAULT_CLASSES = ("eio", "enospc", "torn", "fsync_fail", "fsync_stall",
                 "bitrot")

#: default path classes faults apply to (backup stores are opt-in: the
#: torture harness does not run one)
DEFAULT_PATH_CLASSES = ("journal", "snapshot", "cold")


@dataclasses.dataclass
class DiskFaultPlan:
    """Seeded per-operation fault probabilities + the at-rest bit-rot
    cadence. Probabilities apply per write / per fsync on files whose
    :func:`classify_path` class is enabled in ``classes``."""

    seed: int = 0
    eio_p: float = 0.0
    enospc_p: float = 0.0
    torn_p: float = 0.0
    fsync_fail_p: float = 0.0
    fsync_stall_p: float = 0.0
    stall_ms: int = 200
    #: 0 disables at-rest bit rot; otherwise one flip per interval
    bitrot_interval_ms: int = 0
    #: first flip no earlier than this long after install: boot-era
    #: journal files are tiny, so undelayed rot concentrates enough
    #: per-file damage to destroy the same region on EVERY replica
    #: faster than repair can re-replicate — a pressure no RF can
    #: survive and far beyond any real disk's rot rate
    bitrot_delay_ms: int = 0
    classes: tuple = DEFAULT_PATH_CLASSES

    def configured_classes(self) -> list[str]:
        """The fault classes this plan can actually produce."""
        out = []
        if self.eio_p > 0:
            out.append("eio")
        if self.enospc_p > 0:
            out.append("enospc")
        if self.torn_p > 0:
            out.append("torn")
        if self.fsync_fail_p > 0:
            out.append("fsync_fail")
        if self.fsync_stall_p > 0:
            out.append("fsync_stall")
        if self.bitrot_interval_ms > 0:
            out.append("bitrot")
        return out


def format_spec(plan: DiskFaultPlan) -> str:
    parts = [
        f"seed={plan.seed},eio={plan.eio_p},enospc={plan.enospc_p},"
        f"torn={plan.torn_p},fsync_fail={plan.fsync_fail_p},"
        f"fsync_stall={plan.fsync_stall_p},stall_ms={plan.stall_ms},"
        f"bitrot_interval_ms={plan.bitrot_interval_ms},"
        f"bitrot_delay_ms={plan.bitrot_delay_ms}"
    ]
    parts.append("classes=" + "|".join(plan.classes))
    return ";".join(parts)


def parse_spec(spec: str) -> DiskFaultPlan:
    """Inverse of :func:`format_spec`."""
    plan = DiskFaultPlan()
    for section in spec.split(";"):
        section = section.strip()
        if not section:
            continue
        if section.startswith("classes="):
            plan.classes = tuple(
                c.strip() for c in section[len("classes="):].split("|")
                if c.strip())
            continue
        parse_spec_fields(section, {
            "seed": lambda v: setattr(plan, "seed", int(v)),
            "eio": lambda v: setattr(plan, "eio_p", float(v)),
            "enospc": lambda v: setattr(plan, "enospc_p", float(v)),
            "torn": lambda v: setattr(plan, "torn_p", float(v)),
            "fsync_fail": lambda v: setattr(plan, "fsync_fail_p", float(v)),
            "fsync_stall": lambda v: setattr(plan, "fsync_stall_p", float(v)),
            "stall_ms": lambda v: setattr(plan, "stall_ms", int(v)),
            "bitrot_interval_ms": lambda v: setattr(
                plan, "bitrot_interval_ms", int(v)),
            "bitrot_delay_ms": lambda v: setattr(
                plan, "bitrot_delay_ms", int(v)),
        })
    return plan


def classify_path(path) -> str | None:
    """Storage path class of ``path``: ``journal`` (segmented-journal
    ``*.log`` / ``*.meta`` files), ``snapshot`` (anything under a
    ``snapshots``/``pending`` store dir), ``cold`` (``cold-*.seg``),
    ``backup`` (under a ``backups`` dir), or None (not a storage file —
    never faulted)."""
    s = str(path)
    name = os.path.basename(s)
    if name.endswith(".log") or name.endswith(".meta"):
        return "journal"
    if name.startswith("cold-") and name.endswith(".seg"):
        return "cold"
    parts = s.replace(os.sep, "/").split("/")
    if "snapshots" in parts or "pending" in parts:
        return "snapshot"
    if "backups" in parts:
        return "backup"
    return None


class DiskChaosController:
    """The object ``storage_io`` consults on every storage write/fsync.

    Thread-wise: write/fsync decisions run on whatever thread performs the
    IO (pump threads, snapshot persists); ``tick`` (bit-rot + counts dumps)
    runs on the worker's main pump loop. The RNG is shared — chaos needs no
    bit-level reproducibility across threads, only seeded coverage (same
    posture as the TCP plane's real-scheduling caveat)."""

    def __init__(self, plan: DiskFaultPlan, member_id: str = "",
                 root: str | Path | None = None) -> None:
        self.plan = plan
        self.member_id = member_id
        #: directory tree scanned for at-rest bit-rot candidates
        self.root = Path(root) if root is not None else None
        self.rng = member_rng(plan.seed, member_id)
        self.counts = {"writes": 0, "fsyncs": 0}
        for cls in FAULT_CLASSES:
            self.counts[cls] = 0
        self._counts_snap = CountsSnapshot(member_id)
        self._ledger_sink = JsonlLedger()
        self._last_bitrot = time.time() * 1000.0 + plan.bitrot_delay_ms
        # armed=False freezes probabilistic faults (harness quiesce phases
        # need the disk honest while evidence drains); the harness flips
        # it remotely by creating ``disarm_file`` (checked on tick —
        # same runtime-control pattern as chaos_tcp's windows file)
        self.armed = True
        self.disarm_file: str | None = None

    # -- write/fsync faults (called from storage_io) ---------------------------

    def _enabled(self, path) -> bool:
        if not self.armed:
            return False
        cls = classify_path(path)
        return cls is not None and cls in self.plan.classes

    def write_fault(self, path, data_len: int) -> tuple[str, int]:
        """Fault decision for one write: ``("ok", 0)``, ``("eio", 0)``,
        ``("enospc", 0)``, or ``("torn", prefix_len)`` — the caller persists
        ``prefix_len`` bytes then raises."""
        self.counts["writes"] += 1
        if not self._enabled(path):
            return "ok", 0
        plan = self.plan
        r = self.rng.random()
        if r < plan.eio_p:
            self.counts["eio"] += 1
            return "eio", 0
        r -= plan.eio_p
        if r < plan.enospc_p:
            self.counts["enospc"] += 1
            return "enospc", 0
        r -= plan.enospc_p
        if r < plan.torn_p and data_len > 1:
            self.counts["torn"] += 1
            return "torn", 1 + self.rng.randrange(data_len - 1)
        return "ok", 0

    def fsync_fault(self, path) -> None:
        """Apply the fsync fault decision: may sleep (stall) or raise
        ``OSError(EIO)`` (fsyncgate) before the real fsync runs."""
        self.counts["fsyncs"] += 1
        if not self._enabled(path):
            return
        plan = self.plan
        r = self.rng.random()
        if r < plan.fsync_fail_p:
            self.counts["fsync_fail"] += 1
            raise OSError(errno.EIO, f"chaos fsync failure on {path}")
        r -= plan.fsync_fail_p
        if r < plan.fsync_stall_p:
            self.counts["fsync_stall"] += 1
            time.sleep(plan.stall_ms / 1000.0)

    # -- the tick (bit-rot + evidence dumps) -----------------------------------

    def tick(self, now_ms: float | None = None) -> None:
        now = time.time() * 1000.0 if now_ms is None else now_ms
        if (self.armed and self.disarm_file is not None
                and os.path.exists(self.disarm_file)):
            self.armed = False
            logger.warning("disk chaos DISARMED for %s", self.member_id)
        if (self.armed and self.plan.bitrot_interval_ms > 0
                and self.root is not None
                and now - self._last_bitrot >= self.plan.bitrot_interval_ms):
            self._last_bitrot = now
            self._apply_bitrot(now)
        self._maybe_dump_counts()

    def _bitrot_candidates(self) -> list[tuple[str, Path]]:
        out: list[tuple[str, Path]] = []
        root = self.root
        if "journal" in self.plan.classes:
            # raft segments live one level deeper than stream segments
            # (<partition>/raft/raft-log/*.log vs <partition>/stream/*.log)
            for pattern in ("**/raft/raft-log/*.log", "**/stream/*.log"):
                for p in root.glob(pattern):
                    out.append(("journal", p))
        if "snapshot" in self.plan.classes:
            for p in root.glob("**/snapshots/snapshots/*/*"):
                if p.is_file():
                    out.append(("snapshot", p))
        if "cold" in self.plan.classes:
            for p in root.glob("**/cold/cold-*.seg"):
                out.append(("cold", p))
        return out

    #: segment header bytes never flipped in journal files — a rotten header
    #: is an unopenable segment, a different (coarser) failure mode than the
    #: frame-level rot the scrubber hunts
    _JOURNAL_HEADER = 24

    def _apply_bitrot(self, now_ms: float) -> None:
        candidates = self._bitrot_candidates()
        self.rng.shuffle(candidates)
        for cls, path in candidates:
            floor = self._JOURNAL_HEADER if cls == "journal" else 0
            try:
                size = path.stat().st_size
                if size <= floor + 1:
                    continue
                offset = floor + self.rng.randrange(size - floor)
                fd = os.open(path, os.O_RDWR)
                try:
                    old = os.pread(fd, 1, offset)
                    if len(old) != 1:
                        continue
                    os.pwrite(fd, bytes((old[0] ^ 0xFF,)), offset)
                finally:
                    os.close(fd)
            except OSError:
                continue
            self.counts["bitrot"] += 1
            self._ledger({"path": str(path), "class": cls, "offset": offset,
                          "atMs": now_ms, "member": self.member_id,
                          "pid": os.getpid()})
            logger.warning("disk chaos: bit-rot %s @%d (%s)", path, offset,
                           cls)
            return

    @property
    def counts_file(self):
        return self._counts_snap.counts_file

    @counts_file.setter
    def counts_file(self, value) -> None:
        self._counts_snap.counts_file = value

    @property
    def ledger_file(self):
        return self._ledger_sink.path

    @ledger_file.setter
    def ledger_file(self, value) -> None:
        self._ledger_sink.path = value

    def _ledger(self, entry: dict) -> None:
        self._ledger_sink.append(entry)

    def _maybe_dump_counts(self) -> None:
        self._counts_snap.maybe_dump(self.counts)


def maybe_install_from_env(member_id: str = "",
                           data_dir: str | None = None,
                           env: dict | None = None):
    """Install a :class:`DiskChaosController` into the ``storage_io`` seam
    when ``ZEEBE_CHAOS_DISK`` is set; returns it (or None). ``data_dir``
    roots the bit-rot scan and the evidence files."""
    from zeebe_tpu.utils import storage_io

    env = os.environ if env is None else env
    spec = env.get("ZEEBE_CHAOS_DISK")
    if not spec:
        return None
    try:
        plan = parse_spec(spec)
    except ValueError as exc:
        logger.error("ignoring malformed ZEEBE_CHAOS_DISK %r: %s", spec, exc)
        return None
    controller = DiskChaosController(plan, member_id=member_id, root=data_dir)
    if data_dir:
        controller.counts_file = os.path.join(
            data_dir, f"disk-chaos-counts-{os.getpid()}.json")
        controller.ledger_file = os.path.join(
            data_dir, f"disk-bitrot-{os.getpid()}.jsonl")
    controller.disarm_file = env.get("ZEEBE_CHAOS_DISK_DISARMFILE") or None
    storage_io.install_controller(controller)
    logger.warning("disk chaos ACTIVE for %s: %s", member_id, spec)
    return controller
