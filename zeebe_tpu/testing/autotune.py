"""Autotune A/B gate (ISSUE 12): adaptive broker vs a fixed-knob panel.

The acceptance harness for the closed-loop control plane, built on the PR
11 open-loop machinery: the SAME seeded bursty Poisson arrival schedule
(calm → burst → calm, dispatched by concurrent client streams against the
real supervised multi-process TCP cluster, latency measured from the
SCHEDULED arrival) is offered to every arm at equal load:

- ``adaptive``           — ``ZEEBE_CONTROL_ENABLED=1``: the controllers
  steer the ingress coalescing window and the raft group-commit pacing
  live (plus tiering/routing, idle in this workload);
- ``default``            — the plane off, every knob at its shipped
  default (per-append fsync, no coalescing);
- ``journal-aggressive`` — per-append fsync AND a tiny unflushed-byte
  bound (drain per append);
- ``journal-conservative`` — a fixed 50ms group-commit delay (every ack
  waits for a wide barrier, calm traffic included);
- ``coalesce-small`` / ``coalesce-large`` — fixed 1ms / 75ms ingress
  coalescing windows (the brackets around the plausible range; the
  adaptive cap sits at 25ms between them).

Gates (AUTOTUNE[_quick].json):

1. **p99**: the adaptive arm beats EVERY fixed arm on acked p99 latency;
2. **goodput**: adaptive acked/s within ``goodput_band`` of the best
   fixed arm;
3. **zero acked loss** in every arm, via the PR 9 offline journal readers
   (every acked request appears exactly once in the committed log);
4. **audit**: every adjustment is a ``control_adjust`` flight event (read
   back from the workers' dumps) and every actuated knob stayed provably
   inside its declared bounds (``minSeen``/``maxSeen`` vs ``min``/``max``
   from the single-write-path actuator snapshots).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import random
import sys
import threading
import time
from pathlib import Path
from typing import Any

from zeebe_tpu.testing.serving import (
    ServingOp,
    check_serving_history,
    execute_op,
    gate_cli_main,
    poisson_schedule,
)

logger = logging.getLogger("zeebe_tpu.testing.autotune")


@dataclasses.dataclass
class AutotuneConfig:
    seed: int = 0
    workers: int = 2
    partitions: int = 2
    replication: int = 2
    client_streams: int = 48
    #: offered arrival rates (total across partitions), requests/s
    calm_rate: float = 40.0
    burst_rate: float = 160.0
    phase_calm_s: float = 3.0
    phase_burst_s: float = 8.0
    phase_tail_s: float = 3.0
    #: rounds per arm, round-robin (the PR 7 interleave discipline): each
    #: arm's gated p99 is its BEST round — a background-load spike on the
    #: shared box pollutes one round, not the verdict
    rounds: int = 2
    request_timeout_s: float = 12.0
    #: adaptive goodput must stay within this band of the best fixed arm
    goodput_band: float = 0.05
    #: faster sensing + control convergence for the short quick drive
    #: (identical for every arm — the A/B compares knob POSTURES, not
    #: sampling cadences)
    metrics_sampling_ms: int = 100
    control_interval_ms: int = 100
    boot_timeout_s: float = 180.0
    kernel_backend: bool = False


FULL_CONFIG = AutotuneConfig(
    workers=3, partitions=3, replication=3, client_streams=128,
    calm_rate=80.0, burst_rate=320.0,
    phase_calm_s=10.0, phase_burst_s=30.0, phase_tail_s=10.0, rounds=3)


def fixed_panel() -> dict[str, dict[str, str]]:
    """The fixed-knob arms (every one runs with the control plane OFF)."""
    return {
        "default": {},
        "journal-aggressive": {
            "ZEEBE_BROKER_DATA_LOGFLUSHDELAYMS": "0",
            "ZEEBE_BROKER_DATA_LOGMAXUNFLUSHEDBYTES": str(64 * 1024),
        },
        "journal-conservative": {
            "ZEEBE_BROKER_DATA_LOGFLUSHDELAYMS": "50",
        },
        "coalesce-small": {
            "ZEEBE_BROKER_PROCESSING_COALESCEWINDOWMS": "1",
        },
        "coalesce-large": {
            "ZEEBE_BROKER_PROCESSING_COALESCEWINDOWMS": "75",
        },
    }


def build_schedule(cfg: AutotuneConfig) -> list[float]:
    """The bursty open-loop arrival schedule (seconds), IDENTICAL for
    every arm: calm -> burst -> calm, seeded non-homogeneous Poisson."""
    drive_s = cfg.phase_calm_s + cfg.phase_burst_s + cfg.phase_tail_s

    def rate(t: float) -> float:
        if t < cfg.phase_calm_s:
            return cfg.calm_rate
        if t < cfg.phase_calm_s + cfg.phase_burst_s:
            return cfg.burst_rate
        return cfg.calm_rate

    rng = random.Random(cfg.seed << 4 | 0xA)
    return poisson_schedule(rng, drive_s, rate,
                            max(cfg.calm_rate, cfg.burst_rate))


# ---------------------------------------------------------------------------
# offline control-audit evidence (pure over dump payloads — unit-testable)


#: the PLANE's own loops — the A/B evidence counts only these. The
#: admission shed ladder and snapshot scheduler also emit control_adjust,
#: but they run with the plane disabled too: counting them would flunk a
#: fixed arm whose ladder fired (false positive) and could satisfy the
#: adaptive arm's audit gate without the plane adjusting anything (false
#: negative).
PLANE_CONTROLLERS = frozenset({
    "ingress-coalescing", "journal-flush", "state-tiering",
    "kernel-routing",
})


def control_evidence(dumps: list[dict]) -> dict:
    """Aggregate the control audit trail from one arm's flight dumps:
    the PLANE controllers' control_adjust events (deduplicated across
    overlapping ring snapshots) and, from the NEWEST dump's ``control``
    context block, the per-actuator bounds verdict."""
    events: dict[tuple, dict] = {}
    newest_control: tuple[int, dict] | None = None
    for dump in dumps:
        for ring in dump.get("partitions", {}).values():
            for event in ring:
                if event.get("kind") != "control_adjust":
                    continue
                if event.get("controller") not in PLANE_CONTROLLERS:
                    continue
                key = (event.get("t"), event.get("controller"),
                       event.get("knob"), event.get("before"),
                       event.get("after"))
                events[key] = event
        control = dump.get("control")
        if control is not None:
            at = dump.get("dumpedAtMs", 0)
            if newest_control is None or at >= newest_control[0]:
                newest_control = (at, control)
    adjusts = sorted(events.values(), key=lambda e: e.get("t", 0))
    out: dict[str, Any] = {
        "controlAdjustEvents": len(adjusts),
        "byController": {},
        "knobsWithinBounds": None,
        "boundsViolations": [],
    }
    for event in adjusts:
        out["byController"].setdefault(event.get("controller", "?"), 0)
        out["byController"][event.get("controller", "?")] += 1
    if newest_control is not None:
        violations = []
        actuators = []
        for name, ctl in newest_control[1].get("controllers", {}).items():
            for act in ctl.get("actuators", []):
                actuators.append({**act, "controller": name})
                if not (act["min"] <= act["minSeen"]
                        and act["maxSeen"] <= act["max"]):
                    violations.append(
                        f"{name}/{act['knob']}: seen "
                        f"[{act['minSeen']}, {act['maxSeen']}] outside "
                        f"declared [{act['min']}, {act['max']}]")
        out["knobsWithinBounds"] = not violations
        out["boundsViolations"] = violations
        out["actuators"] = actuators
    return out


def evaluate_arms(arms: dict[str, dict], cfg: AutotuneConfig) -> list[str]:
    """The autotune gates over finished arm reports (pure)."""
    violations: list[str] = []
    for name, arm in arms.items():
        for v in arm.get("violations", []):
            violations.append(f"arm {name}: {v}")
    adaptive = arms.get("adaptive")
    fixed = {k: v for k, v in arms.items() if k != "adaptive"}
    if adaptive is None or not fixed:
        return violations + ["autotune needs an adaptive arm and a panel"]
    a_p99 = adaptive["ackedLatency"].get("p99Ms")
    if a_p99 is None:
        return violations + ["adaptive arm acked nothing"]
    for name, arm in fixed.items():
        f_p99 = arm["ackedLatency"].get("p99Ms")
        if f_p99 is None:
            violations.append(f"fixed arm {name} acked nothing")
        elif a_p99 >= f_p99:
            violations.append(
                f"adaptive p99 {a_p99}ms does not beat fixed arm "
                f"{name} ({f_p99}ms)")
    best_goodput = max(arm["goodputPerSec"] for arm in fixed.values())
    if adaptive["goodputPerSec"] < (1.0 - cfg.goodput_band) * best_goodput:
        violations.append(
            f"adaptive goodput {adaptive['goodputPerSec']}/s under "
            f"{1.0 - cfg.goodput_band:.0%} of the best fixed arm "
            f"({best_goodput}/s)")
    control = adaptive.get("control", {})
    if not control.get("controlAdjustEvents"):
        violations.append(
            "adaptive arm recorded no control_adjust flight events — "
            "either the plane never adjusted or the audit trail is broken")
    if control.get("knobsWithinBounds") is not True:
        violations.append(
            "adaptive arm lacks the knob-bounds proof: "
            + ("; ".join(control.get("boundsViolations", []))
               or "no control snapshot in any flight dump"))
    for name, arm in fixed.items():
        if arm.get("control", {}).get("controlAdjustEvents", 0):
            violations.append(
                f"fixed arm {name} recorded control_adjust events with the "
                f"plane disabled (the A/B is not an A/B)")
    return violations


# ---------------------------------------------------------------------------
# one arm = one supervised multi-process cluster + the shared schedule


def run_arm(name: str, env_overlay: dict[str, str], cfg: AutotuneConfig,
            directory: Path, schedule: list[float]) -> dict:
    from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
    from zeebe_tpu.multiproc.runtime import MultiProcClusterRuntime
    from zeebe_tpu.multiproc.supervisor import (
        WorkerSpec,
        WorkerSupervisor,
        worker_cmd,
    )
    from zeebe_tpu.protocol import ValueType
    from zeebe_tpu.protocol.intent import (
        DeploymentIntent,
        ProcessInstanceCreationIntent,
    )
    from zeebe_tpu.protocol.record import command
    from zeebe_tpu.standalone import _free_ports
    from zeebe_tpu.testing.consistency import collect_logs
    from zeebe_tpu.testing.evidence import percentile

    directory = Path(directory)
    started = time.monotonic()
    violations: list[str] = []
    worker_names = [f"worker-{i}" for i in range(cfg.workers)]
    ports = _free_ports(cfg.workers + 1)
    contacts = {n: ("127.0.0.1", p) for n, p in zip(worker_names, ports)}
    contacts["gateway-0"] = ("127.0.0.1", ports[-1])
    contact_str = ",".join(
        f"{m}={h}:{p}" for m, (h, p) in sorted(contacts.items()))

    repo = str(Path(__file__).resolve().parent.parent.parent)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    env["JAX_PLATFORMS"] = "cpu"
    if not cfg.kernel_backend:
        env["ZEEBE_BROKER_EXPERIMENTAL_KERNELBACKEND"] = "false"
    # equal footing: the plane is explicitly OFF unless the arm turns it on
    env["ZEEBE_CONTROL_ENABLED"] = "0"
    env["ZEEBE_CONTROL_INTERVALMS"] = str(cfg.control_interval_ms)
    env["ZEEBE_BROKER_METRICS_SAMPLINGINTERVALMS"] = str(
        cfg.metrics_sampling_ms)
    env.update(env_overlay)

    specs = [WorkerSpec(
        node_id=wname,
        cmd=worker_cmd(wname, f"127.0.0.1:{contacts[wname][1]}", contact_str,
                       "gateway-0", cfg.partitions, cfg.replication,
                       data_dir=str(directory / wname)),
        data_dir=str(directory / wname)) for wname in worker_names]
    supervisor = WorkerSupervisor(specs, env=env, restart_backoff_s=0.2)
    runtime = MultiProcClusterRuntime(
        "gateway-0",
        {m: a for m, a in contacts.items() if m != "gateway-0"},
        partition_count=cfg.partitions, replication_factor=cfg.replication,
        bind=contacts["gateway-0"], supervisor=supervisor)

    history: list[ServingOp] = []
    history_lock = threading.Lock()
    op_seq = [0]
    drive_t0 = [0.0]

    def drive_ms() -> float:
        return (time.monotonic() - drive_t0[0]) * 1000.0

    def new_op(kind: str, partition: int, scheduled_ms: float) -> ServingOp:
        with history_lock:
            op_seq[0] += 1
            op = ServingOp(index=op_seq[0], tenant="t-auto", kind=kind,
                           partition=partition, scheduled_ms=scheduled_ms)
            history.append(op)
        return op

    def execute(op: ServingOp, record) -> ServingOp:
        return execute_op(runtime, op, record, cfg.request_timeout_s,
                          drive_ms)

    def create_cmd():
        return command(ValueType.PROCESS_INSTANCE_CREATION,
                       ProcessInstanceCreationIntent.CREATE,
                       {"bpmnProcessId": "auto", "version": -1,
                        "variables": {}, "tenantId": "t-auto"})

    arrivals: "queue.Queue[float | None]" = queue.Queue()
    stop_streams = threading.Event()

    def client_stream() -> None:
        while not stop_streams.is_set():
            try:
                item = arrivals.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None:
                return
            op = new_op("create", runtime.partition_for_new_instance(),
                        item * 1000.0)
            execute(op, create_cmd())

    def scheduler() -> None:
        for at_s in schedule:
            delay = drive_t0[0] + at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if stop_streams.is_set():
                return
            arrivals.put(at_s)

    final_status: dict = {}
    try:
        runtime.start()
        boot_deadline = time.monotonic() + cfg.boot_timeout_s
        while True:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                if time.monotonic() >= boot_deadline:
                    raise
        # warm: deploy + per-partition create probes (deployment
        # distribution must settle BEFORE the clock starts — warm cost is
        # identical across arms and not part of the A/B)
        drive_t0[0] = time.monotonic()
        model = (Bpmn.create_executable_process("auto")
                 .start_event("s").end_event("e").done())
        deploy = execute(
            new_op("deploy", 1, -1.0),
            command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
                "resources": [{"resourceName": "auto.bpmn",
                               "resource": to_bpmn_xml(model)}],
                "tenantId": "t-auto"}))
        if deploy.outcome != "ack":
            raise RuntimeError(f"arm {name}: deploy failed: {deploy.row()}")
        for pid in range(1, cfg.partitions + 1):
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                probe = execute(new_op("create", pid, -1.0), create_cmd())
                if probe.outcome == "ack":
                    break
                time.sleep(0.25)
            else:
                raise RuntimeError(
                    f"arm {name}: partition {pid} never served a create; "
                    f"last probe: {probe.row()}")

        drive_t0[0] = time.monotonic()
        streams = [threading.Thread(target=client_stream, daemon=True,
                                    name=f"auto-stream-{i}")
                   for i in range(cfg.client_streams)]
        for t in streams:
            t.start()
        sched = threading.Thread(target=scheduler, daemon=True,
                                 name="autotune-scheduler")
        sched.start()
        drive_end = cfg.phase_calm_s + cfg.phase_burst_s + cfg.phase_tail_s
        remaining = drive_t0[0] + drive_end - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        sched.join(timeout=10)
        drain_deadline = time.monotonic() + cfg.request_timeout_s + 10
        while time.monotonic() < drain_deadline and not arrivals.empty():
            time.sleep(0.2)
        for _ in streams:
            arrivals.put(None)
        join_by = time.monotonic() + cfg.request_timeout_s + 10
        for t in streams:
            t.join(timeout=max(join_by - time.monotonic(), 0.1))
        stop_streams.set()
        final_status = {w: dict(s)
                        for w, s in runtime._worker_status.items()}
    finally:
        stop_streams.set()
        try:
            runtime.stop()
        except Exception:  # noqa: BLE001 — teardown must reach evidence
            logger.exception("arm %s: runtime stop failed", name)

    # ---- offline evidence ---------------------------------------------------
    logs, log_violations = collect_logs(directory, worker_names,
                                        cfg.partitions)
    violations += log_violations
    violations += check_serving_history(history, logs)

    drive_ops = [op for op in history if op.scheduled_ms >= 0]
    acked = sorted(op.latency_ms for op in drive_ops
                   if op.outcome == "ack")
    outcomes: dict[str, int] = {}
    for op in drive_ops:
        outcomes[op.outcome] = outcomes.get(op.outcome, 0) + 1
    pending = outcomes.get("pending", 0)
    if pending:
        violations.append(f"{pending} op(s) never completed (silent drop)")
    drive_s = cfg.phase_calm_s + cfg.phase_burst_s + cfg.phase_tail_s

    dumps = []
    for path in sorted(directory.glob("*/flight-*.json")):
        try:
            dumps.append(json.loads(path.read_text()))
        except (OSError, ValueError):
            violations.append(f"unreadable flight dump {path}")
    report = {
        "arm": name,
        "envOverlay": env_overlay,
        "offered": len(drive_ops),
        "outcomes": outcomes,
        "ackedLatency": ({
            "count": len(acked),
            "p50Ms": round(percentile(acked, 0.50), 1),
            "p95Ms": round(percentile(acked, 0.95), 1),
            "p99Ms": round(percentile(acked, 0.99), 1),
            "maxMs": round(acked[-1], 1),
        } if acked else {"count": 0}),
        "goodputPerSec": round(len(acked) / drive_s, 2),
        "control": control_evidence(dumps),
        "flightDumps": [str(p) for p in
                        sorted(directory.glob("*/flight-*.json"))],
        "workerStatus": {
            w: {"control": s.get("control"), "admission": bool(s.get(
                "admission", {}).get("shedLevel", 0))}
            for w, s in final_status.items()},
        "violations": violations,
        "wallSeconds": round(time.monotonic() - started, 2),
    }
    return report


def merge_rounds(rounds: list[dict]) -> dict:
    """One arm's gated report from its rounds: the BEST round's latency
    (paired same-box discipline — a box-noise spike pollutes one round,
    not the verdict), the best round's goodput, every round's violations
    and audit evidence. Pure — unit-tested."""
    best = min(rounds,
               key=lambda r: r["ackedLatency"].get("p99Ms", float("inf")))
    control = {
        "controlAdjustEvents": sum(
            r["control"].get("controlAdjustEvents", 0) for r in rounds),
        "byController": {},
        # the bounds proof must hold in EVERY round, not just the best one
        "knobsWithinBounds": all(
            r["control"].get("knobsWithinBounds") in (True, None)
            for r in rounds) and any(
            r["control"].get("knobsWithinBounds") is True for r in rounds),
        "boundsViolations": [v for r in rounds
                             for v in r["control"].get(
                                 "boundsViolations", [])],
    }
    for r in rounds:
        for ctl, count in r["control"].get("byController", {}).items():
            control["byController"][ctl] = (
                control["byController"].get(ctl, 0) + count)
    outcomes: dict[str, int] = {}
    for r in rounds:
        for outcome, count in r["outcomes"].items():
            outcomes[outcome] = outcomes.get(outcome, 0) + count
    return {
        "arm": best["arm"],
        "envOverlay": best["envOverlay"],
        "rounds": len(rounds),
        "offered": sum(r["offered"] for r in rounds),
        "outcomes": outcomes,
        "ackedLatency": best["ackedLatency"],
        "p99MsByRound": [r["ackedLatency"].get("p99Ms") for r in rounds],
        "goodputPerSec": max(r["goodputPerSec"] for r in rounds),
        "control": control,
        "violations": [v for r in rounds for v in r["violations"]],
        "wallSeconds": round(sum(r["wallSeconds"] for r in rounds), 2),
        "roundReports": rounds,
    }


def run_autotune(cfg: AutotuneConfig, directory: str | Path) -> dict:
    """Every arm, round-robin over ``cfg.rounds`` rounds, always the SAME
    seeded schedule at equal offered load; then the gates."""
    directory = Path(directory)
    started = time.monotonic()
    schedule = build_schedule(cfg)
    panel = {"adaptive": {"ZEEBE_CONTROL_ENABLED": "1"}, **fixed_panel()}
    rounds: dict[str, list[dict]] = {name: [] for name in panel}
    for round_idx in range(max(cfg.rounds, 1)):
        for name, overlay in panel.items():
            arm_dir = directory / f"{name}-r{round_idx}"
            arm_dir.mkdir(parents=True, exist_ok=True)
            logger.warning(
                "autotune arm %s round %d starting (%d offered arrivals)",
                name, round_idx, len(schedule))
            rounds[name].append(
                run_arm(name, overlay, cfg, arm_dir, schedule))
    arms = {name: merge_rounds(reports)
            for name, reports in rounds.items()}
    violations = evaluate_arms(arms, cfg)
    return {
        "seed": cfg.seed,
        "workers": cfg.workers,
        "partitions": cfg.partitions,
        "replication": cfg.replication,
        "clientStreams": cfg.client_streams,
        "offeredArrivals": len(schedule),
        "phases": {"calmSeconds": cfg.phase_calm_s,
                   "burstSeconds": cfg.phase_burst_s,
                   "tailSeconds": cfg.phase_tail_s,
                   "calmRatePerSec": cfg.calm_rate,
                   "burstRatePerSec": cfg.burst_rate},
        "arms": arms,
        "summary": {
            name: {"p99Ms": arm["ackedLatency"].get("p99Ms"),
                   "goodputPerSec": arm["goodputPerSec"],
                   "controlAdjusts": arm["control"].get(
                       "controlAdjustEvents", 0)}
            for name, arm in arms.items()},
        "violations": violations,
        "wallSeconds": round(time.monotonic() - started, 2),
    }


def main(argv: list[str] | None = None) -> int:  # pragma: no cover — manual
    return gate_cli_main("zeebe-tpu-autotune", AutotuneConfig(), FULL_CONFIG,
                         run_autotune, argv)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
