"""The device-chaos gate: accelerator + kill chaos over the kernel path
(ISSUE 15).

The torture gate (PR 14) proved delivery invariants when the disk lies;
this gate makes the thing the paper's kernel exists for — the device —
the liar, with the kernel backend LIVE in every worker. Real supervised
worker processes serve the PR 9 Jepsen-shaped workload while
``ZEEBE_CHAOS_DEVICE`` injects compile failures, dispatch exceptions,
stalls (converted to typed wedges by the dispatch watchdog), partial-chunk
failures, and seeded bit-flips into fetched kernel results, and a
``kill_worker`` rides along. Shadow verification runs at rate 1.0 — the
exhaustive posture for the gate (production samples; the honest caveat in
docs/device-faults.md).

Two phases: a **survival window** (chaos armed — containment + detection +
the ladder's descent to QUARANTINED) and a **recovery window** (the disarm
file ends the chaos; canary dispatches must re-prove the device back to
HEALTHY while traffic keeps flowing).

Gates:

- **delivery invariants hold** — the PR 9 checker (no acked loss in log
  AND export stream, no duplicate application, rejections terminal,
  positions monotone) plus replica CRC equality: a corrupted device
  result that reached the log would diverge replicas exactly here;
- **every configured device-fault class observed** (per-life counts
  snapshots) — configured-but-never-applied chaos is a violation;
- **every injected result corruption accounted**: each ledger ``inject``
  line needs a ``caught`` line (shadow mismatch or containment) from the
  same process life — wrong bytes provably never reached the commit path.
  An inject in the final moments of a life that verifiably DIED (pid
  absent at teardown) is waived — the carrying group died uncommitted
  with the process; lives that survived to disarm get no waiver;
- **≥ 1 full health-ladder cycle** — one worker life must walk
  HEALTHY→SUSPECT→QUARANTINED and return QUARANTINED→HEALTHY through
  verified canaries (evidence: the per-life device-health JSONL).

``bench.py --device-chaos [--quick]`` runs this and writes
DEVICE_CHAOS[_quick].json; the CI ``device-chaos-smoke`` job gates on it.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import sys
import threading
import time
from pathlib import Path
from typing import Any

from zeebe_tpu.testing.chaos_common import read_jsonl_ledgers, sum_counts_files
from zeebe_tpu.testing.chaos_device import DeviceFaultPlan, format_spec
from zeebe_tpu.testing.consistency import (
    ClientOp,
    _await_exports,
    check_consistency,
    collect_exports,
    collect_logs,
    submit_client_op,
)

logger = logging.getLogger("zeebe_tpu.testing.device_chaos")


@dataclasses.dataclass
class DeviceChaosConfig:
    seed: int = 0
    workers: int = 3
    partitions: int = 2
    replication: int = 3
    drive_seconds: float = 30.0
    #: fraction of the drive with chaos armed; the rest is the recovery
    #: window (canary ladder re-proving under live traffic)
    chaos_fraction: float = 0.6
    think_ms: float = 10.0
    request_timeout_s: float = 20.0
    kills: int = 1
    # device chaos rates — sized so every class fires with margin across
    # the pre-quarantine dispatches PLUS the ~4/s canary stream that keeps
    # rolling the dice while QUARANTINED (the gate REQUIRES a nonzero
    # observed count per configured class)
    compile_fail_p: float = 0.10
    dispatch_fail_p: float = 0.10
    stall_p: float = 0.10
    stall_ms: int = 900
    chunk_fail_p: float = 0.12
    corrupt_p: float = 0.18
    flips: int = 3
    #: watchdog well under stall_ms: every stall becomes a typed wedge and
    #: the pump pays the deadline, not the stall
    dispatch_timeout_ms: int = 450
    #: high enough that the pre-quarantine window carries every fault class
    #: at full dispatch rate with margin (after quarantine only the canary
    #: stream keeps rolling the dice)
    quarantine_faults: int = 8
    canary_interval_ms: int = 150
    canary_successes: int = 2
    reject_every: int = 25


#: a kill that lands mid-group can orphan at most this trailing slice of a
#: life's corruption-ledger activity without failing the accounting
_DEATH_WAIVER_MS = 2_000.0


# ---------------------------------------------------------------------------
# offline verification (pure — unit-testable without a cluster)


def check_fault_classes(plan: DeviceFaultPlan,
                        counts: dict[str, int]) -> list[str]:
    """Every CONFIGURED device-fault class must have a nonzero observed
    count aggregated across every worker life."""
    violations = []
    for fault_class in plan.configured_classes():
        if not counts.get(fault_class):
            violations.append(
                f"device-fault class `{fault_class}` configured but never "
                f"observed (0 applied across every worker life) — the "
                f"chaos plane is not reaching the dispatch seam")
    return violations


def check_corruption_accounting(
        entries: list[dict],
        dead_pids: set | None = None) -> tuple[list[str], dict]:
    """Join ``inject`` lines against ``caught`` lines per process life.
    An inject with no catch means corrupt bytes were decoded and allowed
    toward the commit path — a violation, unless the life actually DIED
    (``dead_pids``: pids not alive at teardown — chaos-killed or crashed)
    and the inject sits in the final moments of its ledger (SIGKILL
    mid-group: the carrying group's transaction died with the process and
    replay excludes it). A life that survived to disarm gets no waiver —
    it had every chance to report the catch, and waiving its tail would
    green-light a detection bug in the last seconds of the armed window."""
    violations: list[str] = []
    stats = {"injected": 0, "caughtShadow": 0, "caughtContained": 0,
             "waivedByDeath": 0}
    dead_pids = dead_pids or set()
    by_life: dict[tuple, list[dict]] = {}
    for entry in entries:
        by_life.setdefault((entry.get("member"), entry.get("pid")),
                           []).append(entry)
    for (member, pid), rows in by_life.items():
        caught_by_seq: dict[int, str] = {}
        last_ms = max((r.get("atMs", 0.0) for r in rows), default=0.0)
        for row in rows:
            if row.get("kind") == "caught":
                caught_by_seq[row["seq"]] = row.get("how", "?")
        for row in rows:
            if row.get("kind") != "inject":
                continue
            stats["injected"] += 1
            how = caught_by_seq.get(row["seq"])
            if how == "shadow":
                stats["caughtShadow"] += 1
            elif how is not None:
                stats["caughtContained"] += 1
            elif (pid in dead_pids
                  and last_ms - row.get("atMs", 0.0) <= _DEATH_WAIVER_MS):
                # the life died and its ledger ends right here: killed
                # mid-group, the carrying transaction died with it
                stats["waivedByDeath"] += 1
            else:
                violations.append(
                    f"injected result corruption seq {row['seq']} on "
                    f"{member} (pid {pid}) was never caught — corrupt "
                    f"device output reached the commit path unverified")
    return violations, stats


def check_health_cycle(transitions: list[dict]) -> tuple[list[str], dict]:
    """≥1 process life must complete the full ladder cycle:
    HEALTHY→SUSPECT, →QUARANTINED, and QUARANTINED→HEALTHY via canaries."""
    by_pid: dict[Any, list[dict]] = {}
    for t in transitions:
        by_pid.setdefault(t.get("pid"), []).append(t)
    cycles = 0
    suspects = quarantines = recoveries = 0
    for pid, rows in by_pid.items():
        rows.sort(key=lambda r: r.get("atMs", 0.0))
        saw_suspect = saw_quarantine = False
        completed = False
        for row in rows:
            if row.get("to") == "SUSPECT":
                saw_suspect = True
                suspects += 1
            elif row.get("to") == "QUARANTINED":
                quarantines += 1
                if saw_suspect:
                    saw_quarantine = True
            elif (row.get("to") == "HEALTHY"
                  and row.get("from") == "QUARANTINED"):
                recoveries += 1
                if saw_quarantine and "canary" in row.get("reason", ""):
                    completed = True
        if completed:
            cycles += 1
    stats = {"lives": len(by_pid), "suspectTransitions": suspects,
             "quarantineTransitions": quarantines,
             "quarantineRecoveries": recoveries, "fullCycles": cycles}
    violations = []
    if cycles < 1:
        violations.append(
            "no worker life completed the full device health cycle "
            "SUSPECT→QUARANTINED→canary→HEALTHY — the recovery ladder is "
            f"unproven ({stats})")
    return violations, stats


# ---------------------------------------------------------------------------
# the harness


def run_device_chaos(cfg: DeviceChaosConfig, directory: str | Path) -> dict:
    """Run the full device-chaos gate; returns the report dict."""
    from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
    from zeebe_tpu.multiproc.runtime import MultiProcClusterRuntime
    from zeebe_tpu.multiproc.supervisor import (
        WorkerSpec,
        WorkerSupervisor,
        worker_cmd,
    )
    from zeebe_tpu.protocol import ValueType
    from zeebe_tpu.protocol.intent import (
        DeploymentIntent,
        ProcessInstanceCreationIntent,
    )
    from zeebe_tpu.protocol.record import command
    from zeebe_tpu.standalone import _free_ports

    directory = Path(directory)
    export_dir = directory / "exports"
    export_dir.mkdir(parents=True, exist_ok=True)
    rng = random.Random(cfg.seed)
    started = time.monotonic()
    epoch_ms = time.time() * 1000.0

    worker_names = [f"worker-{i}" for i in range(cfg.workers)]
    ports = _free_ports(cfg.workers + 1)
    contacts = {n: ("127.0.0.1", p) for n, p in zip(worker_names, ports)}
    contacts["gateway-0"] = ("127.0.0.1", ports[-1])
    contact_str = ",".join(
        f"{m}={h}:{p}" for m, (h, p) in sorted(contacts.items()))

    plan = DeviceFaultPlan(
        seed=cfg.seed, compile_fail_p=cfg.compile_fail_p,
        dispatch_fail_p=cfg.dispatch_fail_p, stall_p=cfg.stall_p,
        stall_ms=cfg.stall_ms, chunk_fail_p=cfg.chunk_fail_p,
        corrupt_p=cfg.corrupt_p, flips=cfg.flips)
    disarm_file = directory / "device-chaos-disarm"

    repo = str(Path(__file__).resolve().parent.parent.parent)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    env["JAX_PLATFORMS"] = "cpu"
    # the whole point: the kernel backend is LIVE in every worker — on the
    # DIRECT dispatch path (the seam under test); mesh dispatch has its own
    # killable probe (PR 7) and would otherwise auto-activate under
    # bench.py's inherited 8-virtual-device XLA_FLAGS
    env["ZEEBE_BROKER_EXPERIMENTAL_KERNELBACKEND"] = "true"
    env["ZEEBE_BROKER_EXPERIMENTAL_KERNELMESHSHARDS"] = "0"
    env["ZEEBE_CHAOS_DEVICE"] = format_spec(plan)
    env["ZEEBE_CHAOS_DEVICE_DISARMFILE"] = str(disarm_file)
    # exhaustive detection for the gate: EVERY group shadow-verified, so
    # every injected corruption must be caught before commit
    env["ZEEBE_BROKER_DEVICE_SHADOWSAMPLERATE"] = "1.0"
    env["ZEEBE_BROKER_DEVICE_DISPATCHTIMEOUTMS"] = str(
        cfg.dispatch_timeout_ms)
    env["ZEEBE_BROKER_DEVICE_QUARANTINEFAULTS"] = str(cfg.quarantine_faults)
    env["ZEEBE_BROKER_DEVICE_FAULTWINDOWMS"] = "600000"
    # SUSPECT must escalate (not quietly clear) during the survival window
    env["ZEEBE_BROKER_DEVICE_SUSPECTCLEARMS"] = "600000"
    env["ZEEBE_BROKER_DEVICE_CANARYINTERVALMS"] = str(cfg.canary_interval_ms)
    env["ZEEBE_BROKER_DEVICE_CANARYSUCCESSES"] = str(cfg.canary_successes)
    env["ZEEBE_BROKER_EXPORTERS_DEVCHAOS_CLASSNAME"] = \
        "zeebe_tpu.testing.consistency.JsonlExporter"
    env["ZEEBE_BROKER_EXPORTERS_DEVCHAOS_ARGS_DIR"] = str(export_dir)

    specs = [WorkerSpec(
        node_id=name,
        cmd=worker_cmd(name, f"127.0.0.1:{contacts[name][1]}", contact_str,
                       "gateway-0", cfg.partitions, cfg.replication,
                       data_dir=str(directory / name)),
        data_dir=str(directory / name)) for name in worker_names]
    supervisor = WorkerSupervisor(specs, env=env, restart_backoff_s=0.2)
    runtime = MultiProcClusterRuntime(
        "gateway-0",
        {m: a for m, a in contacts.items() if m != "gateway-0"},
        partition_count=cfg.partitions, replication_factor=cfg.replication,
        bind=contacts["gateway-0"], supervisor=supervisor)

    history: list[ClientOp] = []
    history_lock = threading.Lock()
    op_seq = [0]
    events: list[dict] = []
    report: dict[str, Any] = {"seed": cfg.seed}
    surviving_pids: set = set()

    def clock_ms() -> float:
        return time.time() * 1000.0 - epoch_ms

    def submit_op(partition: int, kind: str, record) -> ClientOp:
        return submit_client_op(
            runtime, partition, kind, record, history=history,
            history_lock=history_lock, op_seq=op_seq, clock_ms=clock_ms,
            timeout_s=cfg.request_timeout_s)

    model = (Bpmn.create_executable_process("devchaos")
             .start_event("s").end_event("e").done())
    deploy = command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
        "resources": [{"resourceName": "devchaos.bpmn",
                       "resource": to_bpmn_xml(model)}]})

    def create_cmd(process_id: str = "devchaos"):
        return command(ValueType.PROCESS_INSTANCE_CREATION,
                       ProcessInstanceCreationIntent.CREATE,
                       {"bpmnProcessId": process_id, "version": -1,
                        "variables": {}})

    stop_driving = threading.Event()

    def drive(partition: int) -> None:
        n = 0
        while not stop_driving.is_set():
            n += 1
            if cfg.reject_every and n % cfg.reject_every == 0:
                submit_op(partition, "create-missing",
                          create_cmd("no-such-process"))
            else:
                submit_op(partition, "create", create_cmd())
            time.sleep(cfg.think_ms / 1000.0)

    try:
        runtime.start()
        boot_deadline = time.monotonic() + 180.0
        while True:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                if time.monotonic() >= boot_deadline:
                    raise
        deploy_op = submit_op(1, "deploy", deploy)
        if deploy_op.outcome != "ack":
            raise RuntimeError(f"deploy failed: {deploy_op.row()}")
        for pid in range(1, cfg.partitions + 1):
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if submit_op(pid, "create", create_cmd()).outcome == "ack":
                    break
                time.sleep(0.25)
            else:
                raise RuntimeError(f"partition {pid} never served a create")

        drive_started = time.monotonic()
        chaos_window = cfg.chaos_fraction * cfg.drive_seconds
        drivers = [threading.Thread(target=drive, args=(pid,), daemon=True,
                                    name=f"driver-{pid}")
                   for pid in range(1, cfg.partitions + 1)]
        for t in drivers:
            t.start()
        # kills land EARLY in the survival window so post-kill leader lives
        # span quarantine AND recovery (the full-cycle evidence)
        for _ in range(cfg.kills):
            at = rng.uniform(0.1, 0.35) * chaos_window
            delay = drive_started + at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            target = worker_names[rng.randrange(len(worker_names))]
            logger.warning("device chaos: kill %s at t=%.1fs", target, at)
            events.append({"atMs": clock_ms(), "action": "kill",
                           "target": target})
            supervisor.kill_worker(target)
        remaining = drive_started + chaos_window - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        # recovery window: device honest again; canaries re-prove it while
        # the drivers keep the kernel path under load
        disarm_file.write_text("disarm\n", encoding="utf-8")
        events.append({"atMs": clock_ms(), "action": "disarm"})
        remaining = drive_started + cfg.drive_seconds - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        stop_driving.set()
        for t in drivers:
            t.join(timeout=cfg.request_timeout_s + 10)

        quiesce_deadline = time.monotonic() + 90.0
        while time.monotonic() < quiesce_deadline:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                continue
        _await_exports(export_dir, history, deadline_s=60.0)
        report["gatewayFlight"] = runtime.flight.snapshot()
        report["workerRestarts"] = dict(supervisor.restarts)
        # lives alive at teardown: the death waiver in the corruption
        # accounting applies ONLY to pids absent from this set
        surviving_pids.update(
            p for n in worker_names
            if (p := supervisor.pid_of(n)) is not None)
    finally:
        try:
            runtime.stop()
        except Exception:  # noqa: BLE001 — teardown must reach evidence
            logger.exception("runtime stop failed")

    # ---- offline evidence + checks ----------------------------------------
    logs, violations = collect_logs(directory, worker_names, cfg.partitions)
    exports, export_violations, re_exports = collect_exports(export_dir)
    violations += export_violations
    violations += check_consistency(history, logs, exports)

    device_counts = sum_counts_files(
        sorted(directory.glob("*/device-chaos-counts-*.json")))
    corrupt_entries = read_jsonl_ledgers(
        sorted(directory.glob("*/device-corrupt-*.jsonl")))
    # the ledger is flushed per line; the counts snapshot is throttled and
    # a SIGKILL can lose its tail — the ledger is authoritative for corrupt
    injected = sum(1 for e in corrupt_entries if e.get("kind") == "inject")
    device_counts["corrupt"] = max(device_counts.get("corrupt", 0), injected)
    violations += check_fault_classes(plan, device_counts)
    dead_pids = {e.get("pid") for e in corrupt_entries} - surviving_pids
    corruption_violations, corruption_stats = check_corruption_accounting(
        corrupt_entries, dead_pids=dead_pids)
    violations += corruption_violations
    if injected and not corruption_stats["caughtShadow"]:
        violations.append(
            "result corruptions were injected but not one was caught by "
            "shadow verification — the detection layer is not engaging")

    health_transitions = read_jsonl_ledgers(
        sorted(directory.glob("*/device-health-*.jsonl")))
    cycle_violations, cycle_stats = check_health_cycle(health_transitions)
    violations += cycle_violations

    outcomes: dict[str, int] = {}
    for op in history:
        outcomes[op.outcome] = outcomes.get(op.outcome, 0) + 1
    report.update({
        "workers": cfg.workers,
        "partitions": cfg.partitions,
        "replication": cfg.replication,
        "requests": len(history),
        "outcomes": outcomes,
        "ackedCommands": outcomes.get("ack", 0),
        "kills": len([e for e in events if e["action"] == "kill"]),
        "events": events,
        "deviceChaosSpec": format_spec(plan),
        "deviceFaultsObserved": device_counts,
        "corruptionAccounting": corruption_stats,
        "healthCycle": cycle_stats,
        "healthTransitions": health_transitions[:64],
        "reExportedRecords": re_exports,
        "logRecords": {str(p): len(r) for p, r in logs.items()},
        "exportedPositions": {str(p): len(v) for p, v in exports.items()},
        "violations": violations,
        "wallSeconds": round(time.monotonic() - started, 2),
    })
    return report


def main(argv: list[str] | None = None) -> int:  # pragma: no cover — manual
    from zeebe_tpu.testing.serving import gate_cli_main

    return gate_cli_main(
        "zeebe-tpu-device-chaos", DeviceChaosConfig(),
        DeviceChaosConfig(drive_seconds=90.0, kills=3), run_device_chaos,
        argv)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
