"""Device-layer chaos: seeded accelerator fault injection (ISSUE 15).

``chaos_tcp`` made the network lie, ``chaos_disk`` the disk; this module
makes the *accelerator* lie. A seeded :class:`DeviceFaultPlan` is applied
by a :class:`DeviceChaosController` installed into the kernel backend's
ONE dispatch seam (``KernelBackend.begin_group``/``finish_group`` —
concretely the first-chunk dispatch and every device fetch), so every
fault class lands exactly where real hardware would produce it:

- **compile_fail** — the first dispatch of a group raises (XLA
  compile/lowering failure, driver OOM at program build);
- **dispatch_fail** — a dispatch raises after compile (runtime launch
  failure, a dying device rejecting work);
- **stall** — a device fetch blocks ``stall_ms`` before returning (the
  wedged-tunnel / dying-HBM latency tail — "Gray Failure"'s
  degraded-not-dead shape; trips the backend's dispatch watchdog);
- **chunk_fail** — a fetch raises mid-group after earlier chunks already
  landed (partial-group device failure);
- **corrupt** — seeded bit-flips in the fetched int32 result rows BEFORE
  decode (the "Cores that don't count" silent-data-corruption shape; the
  packed event tensor is integer, so flips — not float NaNs — are the
  faithful corruption model). Every corruption is recorded in a JSONL
  LEDGER, and the backend reports back each one it caught (shadow
  mismatch or containment) — an injected corruption with no ``caught``
  line is a device-chaos-gate violation: wrong bytes reached the commit
  path.

Per-member RNG streams derive from ``seed ^ crc32(member id)`` and the
evidence discipline matches the other planes (shared home:
``testing/chaos_common.py``): per-life applied-fault counts snapshots, a
disarm file the harness flips to end the survival window, and
configured-but-never-applied classes failing the gate.

Environment wiring (the worker process entry):

- ``ZEEBE_CHAOS_DEVICE`` — the spec, e.g.
  ``seed=7,compile_fail=0.02,dispatch_fail=0.02,stall=0.02,stall_ms=900,
  chunk_fail=0.02,corrupt=0.08,flips=3``
- ``ZEEBE_CHAOS_DEVICE_DISARMFILE`` — when this file appears the
  controller freezes (checked on tick): the harness's recovery phase
  needs the device honest so the canary ladder can re-prove it.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time

from zeebe_tpu.testing.chaos_common import (
    CountsSnapshot,
    JsonlLedger,
    member_rng,
    parse_spec_fields,
)

logger = logging.getLogger("zeebe_tpu.testing.chaos_device")

#: every fault class a plan can configure (the device-chaos gate asserts a
#: nonzero observed count for each CONFIGURED one)
FAULT_CLASSES = ("compile_fail", "dispatch_fail", "stall", "chunk_fail",
                 "corrupt")


class DeviceChaosError(RuntimeError):
    """A chaos-injected device failure; ``kind`` is the fault class. The
    kernel backend's containment layer must absorb it exactly like a real
    dispatch exception — typed fallback, never a poisoned pump."""

    def __init__(self, kind: str, message: str) -> None:
        super().__init__(message)
        self.kind = kind


@dataclasses.dataclass
class DeviceFaultPlan:
    """Seeded per-dispatch/per-fetch fault probabilities."""

    seed: int = 0
    compile_fail_p: float = 0.0
    dispatch_fail_p: float = 0.0
    stall_p: float = 0.0
    stall_ms: int = 900
    chunk_fail_p: float = 0.0
    corrupt_p: float = 0.0
    #: bit flips per corrupted fetch (spread over seeded row positions)
    flips: int = 3

    def configured_classes(self) -> list[str]:
        out = []
        if self.compile_fail_p > 0:
            out.append("compile_fail")
        if self.dispatch_fail_p > 0:
            out.append("dispatch_fail")
        if self.stall_p > 0:
            out.append("stall")
        if self.chunk_fail_p > 0:
            out.append("chunk_fail")
        if self.corrupt_p > 0:
            out.append("corrupt")
        return out


def format_spec(plan: DeviceFaultPlan) -> str:
    return (f"seed={plan.seed},compile_fail={plan.compile_fail_p},"
            f"dispatch_fail={plan.dispatch_fail_p},stall={plan.stall_p},"
            f"stall_ms={plan.stall_ms},chunk_fail={plan.chunk_fail_p},"
            f"corrupt={plan.corrupt_p},flips={plan.flips}")


def parse_spec(spec: str) -> DeviceFaultPlan:
    """Inverse of :func:`format_spec`."""
    plan = DeviceFaultPlan()
    for section in spec.split(";"):
        section = section.strip()
        if not section:
            continue
        parse_spec_fields(section, {
            "seed": lambda v: setattr(plan, "seed", int(v)),
            "compile_fail": lambda v: setattr(plan, "compile_fail_p",
                                              float(v)),
            "dispatch_fail": lambda v: setattr(plan, "dispatch_fail_p",
                                               float(v)),
            "stall": lambda v: setattr(plan, "stall_p", float(v)),
            "stall_ms": lambda v: setattr(plan, "stall_ms", int(v)),
            "chunk_fail": lambda v: setattr(plan, "chunk_fail_p", float(v)),
            "corrupt": lambda v: setattr(plan, "corrupt_p", float(v)),
            "flips": lambda v: setattr(plan, "flips", int(v)),
        })
    return plan


class DeviceChaosController:
    """The object the kernel backend consults at its dispatch seam.

    Thread-wise: ``dispatch_fault``/``fetch_fault``/``corrupt_rows`` run
    on whichever thread performs the device call (the pump thread, or the
    backend's watchdog fetch thread); ``tick`` (disarm + counts dumps)
    rides the worker's pump loop. The RNG is shared across partitions —
    chaos needs seeded coverage, not bit-level cross-thread
    reproducibility (the TCP plane's documented posture)."""

    def __init__(self, plan: DeviceFaultPlan, member_id: str = "") -> None:
        self.plan = plan
        self.member_id = member_id
        self.rng = member_rng(plan.seed, member_id)
        self.counts = {"dispatches": 0, "fetches": 0, "corrupt_caught": 0}
        for cls in FAULT_CLASSES:
            self.counts[cls] = 0
        self._counts_snap = CountsSnapshot(member_id)
        self._ledger = JsonlLedger()
        self._corrupt_seq = 0
        self.armed = True
        self.disarm_file: str | None = None

    @property
    def counts_file(self):
        return self._counts_snap.counts_file

    @counts_file.setter
    def counts_file(self, value) -> None:
        self._counts_snap.counts_file = value

    @property
    def ledger_file(self):
        return self._ledger.path

    @ledger_file.setter
    def ledger_file(self, value) -> None:
        self._ledger.path = value

    # -- dispatch-seam faults -----------------------------------------------

    def dispatch_fault(self) -> None:
        """Called once per group dispatch, BEFORE the first chunk runs: may
        raise a compile failure or a dispatch exception."""
        self.counts["dispatches"] += 1
        if not self.armed:
            return
        plan = self.plan
        r = self.rng.random()
        if r < plan.compile_fail_p:
            self.counts["compile_fail"] += 1
            raise DeviceChaosError(
                "compile_fail", "chaos: XLA compile failure at group dispatch")
        r -= plan.compile_fail_p
        if r < plan.dispatch_fail_p:
            self.counts["dispatch_fail"] += 1
            raise DeviceChaosError(
                "dispatch_fail", "chaos: device dispatch exception")

    def fetch_fault(self, chunk_index: int) -> None:
        """Called per device fetch (inside the backend's watchdog thread
        when one is armed): may stall (the watchdog's deadline converts the
        stall into a typed wedge) or raise a partial-chunk failure."""
        self.counts["fetches"] += 1
        if not self.armed:
            return
        plan = self.plan
        r = self.rng.random()
        if r < plan.stall_p:
            self.counts["stall"] += 1
            time.sleep(plan.stall_ms / 1000.0)
            return
        r -= plan.stall_p
        if r < plan.chunk_fail_p:
            self.counts["chunk_fail"] += 1
            raise DeviceChaosError(
                "chunk_fail",
                f"chaos: device failure fetching chunk {chunk_index}")

    def corrupt_rows(self, rows, chunk_index: int) -> int | None:
        """Maybe flip seeded bits in the fetched int32 result rows IN PLACE
        (silent data corruption between device and decode). Returns the
        ledger sequence of the injection (the backend reports the catch
        back through :meth:`note_caught`), or None."""
        if not self.armed or rows.size == 0:
            return None
        if self.rng.random() >= self.plan.corrupt_p:
            return None
        flat = rows.reshape(-1)
        flips = []
        for _ in range(max(1, self.plan.flips)):
            idx = self.rng.randrange(flat.size)
            bit = self.rng.randrange(31)  # stay off the sign bit: plausible
            flat[idx] ^= (1 << bit)       # garbage, not guaranteed-invalid
            flips.append([int(idx), int(bit)])
        self.counts["corrupt"] += 1
        self._corrupt_seq += 1
        seq = self._corrupt_seq
        self._ledger.append({
            "kind": "inject", "seq": seq, "member": self.member_id,
            "pid": os.getpid(), "chunk": chunk_index, "flips": flips,
            "atMs": time.time() * 1000.0})
        logger.warning("device chaos: corrupted result rows (seq %d, "
                       "%d flips)", seq, len(flips))
        return seq

    def note_caught(self, seq: int, how: str) -> None:
        """The backend proves one injected corruption never reached the
        commit path: ``how`` is ``shadow`` (mismatch vs the host oracle,
        host result committed) or ``contained`` (the carrying group was
        abandoned and host re-executed)."""
        self.counts["corrupt_caught"] += 1
        self._ledger.append({
            "kind": "caught", "seq": seq, "member": self.member_id,
            "pid": os.getpid(), "how": how, "atMs": time.time() * 1000.0})

    # -- the tick (disarm + evidence) ---------------------------------------

    def tick(self) -> None:
        if (self.armed and self.disarm_file is not None
                and os.path.exists(self.disarm_file)):
            self.armed = False
            logger.warning("device chaos DISARMED for %s", self.member_id)
        self._counts_snap.maybe_dump(self.counts)


def maybe_install_from_env(member_id: str = "",
                           data_dir: str | None = None,
                           env: dict | None = None):
    """Install a :class:`DeviceChaosController` into the kernel backend's
    dispatch seam when ``ZEEBE_CHAOS_DEVICE`` is set; returns it (or None).
    Also points the process's device-health ladder at a JSONL evidence
    file so the offline gate can prove the full quarantine→canary cycle."""
    env = os.environ if env is None else env
    spec = env.get("ZEEBE_CHAOS_DEVICE")
    if not spec:
        return None
    try:
        plan = parse_spec(spec)
    except ValueError as exc:
        logger.error("ignoring malformed ZEEBE_CHAOS_DEVICE %r: %s", spec, exc)
        return None
    controller = DeviceChaosController(plan, member_id=member_id)
    if data_dir:
        controller.counts_file = os.path.join(
            data_dir, f"device-chaos-counts-{os.getpid()}.json")
        controller.ledger_file = os.path.join(
            data_dir, f"device-corrupt-{os.getpid()}.jsonl")
    controller.disarm_file = env.get("ZEEBE_CHAOS_DEVICE_DISARMFILE") or None

    from zeebe_tpu.engine import kernel_backend
    from zeebe_tpu.engine.device_health import shared_device_health

    kernel_backend.install_device_chaos(controller)
    if data_dir:
        shared_device_health().evidence_file = os.path.join(
            data_dir, f"device-health-{os.getpid()}.jsonl")
    logger.warning("device chaos ACTIVE for %s: %s", member_id, spec)
    return controller
