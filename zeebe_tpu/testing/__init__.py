"""EngineHarness — the EngineRule equivalent: a real engine on a real log with
no gateway, no Raft, no network.

Reference: engine/src/test/java/io/camunda/zeebe/engine/util/EngineRule.java:73,
TestStreams (writes commands directly to the log), ProcessingExporterTransistor
(feeds every written record into the RecordingExporter), ControlledActorClock
(deterministic time).

Also the module the bench and the gateway-less demo drive — the reference uses
EngineRule for its CI perf gate (EngineLargeStatePerformanceTest) the same way.
"""

from __future__ import annotations

import tempfile
from pathlib import Path
from typing import Any

from zeebe_tpu.engine.engine import Engine
from zeebe_tpu.exporters.recording import RecordingExporter
from zeebe_tpu.journal import SegmentedJournal
from zeebe_tpu.logstreams import LogAppendEntry, LogStream
from zeebe_tpu.models.bpmn import ProcessModel, to_bpmn_xml
from zeebe_tpu.protocol import Record, ValueType, command
from zeebe_tpu.protocol.intent import (
    DeploymentIntent,
    IncidentIntent,
    JobBatchIntent,
    JobIntent,
    ProcessInstanceCreationIntent,
    ProcessInstanceIntent,
    VariableDocumentIntent,
)
from zeebe_tpu.state import ZbDb
from zeebe_tpu.stream import StreamProcessor, StreamProcessorMode


class ControlledClock:
    """Deterministic test clock (reference: ControlledActorClock)."""

    def __init__(self, start_millis: int = 1_000_000) -> None:
        self.millis = start_millis

    def __call__(self) -> int:
        return self.millis

    def advance(self, millis: int) -> None:
        self.millis += millis


class EngineHarness:
    def __init__(
        self,
        directory: str | Path | None = None,
        partition_id: int = 1,
        max_commands_in_batch: int = 100,
        consistency_checks: bool = True,
        partition_count: int = 1,
        sender=None,
        clock: ControlledClock | None = None,
        use_kernel_backend: bool = False,
        mesh_runner=None,
    ) -> None:
        self._tmp = None
        if directory is None:
            self._tmp = tempfile.TemporaryDirectory()
            directory = self._tmp.name
        self.clock = clock or ControlledClock()
        self.journal = SegmentedJournal(Path(directory) / "log")
        self.stream = LogStream(self.journal, partition_id, clock=self.clock)
        self.db = ZbDb(consistency_checks=consistency_checks)
        self.engine = Engine(self.db, partition_id, clock_millis=self.clock,
                             partition_count=partition_count)
        self.exporter = RecordingExporter()
        self.responses: list = []
        kernel_backend = None
        if use_kernel_backend:
            from zeebe_tpu.engine.kernel_backend import KernelBackend

            # audit mode: every burst-template hit ALSO runs the slow path
            # and asserts byte/state/response equality — the whole test suite
            # continuously cross-checks the template codegen
            # small group bucket: tests drive few instances at a time, and
            # the kernel pads every group to the max-group geometry
            kernel_backend = KernelBackend(self.engine, max_group=64,
                                           audit_templates=True,
                                           mesh_runner=mesh_runner)
        self.kernel_backend = kernel_backend
        self.processor = StreamProcessor(
            self.stream,
            self.db,
            self.engine,
            max_commands_in_batch=max_commands_in_batch,
            response_sink=self.responses.append,
            clock_millis=self.clock,
            kernel_backend=kernel_backend,
        )
        from zeebe_tpu.engine.distribution import CommandRedistributor
        from zeebe_tpu.engine.message_timer import DueDateCheckers
        from zeebe_tpu.parallel.partitioning import LoopbackCommandSender

        if sender is None:
            sender = LoopbackCommandSender(
                lambda rec: self.stream.writer.try_write([LogAppendEntry(rec)])
            )
        self.engine.wire_sender(sender)
        self.checkers = DueDateCheckers(self.engine.state, self.processor.schedule_service, self.clock)
        self.redistributor = CommandRedistributor(
            self.engine.state, self.engine.sender, self.processor.schedule_service, self.clock
        )
        self.processor.start()
        self._exported_until = 0

    def close(self) -> None:
        self.journal.close()
        if self._tmp is not None:
            self._tmp.cleanup()

    # -- pump ----------------------------------------------------------------

    # set by MultiPartitionHarness: partition pumps then drive the whole cluster
    cluster = None

    def pump(self) -> None:
        """Process everything pending (including due scheduled work), then
        transfer new records to the exporter (ProcessingExporterTransistor)."""
        if self.cluster is not None:
            self.cluster.pump_all()
            return
        self._pump_local()

    def _pump_local(self) -> None:
        for _ in range(1000):
            self.processor.run_until_idle()
            self.checkers.reschedule()
            self.redistributor.reschedule()
            due = self.processor.schedule_service.next_due_millis
            if due is None or due > self.clock():
                break
        else:
            raise RuntimeError(
                "pump did not quiesce after 1000 rounds — a due-date sweep is "
                "producing commands that fail to clear their due state"
            )
        for logged in self.stream.new_reader(self._exported_until + 1):
            self.exporter.export(logged)
            self._exported_until = logged.position

    def advance_time(self, millis: int) -> None:
        """Advance the controlled clock and process whatever becomes due."""
        self.clock.advance(millis)
        self.pump()
    # -- command ingress (the TestStreams role) ------------------------------

    def write_command(self, record: Record, request_id: int = -1) -> None:
        rec = record.replace(request_id=request_id, request_stream_id=0) if request_id >= 0 else record
        self.stream.writer.try_write([LogAppendEntry(rec)])
        self.pump()

    # -- fluent client-ish API ----------------------------------------------

    def deploy(self, *models: ProcessModel | str | tuple, request_id: int = 1) -> None:
        resources = []
        for i, model in enumerate(models):
            if isinstance(model, tuple):  # (resourceName, raw xml) e.g. .dmn
                name, xml = model
            else:
                xml = model if isinstance(model, str) else to_bpmn_xml(model)
                name = f"resource_{i}.bpmn"
                if isinstance(model, ProcessModel):
                    name = f"{model.process_id}.bpmn"
            resources.append({"resourceName": name, "resource": xml})
        self.write_command(
            command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {"resources": resources}),
            request_id=request_id,
        )

    def create_instance(
        self, bpmn_process_id: str, variables: dict[str, Any] | None = None,
        version: int = -1, request_id: int = 2,
    ) -> int:
        self.write_command(
            command(
                ValueType.PROCESS_INSTANCE_CREATION,
                ProcessInstanceCreationIntent.CREATE,
                {
                    "bpmnProcessId": bpmn_process_id,
                    "version": version,
                    "variables": variables or {},
                },
            ),
            request_id=request_id,
        )
        created = (
            self.exporter.all()
            .with_value_type(ValueType.PROCESS_INSTANCE_CREATION)
            .with_intent(ProcessInstanceCreationIntent.CREATED)
            .with_value(bpmnProcessId=bpmn_process_id)
            .to_list()
        )
        return created[-1].record.value["processInstanceKey"]

    def cancel_instance(self, process_instance_key: int, request_id: int = 3) -> None:
        self.write_command(
            command(ValueType.PROCESS_INSTANCE, ProcessInstanceIntent.CANCEL, {},
                    key=process_instance_key),
            request_id=request_id,
        )

    def activate_jobs(
        self, job_type: str, worker: str = "test-worker", max_jobs: int = 32,
        timeout: int = 300_000, request_id: int = 4,
    ) -> list[dict]:
        before = self.exporter.job_batch_records().with_intent(JobBatchIntent.ACTIVATED).count()
        self.write_command(
            command(
                ValueType.JOB_BATCH, JobBatchIntent.ACTIVATE,
                {"type": job_type, "worker": worker, "timeout": timeout,
                 "maxJobsToActivate": max_jobs},
            ),
            request_id=request_id,
        )
        batches = self.exporter.job_batch_records().with_intent(JobBatchIntent.ACTIVATED).to_list()
        new = batches[before:]
        jobs = []
        for batch in new:
            for key, job in zip(batch.record.value["jobKeys"], batch.record.value["jobs"]):
                jobs.append({"key": key, **job})
        return jobs

    def complete_job(self, job_key: int, variables: dict | None = None, request_id: int = 5) -> None:
        self.write_command(
            command(ValueType.JOB, JobIntent.COMPLETE, {"variables": variables or {}}, key=job_key),
            request_id=request_id,
        )

    def fail_job(self, job_key: int, retries: int, error_message: str = "", request_id: int = 6) -> None:
        self.write_command(
            command(ValueType.JOB, JobIntent.FAIL,
                    {"retries": retries, "errorMessage": error_message}, key=job_key),
            request_id=request_id,
        )

    def resolve_incident(self, incident_key: int, request_id: int = 7) -> None:
        self.write_command(
            command(ValueType.INCIDENT, IncidentIntent.RESOLVE, {}, key=incident_key),
            request_id=request_id,
        )

    def update_job_retries(self, job_key: int, retries: int, request_id: int = 8) -> None:
        self.write_command(
            command(ValueType.JOB, JobIntent.UPDATE_RETRIES, {"retries": retries}, key=job_key),
            request_id=request_id,
        )

    def publish_message(
        self, name: str, correlation_key: str, variables: dict | None = None,
        ttl: int = 3_600_000, message_id: str = "", request_id: int = 11,
    ) -> None:
        from zeebe_tpu.protocol.intent import MessageIntent

        self.write_command(
            command(
                ValueType.MESSAGE, MessageIntent.PUBLISH,
                {
                    "name": name,
                    "correlationKey": correlation_key,
                    "timeToLive": ttl,
                    "messageId": message_id,
                    "variables": variables or {},
                },
            ),
            request_id=request_id,
        )

    def broadcast_signal(self, name: str, variables: dict | None = None, request_id: int = 12) -> None:
        from zeebe_tpu.protocol.intent import SignalIntent

        self.write_command(
            command(ValueType.SIGNAL, SignalIntent.BROADCAST,
                    {"signalName": name, "variables": variables or {}}),
            request_id=request_id,
        )

    def throw_job_error(self, job_key: int, error_code: str, error_message: str = "",
                        request_id: int = 13) -> None:
        self.write_command(
            command(ValueType.JOB, JobIntent.THROW_ERROR,
                    {"errorCode": error_code, "errorMessage": error_message}, key=job_key),
            request_id=request_id,
        )

    def set_variables(self, scope_key: int, variables: dict, local: bool = False, request_id: int = 9) -> None:
        self.write_command(
            command(ValueType.VARIABLE_DOCUMENT, VariableDocumentIntent.UPDATE,
                    {"scopeKey": scope_key, "variables": variables, "local": local}),
            request_id=request_id,
        )

    # -- state helpers -------------------------------------------------------

    def is_instance_done(self, process_instance_key: int) -> bool:
        with self.db.transaction():
            return self.engine.state.element_instances.get(process_instance_key) is None

    def variables_of(self, scope_key: int) -> dict:
        with self.db.transaction():
            return self.engine.state.variables.collect(scope_key)


class MultiPartitionHarness:
    """N in-process partitions wired through a loopback inter-partition sender —
    the reference's primary multi-node harness (EngineRule with partitionCount>1
    + TestInterPartitionCommandSender, engine/src/test/…/util/
    TestInterPartitionCommandSender.java): full multi-partition engine logic in
    one process, no Raft, no network."""

    def __init__(self, partition_count: int = 3, directory: str | Path | None = None,
                 consistency_checks: bool = True,
                 use_kernel_backend: bool = False, mesh_runner=None) -> None:
        from zeebe_tpu.parallel.partitioning import InProcessClusterSender

        self._tmp = None
        if directory is None:
            self._tmp = tempfile.TemporaryDirectory()
            directory = self._tmp.name
        self.partition_count = partition_count
        self.clock = ControlledClock()
        self.sender = InProcessClusterSender()
        self.partitions: dict[int, EngineHarness] = {}
        self.mesh_runner = mesh_runner
        self._pumping = False
        for pid in range(1, partition_count + 1):
            h = EngineHarness(
                directory=Path(directory) / f"partition-{pid}",
                partition_id=pid,
                partition_count=partition_count,
                sender=self.sender,
                clock=self.clock,
                consistency_checks=consistency_checks,
                use_kernel_backend=use_kernel_backend,
                mesh_runner=mesh_runner,
            )
            h.cluster = self
            self.partitions[pid] = h
            self.sender.register(
                pid, lambda rec, h=h: h.stream.writer.try_write([LogAppendEntry(rec)])
            )
        self._round_robin = 0

    def close(self) -> None:
        for h in self.partitions.values():
            h.close()
        if self._tmp is not None:
            self._tmp.cleanup()

    def partition(self, partition_id: int) -> EngineHarness:
        return self.partitions[partition_id]

    # -- cluster pump ---------------------------------------------------------

    def pump_all(self) -> None:
        """Pump every partition until the whole cluster quiesces (inter-partition
        sends land on sibling logs and must be drained in turn)."""
        if self._pumping:
            return
        self._pumping = True
        try:
            for _ in range(1000):
                # quiesce on log END positions, not exporter positions: a round
                # whose only effect is a cross-partition send into an
                # already-pumped sibling log must trigger another round
                before = tuple(h.stream._next_position for h in self.partitions.values())
                for h in self.partitions.values():
                    h._pump_local()
                after = tuple(h.stream._next_position for h in self.partitions.values())
                if after == before:
                    return
            raise RuntimeError("cluster pump did not quiesce after 1000 rounds")
        finally:
            self._pumping = False

    def advance_time(self, millis: int) -> None:
        self.clock.advance(millis)
        self.pump_all()

    # -- cluster-level client API --------------------------------------------

    def deploy(self, *models: ProcessModel | str, request_id: int = 1) -> None:
        """Deployments always enter on the deployment partition (1)."""
        self.partitions[1].deploy(*models, request_id=request_id)

    def create_instance(self, bpmn_process_id: str, variables: dict[str, Any] | None = None,
                        partition_id: int | None = None, version: int = -1) -> int:
        """Round-robin instance creation across partitions (the gateway's
        RequestDispatchStrategy) unless a partition is pinned."""
        if partition_id is None:
            partition_id = (self._round_robin % self.partition_count) + 1
            self._round_robin += 1
        return self.partitions[partition_id].create_instance(
            bpmn_process_id, variables, version=version
        )

    def publish_message(self, name: str, correlation_key: str, **kw: Any) -> None:
        """Messages route by correlation-key hash (SubscriptionUtil)."""
        from zeebe_tpu.parallel.partitioning import subscription_partition_id

        pid = subscription_partition_id(correlation_key, self.partition_count)
        self.partitions[pid].publish_message(name, correlation_key, **kw)

    def records(self):
        """All partitions' records merged (position-interleaved per partition)."""
        out = []
        for h in self.partitions.values():
            out.extend(h.exporter.all().to_list())
        return out


def _await_partition_resources(runtime, process_ids, want_present: bool,
                               what: str, timeout_s: float) -> None:
    import time as _time

    deadline = _time.time() + timeout_s
    mismatched: list = [("*", "*")]
    while _time.time() < deadline:
        mismatched = []
        for pid in range(1, runtime.partition_count + 1):
            with runtime._plocks[pid]:
                leader = runtime._leader_partition(pid)
                if leader is None or leader.engine is None:
                    mismatched.append((pid, "*"))
                    continue
                with leader.db.transaction():
                    for process_id in process_ids:
                        found = leader.engine.state.processes.get_latest_by_id(
                            process_id) is not None
                        if found != want_present:
                            mismatched.append((pid, process_id))
        if not mismatched:
            return
        _time.sleep(0.01)
    raise TimeoutError(f"{what}: {mismatched}")


def await_resource_absent(runtime, process_ids, timeout_s: float = 10.0) -> None:
    """Inverse of await_deployment_distributed: block until NO partition
    leader resolves the given process ids (resource DELETION distributes
    asynchronously exactly like deployment)."""
    _await_partition_resources(runtime, process_ids, want_present=False,
                               what="resource deletion not distributed",
                               timeout_s=timeout_s)


def await_deployment_distributed(runtime, process_ids, timeout_s: float = 10.0) -> None:
    """Block until every partition leader of an in-process ClusterRuntime can
    resolve the given process ids. Deployment distribution is asynchronous by
    design (the reference's DeploymentCreateProcessor responds on partition-1
    commit and distributes afterwards — DeploymentCreateProcessor.java:166),
    so a create-by-id racing the distribution to another partition is
    legitimate NOT_FOUND behavior; tests that deploy-then-create on a
    multi-partition cluster should wait this race out the same way the
    reference's own tests await the RecordingExporter."""
    _await_partition_resources(runtime, process_ids, want_present=True,
                               what="deployment not distributed",
                               timeout_s=timeout_s)


def distributing_client(client, runtime):
    """Wrap a ZeebeTpuClient so deploy_resource also awaits distribution to
    every partition (see await_deployment_distributed)."""
    original = client.deploy_resource

    def deploy_and_await(*resources, **kw):
        result = original(*resources, **kw)
        ids = [p["bpmnProcessId"] for p in result.get("processes", [])]
        if ids:
            await_deployment_distributed(runtime, ids)
        return result

    client.deploy_resource = deploy_and_await
    return client
