"""Shared gate-evidence plumbing: flight-dump collection and CI artifact
preservation — ONE home (the ``_collect_gate_dumps`` consolidation started
in PR 9, finished here after zlint's drift-copy rule caught the
``_collect_flight_dumps`` twins in the soak and scale-soak harnesses).

Protocols, each used by every chaos gate:

- :func:`collect_flight_dumps` — after a crash-restart, verify the broker
  left a readable flight dump newer than the restart whose rings carry the
  recovery event, and track which dumps have been claimed.
- :func:`collect_gate_dumps` — copy a gate's flight dumps out of its
  about-to-be-deleted work dir into ``<repo>/<NAME>_dumps/`` for CI
  artifact upload.
- :func:`percentile` — the one shared latency-percentile rule for gate
  reports (the serving gate's SLO math must not drift from any other
  gate's).
- :func:`collect_span_dumps` — gather the per-process span JSONL files
  (``spans-<node>-<pid>.jsonl``) a traced cluster run left behind, for the
  offline critical-path assembler (PR 19).
"""

from __future__ import annotations

import json
import os
from pathlib import Path


def percentile(ordered: list, q: float) -> float:
    """Nearest-rank percentile (rank = ceil(q*n)) over an ASCENDING list,
    0 < q <= 1. Empty input yields 0.0 — a gate with no samples must gate
    on the count, not on a synthetic latency."""
    import math

    if not ordered:
        return 0.0
    rank = max(math.ceil(q * len(ordered)) - 1, 0)
    return float(ordered[min(rank, len(ordered) - 1)])


def collect_flight_dumps(data_dir: str | Path, seen: list[str],
                         since_ms: int, label: str,
                         violations: list[str]) -> None:
    """Claim the new flight dumps under ``data_dir`` for one recovery.

    The partition dumps its flight rings itself when a recovery completes;
    every gate verifies each restart left such an artifact — a readable
    dump, newer than the restart (``since_ms``, broker clock), whose rings
    carry the recovery event. Claimed paths append to ``seen`` (so the next
    restart only considers newer dumps); failures append to ``violations``
    prefixed with ``label``.
    """
    found = False
    for path in sorted(Path(data_dir).glob("flight-*.json")):
        if str(path) in seen:
            continue
        try:
            dump = json.loads(path.read_text())
        except (OSError, ValueError):
            violations.append(f"{label}: flight dump {path} is unreadable")
            continue
        if dump.get("dumpedAtMs", 0) < since_ms:
            continue
        seen.append(str(path))
        if any(ev.get("kind") == "recovery"
               for ring in dump.get("partitions", {}).values()
               for ev in ring):
            found = True
    if not found:
        violations.append(
            f"{label}: no flight dump carries the recovery event for this "
            f"restart")


def collect_span_dumps(root: str | Path) -> list[Path]:
    """Every per-process span dump under ``root`` (recursive): each traced
    process — gateway (``ZEEBE_TRACE_DUMP_DIR``) and workers (their broker
    data dirs) — writes ``spans-<node>-<pid>.jsonl`` at orderly shutdown.
    Point every process at dirs under one root and this finds them all;
    feed the result to ``critical_path.load_spans`` / ``assemble`` to merge
    the cluster's view of each trace."""
    return sorted(Path(root).rglob("spans-*.jsonl"))


def collect_gate_dumps(dump_paths, dumps_name: str, work_dir: str,
                       repo_dir: str | None = None) -> list:
    """Copy a chaos gate's flight dumps out of its (about-to-be-deleted)
    work dir into ``<repo_dir>/<dumps_name>/`` for CI artifact upload;
    returns the repo-relative copied paths. Shared by the soak, scale-soak,
    and consistency gates — one dump-preservation protocol, not three."""
    import shutil

    if repo_dir is None:
        # zeebe_tpu/testing/evidence.py -> repo root
        repo_dir = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    dumps_dir = os.path.join(repo_dir, dumps_name)
    shutil.rmtree(dumps_dir, ignore_errors=True)
    os.makedirs(dumps_dir, exist_ok=True)
    copied = []
    for dump in dump_paths:
        rel = os.path.relpath(str(dump), work_dir).replace(os.sep, "__")
        target = os.path.join(dumps_dir, rel)
        try:
            shutil.copyfile(dump, target)
            copied.append(os.path.relpath(target, repo_dir))
        except OSError:
            pass
    return copied
