"""The storage torture gate: disk + TCP + kill chaos, live simultaneously
(ISSUE 14).

The consistency gate (PR 9) proved exactly-once delivery when the *network*
and *processes* lie; this gate adds the third liar — the disk — and keeps
all three running at once. Real supervised worker processes serve the
Jepsen-shaped workload while ``ZEEBE_CHAOS_DISK`` injects write EIO/ENOSPC,
torn short-writes, fsync stalls, fsync failures, and at-rest bit-rot flips
into their journals, snapshot stores, and cold tiers, and ``ZEEBE_CHAOS_TCP``
plus a ``kill_worker`` storm keep the PR 9 fault classes live.

Gates:

- **delivery invariants hold** — the PR 9 checker (no acked loss in log AND
  export stream, no duplicate application, rejections terminal, positions
  monotone) over the same offline evidence, now collected from disks that
  were actively lying;
- **every configured disk-fault class was observed** (aggregated per-life
  counts snapshots) — configured-but-never-applied chaos is a violation;
- **every at-rest bit-rot flip is accounted for**: each ledger entry must be
  detected by the scrubber/read path (scrub-state evidence), superseded
  (file wiped/quarantined/truncated before it could be read), or verifiably
  repaired (the file's frames re-validate offline); a flip that sat
  readable-and-undetected through the run fails the gate;
- **the repair probe converges**: a follower's raft journal is deliberately
  bit-flipped mid-drive-history, the follower's scrubber must detect and
  truncate-repair it, and the offline comparison proves the follower
  re-converged CRC-identical to the leader's log PAST the corrupted index —
  local corruption degraded into a bounded re-replication event.

``bench.py --torture [--quick]`` runs this and writes TORTURE[_quick].json;
the CI ``torture-smoke`` job gates on it.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import struct
import sys
import threading
import time
import zlib
from pathlib import Path
from typing import Any

from zeebe_tpu.testing.chaos_disk import DiskFaultPlan
from zeebe_tpu.testing.chaos_disk import format_spec as format_disk_spec
from zeebe_tpu.testing.consistency import (
    ClientOp,
    _await_exports,
    check_consistency,
    collect_exports,
    submit_client_op,
)

logger = logging.getLogger("zeebe_tpu.testing.torture")

#: flips younger than this at run end are excused from the detection
#: requirement (the scrubber never got a full pass over them)
BITROT_GRACE_MS = 12_000


@dataclasses.dataclass
class TortureConfig:
    seed: int = 0
    workers: int = 3
    partitions: int = 2
    replication: int = 3
    drive_seconds: float = 20.0
    think_ms: float = 15.0
    request_timeout_s: float = 20.0
    kills: int = 1
    # TCP chaos rides along, milder than the consistency gate (the disk is
    # tonight's liar; the network must still be untrustworthy)
    drop_p: float = 0.005
    duplicate_p: float = 0.01
    delay_p: float = 0.02
    reorder_p: float = 0.01
    # disk chaos
    # rates sized so every class fires with margin in a ~20s quick drive
    # (the gate REQUIRES a nonzero observed count per configured class):
    # ~3k writes and ~700 fsyncs per quick run put the rarest class's
    # expected count near 5
    disk_eio_p: float = 0.004
    disk_enospc_p: float = 0.003
    disk_torn_p: float = 0.004
    disk_fsync_fail_p: float = 0.006
    disk_fsync_stall_p: float = 0.01
    disk_stall_ms: int = 80
    disk_bitrot_interval_ms: int = 1_200
    # rot starts after boot + deploy warmup: see DiskFaultPlan
    disk_bitrot_delay_ms: int = 12_000
    scrub_interval_ms: int = 200
    reject_every: int = 25
    kernel_backend: bool = False
    # tiering ON so the cold tier is a live bit-rot target
    tiering: bool = True
    tiering_park_after_ms: int = 500


# ---------------------------------------------------------------------------
# offline verification helpers (pure — unit-testable without a cluster)


_SEG_HEADER = struct.Struct("<IIQQ")
_JOURNAL_FRAME = struct.Struct("<IIQq")
_COLD_FRAME = struct.Struct("<IIH")


#: how far past a damaged frame the tolerant walkers search for the next
#: CRC-verified frame header before giving up on the file
_RESYNC_SCAN_BYTES = 4 << 20


def _walk_frames_tolerant(raw: bytes, first_index: int):
    """Yield ``(index, asqn, data, valid)`` per journal frame, resyncing
    past damaged frames: record indexes are contiguous and known in
    advance, so after a frame whose LENGTH field was rotted (the walk can
    no longer step over it) the next frame is findable by scanning for a
    header whose index matches the expectation AND whose CRC validates —
    a false positive would need a 32-bit CRC collision on top of a
    matching index. Yields ``valid=False`` for skippable bad-CRC frames
    (their extent survived)."""
    offset = _SEG_HEADER.size
    expected = first_index
    n = len(raw)
    while offset + _JOURNAL_FRAME.size <= n:
        length, crc, index, asqn = _JOURNAL_FRAME.unpack_from(raw, offset)
        end = offset + _JOURNAL_FRAME.size + length
        if 0 < length and end <= n and index == expected:
            data = raw[offset + _JOURNAL_FRAME.size:end]
            head = struct.pack("<Qq", index, asqn)
            ok = zlib.crc32(data, zlib.crc32(head)) & 0xFFFFFFFF == crc
            yield index, asqn, data, ok
            expected += 1
            offset = end
            continue
        # structurally damaged (rotted length/index field, or torn tail):
        # try to resync on a later, CRC-proven frame
        found = None
        limit = min(n - _JOURNAL_FRAME.size, offset + _RESYNC_SCAN_BYTES)
        for pos in range(offset + 1, limit):
            c_len, c_crc, c_index, c_asqn = _JOURNAL_FRAME.unpack_from(
                raw, pos)
            if not (0 < c_len and expected <= c_index <= expected + 64
                    and pos + _JOURNAL_FRAME.size + c_len <= n):
                continue
            c_data = raw[pos + _JOURNAL_FRAME.size:
                         pos + _JOURNAL_FRAME.size + c_len]
            c_head = struct.pack("<Qq", c_index, c_asqn)
            if zlib.crc32(c_data, zlib.crc32(c_head)) & 0xFFFFFFFF == c_crc:
                found = (pos, c_index)
                break
        if found is None:
            return  # torn tail / nothing provable beyond this point
        offset, expected = found


def journal_records_crc(path: Path) -> tuple[dict[int, int], bool]:
    """(index → crc32 of record data) for one journal segment file, plus
    whether every byte-reachable frame CRC-validated. A partial trailing
    frame reads as valid (torn tails are crash-normal; recovery truncates
    them) — a CRC mismatch mid-walk does not."""
    try:
        raw = path.read_bytes()
    except OSError:
        return {}, False
    if len(raw) < _SEG_HEADER.size:
        return {}, False
    magic, version, _seg, first = _SEG_HEADER.unpack_from(raw)
    if magic != 0x5A4A4E4C or version != 1:
        return {}, False
    out: dict[int, int] = {}
    offset = _SEG_HEADER.size
    expected = first
    n = len(raw)
    while offset + _JOURNAL_FRAME.size <= n:
        length, crc, index, asqn = _JOURNAL_FRAME.unpack_from(raw, offset)
        end = offset + _JOURNAL_FRAME.size + length
        if length == 0 or end > n or index != expected:
            return out, True  # torn/garbage tail: truncatable, not rot
        data = raw[offset + _JOURNAL_FRAME.size:end]
        head = struct.pack("<Qq", index, asqn)
        if zlib.crc32(data, zlib.crc32(head)) & 0xFFFFFFFF != crc:
            return out, False
        out[index] = zlib.crc32(data) & 0xFFFFFFFF
        expected += 1
        offset = end
    return out, True


def journal_dir_records(directory: Path) -> tuple[dict[int, int], bool]:
    """Merge every segment in a journal directory (oldest→newest) into one
    index→crc map; ``ok`` is False if any mid-file frame failed CRC."""
    out: dict[int, int] = {}
    ok = True
    for path in sorted(directory.glob("journal-*.log"),
                       key=lambda p: int(p.stem.rsplit("-", 1)[1])):
        crcs, seg_ok = journal_records_crc(path)
        out.update(crcs)
        ok = ok and seg_ok
    return out, ok


def journal_dir_records_tolerant(directory: Path) -> dict[int, int]:
    """index→crc over VALID frames only, SKIPPING bad-CRC frames via their
    surviving length fields (same resync trick as the union log reader).
    The probe's convergence comparison needs this: with at-rest bit rot
    running through teardown, EITHER replica may hold late rot the
    scrubber never reached — the repair verdict must compare the frames
    both sides can still read, not stop at the first one they can't."""
    out: dict[int, int] = {}
    for path in sorted(directory.glob("journal-*.log"),
                       key=lambda p: int(p.stem.rsplit("-", 1)[1])):
        try:
            raw = path.read_bytes()
        except OSError:
            continue
        if len(raw) < _SEG_HEADER.size:
            continue
        magic, version, _seg, first = _SEG_HEADER.unpack_from(raw)
        if magic != 0x5A4A4E4C or version != 1:
            continue
        for index, _asqn, data, valid in _walk_frames_tolerant(raw, first):
            if valid:
                out[index] = zlib.crc32(data) & 0xFFFFFFFF
    return out


def cold_file_fully_valid(path: Path) -> bool:
    try:
        raw = path.read_bytes()
    except OSError:
        return False
    pos = 0
    n = len(raw)
    while pos + _COLD_FRAME.size <= n:
        frame_len, crc, _key_len = _COLD_FRAME.unpack_from(raw, pos)
        end = pos + frame_len
        if frame_len < _COLD_FRAME.size or end > n:
            return True  # torn tail (flush boundary), not mid-file rot
        if zlib.crc32(raw[pos + _COLD_FRAME.size:end]) & 0xFFFFFFFF != crc:
            return False
        pos = end
    return True


def flipped_file_repaired(flip: dict) -> bool:
    """Offline proof a flipped file no longer serves the flipped bytes:
    the file's reachable frames all CRC-validate again (journal/cold), or
    the snapshot directory's manifest validates."""
    path = Path(flip["path"])
    cls = flip.get("class")
    if cls == "journal":
        _crcs, ok = journal_records_crc(path)
        return ok
    if cls == "cold":
        return cold_file_fully_valid(path)
    if cls == "snapshot":
        from zeebe_tpu.state.snapshot import _verify_manifest

        return _verify_manifest(path.parent)
    return False


def _detection_matches_flip(event: dict, flip: dict, worker_dir: str) -> bool:
    """Does one scrub-evidence event (detection or repair) plausibly cover
    one ledger flip? Matching is per class: journal flips match raft/stream
    events whose directory prefixes the flipped file; snapshot flips match
    by path or snapshot id; cold flips match any cold event in the same
    worker tree."""
    if event.get("atMs", 0) < flip.get("atMs", 0) - 3_000:
        return False  # evidence predates the flip (clock slack 3s)
    cls = flip.get("class")
    target = event.get("target")
    path = flip.get("path", "")
    if cls == "journal":
        if target not in ("raft", "stream"):
            return False
        directory = event.get("directory", "")
        return bool(directory) and path.startswith(directory)
    if cls == "snapshot":
        if target != "snapshot":
            return False
        if event.get("path") == path:
            return True
        snap_id = event.get("snapshotId")
        return snap_id is not None and f"/{snap_id}/" in path
    if cls == "cold":
        return target == "cold" and path.startswith(worker_dir)
    return False


def collect_scrub_evidence(directory: Path) -> dict[str, list[dict]]:
    """worker-partition dir → detection+repair events, merged from the live
    scrub-state files AND any flight dumps (a killed worker's scrub state
    survives as its last atomic snapshot)."""
    out: dict[str, list[dict]] = {}
    for path in directory.glob("*/partition-*/scrub-state.json"):
        try:
            state = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            continue
        events = list(state.get("detections", []))
        events += list(state.get("repairs", []))
        out[str(path.parent)] = events
    # flight dumps each carry the FULL ring — successive dumps repeat the
    # same events, so dedupe by identity before merging (the matcher's
    # cost and the evidence count must reflect distinct events)
    seen: set[tuple] = set()
    for dump in sorted(directory.glob("*/flight-*.json")):
        try:
            payload = json.loads(dump.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            continue
        key = str(dump.parent)
        for ring in payload.get("partitions", {}).values():
            for ev in ring:
                if ev.get("kind") not in ("storage_corruption",
                                          "storage_repair"):
                    continue
                ident = (key, ev.get("t"), ev.get("kind"), ev.get("target"),
                         ev.get("atMs"), ev.get("corruptIndex"),
                         ev.get("action"))
                if ident in seen:
                    continue
                seen.add(ident)
                out.setdefault(key, []).append(
                    {**ev, "atMs": ev.get("atMs", ev.get("t", 0))})
    return out


def check_bitrot_flips(flips: list[dict], evidence: dict[str, list[dict]],
                       run_end_ms: float) -> tuple[list[str], dict]:
    """The detected-or-repaired accounting over the bit-rot ledger."""
    violations: list[str] = []
    stats = {"flips": len(flips), "detected": 0, "superseded": 0,
             "repairedVerified": 0, "tooRecent": 0}
    for flip in flips:
        path = flip.get("path", "")
        worker_dir = None
        for candidate in evidence:
            if path.startswith(candidate.rsplit("/partition-", 1)[0]):
                worker_dir = candidate.rsplit("/partition-", 1)[0]
                break
        matched = any(
            _detection_matches_flip(ev, flip,
                                    key.rsplit("/partition-", 1)[0])
            for key, events in evidence.items()
            for ev in events
            if worker_dir is None or key.startswith(worker_dir))
        if matched:
            stats["detected"] += 1
            continue
        if not os.path.exists(path):
            # wiped (cold dir on restart), quarantined (snapshot rename),
            # or unlinked (segment delete): the bytes can never be served
            stats["superseded"] += 1
            continue
        if os.path.getsize(path) <= flip.get("offset", 0):
            stats["superseded"] += 1  # truncated below the flip
            continue
        if flipped_file_repaired(flip):
            stats["repairedVerified"] += 1
            continue
        if run_end_ms - flip.get("atMs", 0) < BITROT_GRACE_MS:
            stats["tooRecent"] += 1
            continue
        violations.append(
            f"bit-rot flip at {path}@{flip.get('offset')} "
            f"({flip.get('class')}) was never detected, superseded, or "
            f"repaired — corrupt bytes sat servable through the run")
    return violations, stats


def read_replica_log_tolerant(stream_dir: Path, partition_id: int
                              ) -> tuple[list[dict], int]:
    """One replica's materialized stream journal as checker rows, SKIPPING
    rotten frames instead of truncating at them (the consistency reader's
    posture). At teardown a replica may hold bit-rot the scrubber's last
    pass never reached — on a live system the next boot + scrub + raft
    re-convergence repairs it, but offline the oracle must not let one
    replica's rotten frame hide every later record: record indexes are
    contiguous and the frame length field usually survives a one-byte
    flip, so a bad-CRC frame with a plausible extent is skipped and the
    walk resumes at the next frame. Returns (rows, skipped_frames)."""
    from zeebe_tpu.logstreams.log_stream import _deserialize_batch

    rows: list[dict] = []
    skipped = 0
    for path in sorted(stream_dir.glob("journal-*.log"),
                       key=lambda p: int(p.stem.rsplit("-", 1)[1])):
        try:
            raw = path.read_bytes()
        except OSError:
            continue
        if len(raw) < _SEG_HEADER.size:
            continue
        magic, version, _seg, first = _SEG_HEADER.unpack_from(raw)
        if magic != 0x5A4A4E4C or version != 1:
            continue
        for _index, _asqn, data, valid in _walk_frames_tolerant(raw, first):
            if not valid:
                skipped += 1
                continue
            try:
                batch = _deserialize_batch(data, partition_id)
            except Exception:  # noqa: BLE001 — undetected payload damage
                skipped += 1
                continue
            for logged in batch:
                rec = logged.record
                rows.append({
                    "p": logged.position,
                    "src": logged.source_position,
                    "rt": int(rec.record_type),
                    "vt": int(rec.value_type),
                    "it": int(rec.intent),
                    "rid": rec.request_id,
                    "sid": rec.request_stream_id,
                    "rej": rec.is_rejection,
                    "crc": zlib.crc32(rec.encode()[0]) & 0xFFFFFFFF,
                })
    return rows, skipped


def read_raft_log_tolerant(raft_dir: Path, partition_id: int
                           ) -> tuple[list[dict], int]:
    """Decode a replica's RAFT journal into the same checker rows — the
    raft log is the durable source of truth the ack chain actually rests
    on (fsynced before any ack), while the stream journal is derived and
    may legitimately lag on a wedged-then-killed worker (its un-drained
    tail dies with the process and rebuilds from raft on the next boot).
    Rot-tolerant like the stream reader. Entries beyond the replica's
    commit index can appear; for ACKED requests that is still valid
    evidence — an ack implies the command committed."""
    from zeebe_tpu.logstreams.log_stream import _deserialize_batch
    from zeebe_tpu.protocol.msgpack import unpackb

    rows: list[dict] = []
    skipped = 0
    for path in sorted(raft_dir.glob("journal-*.log"),
                       key=lambda p: int(p.stem.rsplit("-", 1)[1])):
        try:
            raw = path.read_bytes()
        except OSError:
            continue
        if len(raw) < _SEG_HEADER.size:
            continue
        magic, version, _seg, first = _SEG_HEADER.unpack_from(raw)
        if magic != 0x5A4A4E4C or version != 1:
            continue
        for _index, _asqn, data, valid in _walk_frames_tolerant(raw, first):
            if not valid:
                skipped += 1
                continue
            try:
                entry = unpackb(data)
                if entry.get("init") or not entry.get("data"):
                    continue
                batch = _deserialize_batch(entry["data"], partition_id)
            except Exception:  # noqa: BLE001 — undetected payload damage
                skipped += 1
                continue
            for logged in batch:
                rec = logged.record
                rows.append({
                    "p": logged.position,
                    "src": logged.source_position,
                    "rt": int(rec.record_type),
                    "vt": int(rec.value_type),
                    "it": int(rec.intent),
                    "rid": rec.request_id,
                    "sid": rec.request_stream_id,
                    "rej": rec.is_rejection,
                    "crc": zlib.crc32(rec.encode()[0]) & 0xFFFFFFFF,
                })
    return rows, skipped


def collect_logs_union(data_dir: Path, workers: list[str], partitions: int
                       ) -> tuple[dict[int, list[dict]], list[str], int]:
    """Per partition: the UNION of every replica's committed evidence,
    rot-tolerant — the materialized stream journals AND the raft journals
    they derive from (the raft log is what the ack chain fsyncs; a wedged
    worker SIGKILLed at teardown loses its stream journal's un-drained
    tail but never the raft frames backing it). With RF >= 2 a record
    rotten on one disk survives on the others — exactly the repair thesis
    the gate proves — so an acked command counts as lost only when NO
    replica holds a valid frame for it anywhere. Cross-source split-brain
    (same position, different bytes) is still a violation. Returns
    (logs, violations, skipped_frames)."""
    logs: dict[int, list[dict]] = {}
    violations: list[str] = []
    skipped_total = 0
    for pid in range(1, partitions + 1):
        by_position: dict[int, tuple[str, dict]] = {}
        raft_fill: dict[int, dict] = {}
        for worker in workers:
            part_dir = data_dir / worker / f"partition-{pid}"
            stream_dir = part_dir / "stream"
            if stream_dir.exists():
                rows, skipped = read_replica_log_tolerant(stream_dir, pid)
                skipped_total += skipped
                for rec in rows:
                    seen = by_position.get(rec["p"])
                    if seen is None:
                        by_position[rec["p"]] = (f"{worker}/stream", rec)
                    elif seen[1]["crc"] != rec["crc"]:
                        # stream journals hold ONLY committed entries, so
                        # same-position divergence here is real split-brain
                        violations.append(
                            f"partition {pid}: position {rec['p']} "
                            f"diverges between {seen[0]} and "
                            f"{worker}/stream (committed-log split-brain)")
            raft_dir = part_dir / "raft" / "raft-log"
            if raft_dir.exists():
                rows, skipped = read_raft_log_tolerant(raft_dir, pid)
                skipped_total += skipped
                for rec in rows:
                    raft_fill.setdefault(rec["p"], rec)
        # raft rows GAP-FILL only — an uncommitted raft suffix on a dead
        # replica may legitimately conflict with the committed history
        # (positions reused after a leader death), so raft evidence never
        # participates in the split-brain equality check and never
        # overrides a stream row
        for position, rec in raft_fill.items():
            if position not in by_position:
                by_position[position] = ("raft-fill", rec)
        logs[pid] = [rec for _pos, (_w, rec)
                     in sorted(by_position.items())]
    return logs, violations, skipped_total


def check_follower_reconvergence(data_dir: Path, workers: list[str],
                                 follower: str,
                                 corrupt_index: int | None) -> dict:
    """The probe's offline verdict, replica-agnostic: the corrupted
    follower must hold VALID raft entries past the corrupted index whose
    bytes agree with AT LEAST ONE other replica on every common valid
    index. (Comparing against the probe-time leader alone is fragile —
    by teardown that node may itself hold a stale uncommitted suffix or a
    boot-rot-rewound log; any honest replica's agreement proves the
    re-fetched region is the cluster's history, and rot-invalid frames on
    either side are excluded as proving nothing.)"""
    follower_map = journal_dir_records_tolerant(
        data_dir / follower / "partition-1" / "raft" / "raft-log")
    follower_last = max(follower_map, default=0)
    comparisons = []
    agreed = False
    for worker in workers:
        if worker == follower:
            continue
        other = journal_dir_records_tolerant(
            data_dir / worker / "partition-1" / "raft" / "raft-log")
        common = sorted(set(follower_map) & set(other))
        mismatches = [i for i in common
                      if follower_map[i] != other[i]]
        comparisons.append({"worker": worker, "commonRecords": len(common),
                            "crcMismatches": mismatches[:5]})
        if common and not mismatches:
            agreed = True
    verified = (agreed
                and (corrupt_index is None
                     or follower_last >= corrupt_index))
    return {
        "verified": verified,
        "followerValidRecords": len(follower_map),
        "followerLastValidIndex": follower_last,
        "corruptRegionIndex": corrupt_index,
        "comparisons": comparisons,
    }


def snapshot_horizons(data_dir: Path, workers: list[str],
                      partitions: int) -> dict[int, int]:
    """Per partition: the highest processed position covered by any
    replica's VALID snapshot chain (read-only inspection). Positions at or
    below the horizon may legally be COMPACTED out of every journal — the
    durability contract is log+chain, so the acked-loss oracle must not
    demand log evidence for them (export evidence still applies)."""
    from zeebe_tpu.state.snapshot import inspect_store

    horizons: dict[int, int] = {}
    for pid in range(1, partitions + 1):
        for worker in workers:
            store_dir = data_dir / worker / f"partition-{pid}" / "snapshots"
            if not store_dir.exists():
                continue
            for info in inspect_store(store_dir):
                if info.get("chainValid"):
                    horizons[pid] = max(horizons.get(pid, -1),
                                        info["processedPosition"])
    return horizons


def waive_compacted_losses(violations: list[str], history: list,
                           exports: dict[int, dict[int, dict]],
                           horizons: dict[int, int]) -> tuple[list[str], int]:
    """Drop 'no command in the log' violations for acked ops whose
    position sits under a valid snapshot horizon AND was exported — the
    snapshot owns the state, the export stream proves delivery; the log
    prefix was legally compacted. Everything else passes through."""
    by_rid = {(op.partition, op.request_id): op for op in history
              if op.outcome == "ack"}
    kept: list[str] = []
    waived = 0
    for violation in violations:
        if "has no command in the log" not in violation:
            kept.append(violation)
            continue
        op = None
        for (pid, rid), candidate in by_rid.items():
            if f"partition {pid}: acked request {rid} " in violation:
                op = candidate
                break
        if (op is not None and op.position >= 0
                and op.position <= horizons.get(op.partition, -1)
                and op.position in exports.get(op.partition, {})):
            waived += 1
            continue
        kept.append(violation)
    return kept, waived


def check_follower_convergence(leader_raft_dir: Path,
                               follower_raft_dir: Path,
                               corrupt_region_index: int | None) -> dict:
    """Offline CRC comparison of two replicas' raft logs: every common
    VALID index byte-identical, and the follower holds valid entries PAST
    the deliberately-corrupted region — the truncate-and-re-fetch repair
    converged. Rot-tolerant on both sides: at-rest bit rot keeps flipping
    bytes through teardown, so either replica may carry late rot the
    scrubber never reached — frames that no longer CRC are excluded from
    the comparison (a record only one side can read proves nothing either
    way), never allowed to hide the convergence verdict."""
    leader = journal_dir_records_tolerant(leader_raft_dir)
    follower = journal_dir_records_tolerant(follower_raft_dir)
    common = sorted(set(leader) & set(follower))
    mismatches = [i for i in common if leader[i] != follower[i]]
    follower_last = max(follower, default=0)
    verified = (
        not mismatches
        and bool(common)
        and (corrupt_region_index is None
             or follower_last >= corrupt_region_index)
    )
    return {
        "verified": verified,
        "leaderValidRecords": len(leader),
        "followerValidRecords": len(follower),
        "commonRecords": len(common),
        "crcMismatches": mismatches[:10],
        "followerLastValidIndex": follower_last,
        "corruptRegionIndex": corrupt_region_index,
    }


# ---------------------------------------------------------------------------
# the harness


def run_torture(cfg: TortureConfig, directory: str | Path) -> dict:
    """Run the full storage torture gate; returns the report dict."""
    from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
    from zeebe_tpu.multiproc.runtime import MultiProcClusterRuntime
    from zeebe_tpu.multiproc.supervisor import (
        WorkerSpec,
        WorkerSupervisor,
        worker_cmd,
    )
    from zeebe_tpu.protocol import ValueType
    from zeebe_tpu.protocol.intent import (
        DeploymentIntent,
        ProcessInstanceCreationIntent,
    )
    from zeebe_tpu.protocol.record import command
    from zeebe_tpu.standalone import _free_ports
    from zeebe_tpu.testing.chaos import FaultPlan
    from zeebe_tpu.testing.chaos_tcp import format_spec as format_tcp_spec

    directory = Path(directory)
    export_dir = directory / "exports"
    export_dir.mkdir(parents=True, exist_ok=True)
    rng = random.Random(cfg.seed)
    started = time.monotonic()
    epoch_ms = time.time() * 1000.0

    worker_names = [f"worker-{i}" for i in range(cfg.workers)]
    ports = _free_ports(cfg.workers + 1)
    contacts = {n: ("127.0.0.1", p) for n, p in zip(worker_names, ports)}
    contacts["gateway-0"] = ("127.0.0.1", ports[-1])
    contact_str = ",".join(
        f"{m}={h}:{p}" for m, (h, p) in sorted(contacts.items()))

    tcp_plan = FaultPlan(seed=cfg.seed, drop_p=cfg.drop_p,
                         duplicate_p=cfg.duplicate_p, delay_p=cfg.delay_p,
                         reorder_p=cfg.reorder_p, max_delay_ticks=3)
    disk_plan = DiskFaultPlan(
        seed=cfg.seed, eio_p=cfg.disk_eio_p, enospc_p=cfg.disk_enospc_p,
        torn_p=cfg.disk_torn_p, fsync_fail_p=cfg.disk_fsync_fail_p,
        fsync_stall_p=cfg.disk_fsync_stall_p, stall_ms=cfg.disk_stall_ms,
        bitrot_interval_ms=cfg.disk_bitrot_interval_ms,
        bitrot_delay_ms=cfg.disk_bitrot_delay_ms)

    repo = str(Path(__file__).resolve().parent.parent.parent)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    env["JAX_PLATFORMS"] = "cpu"
    if not cfg.kernel_backend:
        env["ZEEBE_BROKER_EXPERIMENTAL_KERNELBACKEND"] = "false"
    env["ZEEBE_CHAOS_TCP"] = format_tcp_spec(tcp_plan, [], tick_ms=50)
    env["ZEEBE_CHAOS_EPOCH_MS"] = str(epoch_ms)
    env["ZEEBE_CHAOS_DISK"] = format_disk_spec(disk_plan)
    # the disarm seam: the drive phase is where the disk lies; probe +
    # quiesce + evidence-drain run with the disk honest again (creating
    # the file flips every worker's controller off on its next tick —
    # same runtime-control pattern as the TCP plane's windows file)
    disarm_file = directory / "disk-chaos-disarm"
    env["ZEEBE_CHAOS_DISK_DISARMFILE"] = str(disarm_file)
    env["ZEEBE_BROKER_DATA_SCRUB_INTERVALMS"] = str(cfg.scrub_interval_ms)
    if cfg.tiering:
        env["ZEEBE_BROKER_DATA_TIERING_ENABLED"] = "true"
        env["ZEEBE_BROKER_DATA_TIERING_PARKAFTERMS"] = str(
            cfg.tiering_park_after_ms)
    env["ZEEBE_BROKER_EXPORTERS_TORTURE_CLASSNAME"] = \
        "zeebe_tpu.testing.consistency.JsonlExporter"
    env["ZEEBE_BROKER_EXPORTERS_TORTURE_ARGS_DIR"] = str(export_dir)

    specs = [WorkerSpec(
        node_id=name,
        cmd=worker_cmd(name, f"127.0.0.1:{contacts[name][1]}", contact_str,
                       "gateway-0", cfg.partitions, cfg.replication,
                       data_dir=str(directory / name)),
        data_dir=str(directory / name)) for name in worker_names]
    supervisor = WorkerSupervisor(specs, env=env, restart_backoff_s=0.2)
    runtime = MultiProcClusterRuntime(
        "gateway-0",
        {m: a for m, a in contacts.items() if m != "gateway-0"},
        partition_count=cfg.partitions, replication_factor=cfg.replication,
        bind=contacts["gateway-0"], supervisor=supervisor)

    history: list[ClientOp] = []
    history_lock = threading.Lock()
    op_seq = [0]
    events: list[dict] = []
    report: dict[str, Any] = {"seed": cfg.seed}

    def clock_ms() -> float:
        return time.time() * 1000.0 - epoch_ms

    def submit_op(partition: int, kind: str, record) -> ClientOp:
        return submit_client_op(
            runtime, partition, kind, record, history=history,
            history_lock=history_lock, op_seq=op_seq, clock_ms=clock_ms,
            timeout_s=cfg.request_timeout_s)

    # workload: plain creates plus message-wait instances that PARK (the
    # tiering path spills them → the cold tier becomes a live bit-rot
    # target), with the Nth request targeting a missing process id so the
    # rejections-terminal invariant stays exercised
    model = (Bpmn.create_executable_process("torture")
             .start_event("s").end_event("e").done())
    wait_model = (Bpmn.create_executable_process("torture_wait")
                  .start_event("s")
                  .intermediate_catch_message(
                      "wait", message_name="torture-msg",
                      correlation_key="=ck")
                  .end_event("e").done())
    deploy = command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
        "resources": [
            {"resourceName": "torture.bpmn",
             "resource": to_bpmn_xml(model)},
            {"resourceName": "torture_wait.bpmn",
             "resource": to_bpmn_xml(wait_model)},
        ]})

    def create_cmd(process_id: str = "torture", variables: dict | None = None):
        return command(ValueType.PROCESS_INSTANCE_CREATION,
                       ProcessInstanceCreationIntent.CREATE,
                       {"bpmnProcessId": process_id, "version": -1,
                        "variables": variables or {}})

    stop_driving = threading.Event()

    def drive(partition: int) -> None:
        n = 0
        while not stop_driving.is_set():
            n += 1
            if cfg.reject_every and n % cfg.reject_every == 0:
                submit_op(partition, "create-missing",
                          create_cmd("no-such-process"))
            elif n % 4 == 0:
                submit_op(partition, "create-wait",
                          create_cmd("torture_wait",
                                     {"ck": f"k-{partition}-{n}"}))
            else:
                submit_op(partition, "create", create_cmd())
            time.sleep(cfg.think_ms / 1000.0)

    probe: dict = {"verified": False, "reason": "not run"}
    corrupted_follower: str | None = None
    leader_at_probe: str | None = None
    try:
        runtime.start()
        boot_deadline = time.monotonic() + 180.0
        while True:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                if time.monotonic() >= boot_deadline:
                    raise
        deploy_op = submit_op(1, "deploy", deploy)
        if deploy_op.outcome != "ack":
            raise RuntimeError(f"deploy failed: {deploy_op.row()}")
        for pid in range(1, cfg.partitions + 1):
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if submit_op(pid, "create", create_cmd()).outcome == "ack":
                    break
                time.sleep(0.25)
            else:
                raise RuntimeError(f"partition {pid} never served a create")

        drive_started = time.monotonic()
        drivers = [threading.Thread(target=drive, args=(pid,), daemon=True,
                                    name=f"driver-{pid}")
                   for pid in range(1, cfg.partitions + 1)]
        for t in drivers:
            t.start()
        for i in range(cfg.kills):
            at = rng.uniform(0.25, 0.7) * cfg.drive_seconds
            delay = drive_started + at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            target = worker_names[rng.randrange(len(worker_names))]
            logger.warning("torture chaos: kill %s at t=%.1fs", target, at)
            events.append({"atMs": clock_ms(), "action": "kill",
                           "target": target})
            supervisor.kill_worker(target)
        remaining = drive_started + cfg.drive_seconds - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        stop_driving.set()
        for t in drivers:
            t.join(timeout=cfg.request_timeout_s + 10)
        # disarm disk chaos: the survival window is over; the probe and
        # the repair-drain phases measure recovery, not fresh damage
        disarm_file.write_text("disarm\n", encoding="utf-8")
        time.sleep(1.0)  # one tick for every worker to notice

        # ---- the repair probe: corrupt a live follower's raft journal ----
        probe, corrupted_follower, leader_at_probe = _corruption_repair_probe(
            runtime, directory, worker_names, events, clock_ms)

        quiesce_deadline = time.monotonic() + 90.0
        while time.monotonic() < quiesce_deadline:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                continue
        _await_exports(export_dir, history, deadline_s=60.0)
        report["gatewayFlight"] = runtime.flight.snapshot()
        report["workerRestarts"] = dict(supervisor.restarts)
    finally:
        try:
            runtime.stop()
        except Exception:  # noqa: BLE001 — teardown must reach evidence
            logger.exception("runtime stop failed")

    run_end_ms = clock_ms()

    # finalize the repair probe offline: the workers are down and their
    # journals flushed — compare the corrupted follower's raft log against
    # the leader's byte-for-byte
    if probe.get("detected") and corrupted_follower:
        convergence = check_follower_reconvergence(
            directory, worker_names, corrupted_follower,
            probe.get("corruptIndex"))
        probe.update(convergence)
        verified = bool(convergence["verified"])
        if not verified:
            # a SECOND repair (an older pre-disarm flip the scrub reached
            # later) may have re-truncated the journal after the probe's
            # reconvergence completed — the repair history proves the
            # refill happened: a later truncate-reconverge whose
            # beforeLastIndex sits PAST the probe's corrupt index
            ci = probe.get("corruptIndex") or 0
            try:
                state = json.loads(
                    (directory / corrupted_follower / "partition-1"
                     / "scrub-state.json").read_text(encoding="utf-8"))
                max_before = max(
                    (r.get("beforeLastIndex", 0)
                     for r in state.get("repairs", [])
                     if r.get("target") == "raft"), default=0)
            except (OSError, ValueError):
                max_before = 0
            probe["reconvergedBeforeLastIndex"] = max_before
            no_mismatch = all(not c["crcMismatches"]
                              for c in convergence["comparisons"])
            verified = bool(no_mismatch and max_before >= ci > 0)
        probe["verified"] = verified

    # ---- offline evidence + checks ----------------------------------------
    logs, violations, skipped_frames = collect_logs_union(
        directory, worker_names, cfg.partitions)
    exports, export_violations, re_exports = collect_exports(export_dir)
    violations += export_violations
    violations += check_consistency(history, logs, exports)
    # chaos-slowed replay triggers adaptive snapshots, whose compaction
    # legally deletes journal prefixes: an acked position under a VALID
    # snapshot horizon that the export stream carries is covered, not lost
    horizons = snapshot_horizons(directory, worker_names, cfg.partitions)
    violations, compaction_waived = waive_compacted_losses(
        violations, history, exports, horizons)

    # observed disk-fault evidence: every CONFIGURED class must have fired
    disk_counts: dict[str, int] = {}
    for counts_path in directory.glob("*/disk-chaos-counts-*.json"):
        try:
            counts = json.loads(counts_path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            continue
        for key, value in counts.items():
            if isinstance(value, int):
                disk_counts[key] = disk_counts.get(key, 0) + value
    flips: list[dict] = []
    for ledger_path in directory.glob("*/disk-bitrot-*.jsonl"):
        try:
            for line in ledger_path.read_text(encoding="utf-8").splitlines():
                if line.strip():
                    flips.append(json.loads(line))
        except (OSError, ValueError):
            continue
    # the ledger is flushed per flip; the counts snapshot is throttled
    # (2s) and a SIGKILL can lose its tail — the ledger is authoritative
    disk_counts["bitrot"] = max(disk_counts.get("bitrot", 0), len(flips))
    for fault_class in disk_plan.configured_classes():
        if not disk_counts.get(fault_class):
            violations.append(
                f"disk-fault class `{fault_class}` configured but never "
                f"observed (0 applied across every worker life) — the "
                f"chaos plane is not reaching the IO seam")

    # TCP chaos sanity (it rides along; it must actually ride)
    tcp_counts: dict[str, int] = {}
    for counts_path in directory.glob("*/chaos-counts-*.json"):
        try:
            counts = json.loads(counts_path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            continue
        for key, value in counts.items():
            if isinstance(value, int):
                tcp_counts[key] = tcp_counts.get(key, 0) + value

    # bit-rot detected-or-repaired accounting (flips collected above)
    scrub_evidence = collect_scrub_evidence(directory)
    bitrot_violations, bitrot_stats = check_bitrot_flips(
        flips, scrub_evidence, run_end_ms)
    violations += bitrot_violations
    scrub_event_total = sum(len(v) for v in scrub_evidence.values())
    if flips and not (bitrot_stats["detected"]
                      or bitrot_stats["repairedVerified"]):
        violations.append(
            "bit-rot flips landed but not one was scrub-detected or "
            "verifiably repaired — the scrubber is not doing its job")

    # repair-probe verdict
    if not probe.get("verified"):
        violations.append(f"follower-corruption repair probe failed: {probe}")

    outcomes: dict[str, int] = {}
    for op in history:
        outcomes[op.outcome] = outcomes.get(op.outcome, 0) + 1
    report.update({
        "workers": cfg.workers,
        "partitions": cfg.partitions,
        "replication": cfg.replication,
        "requests": len(history),
        "outcomes": outcomes,
        "ackedCommands": outcomes.get("ack", 0),
        "kills": len([e for e in events if e["action"] == "kill"]),
        "events": events,
        "diskChaosSpec": format_disk_spec(disk_plan),
        "diskFaultsObserved": disk_counts,
        "tcpChaosObserved": tcp_counts,
        "bitrotFlips": bitrot_stats,
        "bitrotLedger": flips[:50],
        "scrubEvidenceEvents": scrub_event_total,
        "repairProbe": probe,
        "corruptedFollower": corrupted_follower,
        "leaderAtProbe": leader_at_probe,
        "reExportedRecords": re_exports,
        "rottenFramesSkippedOffline": skipped_frames,
        "snapshotHorizons": {str(k): v for k, v in horizons.items()},
        "compactionWaivedLogChecks": compaction_waived,
        "logRecords": {str(p): len(r) for p, r in logs.items()},
        "exportedPositions": {str(p): len(v) for p, v in exports.items()},
        "violations": violations,
        "wallSeconds": round(time.monotonic() - started, 2),
    })
    return report


def _corruption_repair_probe(runtime, directory: Path,
                             worker_names: list[str], events: list[dict],
                             clock_ms) -> tuple[dict, str | None, str | None]:
    """Deliberately flip a byte mid-history in a FOLLOWER's raft journal,
    wait for its scrubber to detect + truncate-repair, drive raft traffic
    so the leader re-converges the suffix, then (post-teardown, by the
    caller) prove the follower's log is CRC-identical to the leader's past
    the corrupted index."""
    # the drive just ended under live chaos (rot-triggered leader
    # step-downs included): wait for leadership to settle before probing
    leader = None
    deadline = time.monotonic() + 45.0
    while time.monotonic() < deadline:
        leader = runtime._leader_of(1)
        if leader is not None:
            break
        time.sleep(0.5)
    if leader is None:
        return {"verified": False, "reason": "no leader for partition 1"}, \
            None, None
    followers = [w for w in worker_names if w != leader]
    if not followers:
        return {"verified": False, "reason": "no follower to corrupt"}, \
            None, leader
    follower = followers[0]
    raft_dir = directory / follower / "partition-1" / "raft" / "raft-log"
    segments = sorted(raft_dir.glob("journal-*.log"))
    if not segments:
        return {"verified": False,
                "reason": f"no raft segments under {raft_dir}"}, \
            follower, leader
    target = segments[-1]
    size = target.stat().st_size
    if size < 64:
        return {"verified": False, "reason": "raft journal too small"}, \
            follower, leader
    # flip mid-history: past the 24-byte segment header, inside the first
    # half of the file so plenty of committed suffix must re-converge
    offset = 24 + (size - 24) // 3
    with open(target, "r+b") as f:
        f.seek(offset)
        old = f.read(1)
        f.seek(offset)
        f.write(bytes((old[0] ^ 0xFF,)))
    events.append({"atMs": clock_ms(), "action": "corrupt-follower-journal",
                   "target": follower, "file": str(target),
                   "offset": offset})
    # wait for the follower's scrubber to detect + repair
    scrub_state = directory / follower / "partition-1" / "scrub-state.json"
    corrupt_index = None
    deadline = time.monotonic() + 45.0
    detected = False
    while time.monotonic() < deadline:
        try:
            state = json.loads(scrub_state.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            time.sleep(0.25)
            continue
        for ev in state.get("detections", []):
            if ev.get("target") == "raft" and \
                    str(raft_dir) == ev.get("directory"):
                corrupt_index = ev.get("corruptIndex")
                detected = True
        repaired = [ev for ev in state.get("repairs", [])
                    if ev.get("target") == "raft"]
        if repaired and not detected:
            # a live raft read tripped on the flip before the scrubber's
            # slice reached it: the repair evidence alone proves detection
            # (same truncate-reconverge seam, different detector)
            detected = True
            corrupt_index = repaired[-1].get("afterLastIndex", 0) + 1
        if detected and repaired:
            break
        time.sleep(0.25)
    if not detected:
        return {"verified": False,
                "reason": "follower scrubber never detected the flip",
                "file": str(target), "offset": offset}, follower, leader
    # wait for replication to re-converge the truncated suffix (heartbeats
    # back the leader up to the follower's surviving prefix and resend);
    # poll the on-disk valid extent — append-only frames make a live
    # tolerant walk safe — because an OLDER pre-disarm flip elsewhere in
    # the journal can trigger a SECOND repair at any moment
    reconverge_deadline = time.monotonic() + 30.0
    while time.monotonic() < reconverge_deadline:
        valid = journal_dir_records_tolerant(raft_dir)
        if corrupt_index is not None and valid \
                and max(valid) >= corrupt_index:
            break
        time.sleep(0.5)
    return {"verified": None,  # finalized offline by the caller
            "detected": True, "corruptIndex": corrupt_index,
            "file": str(target), "offset": offset}, follower, leader


def main(argv: list[str] | None = None) -> int:  # pragma: no cover — manual
    from zeebe_tpu.testing.serving import gate_cli_main

    return gate_cli_main(
        "zeebe-tpu-torture", TortureConfig(),
        TortureConfig(drive_seconds=90.0, kills=3), run_torture, argv)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
