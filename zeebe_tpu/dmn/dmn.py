"""DMN 1.x decision tables + literal expressions over FEEL-lite.

Reference: dmn/src/main/java/io/camunda/zeebe/dmn/impl/DmnScalaDecisionEngine.java
(parse + evaluate via camunda-dmn), EvaluatedDecision/EvaluatedInput/
EvaluatedOutput/MatchedRule audit records (dmn/…/DecisionEvaluationResult).

Supported: decision tables with hit policies UNIQUE, FIRST, ANY, PRIORITY,
RULE ORDER, OUTPUT ORDER, COLLECT (+ SUM/MIN/MAX/COUNT aggregation), literal
expression decisions, decision requirement graphs (required decisions are
evaluated first, their results bound by decision id and name), and FEEL unary
tests: "-", comparisons, intervals, disjunction lists, negation, expression
equality, and "?"-referencing tests.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Any, Callable

from zeebe_tpu.feel.feel import FeelError, parse_feel

_NS = {
    "dmn": "https://www.omg.org/spec/DMN/20191111/MODEL/",
}
# older DMN namespaces seen in the wild (the reference accepts all of them)
_DMN_NAMESPACES = [
    "https://www.omg.org/spec/DMN/20191111/MODEL/",
    "http://www.omg.org/spec/DMN/20180521/MODEL/",
    "http://www.omg.org/spec/DMN/20151101/dmn.xsd",
]


class DmnParseError(Exception):
    pass


class DmnEvalError(Exception):
    pass


@dataclass
class _Input:
    input_id: str
    label: str
    expression_text: str
    expression: Any  # compiled feel


@dataclass
class _Output:
    output_id: str
    name: str
    label: str


@dataclass
class _Rule:
    rule_id: str
    input_entries: list[str]
    output_entries: list[str]
    tests: list[Callable[[Any, dict], bool]] = field(default_factory=list)
    outputs: list[Any] = field(default_factory=list)  # compiled feel


@dataclass
class ParsedDecision:
    decision_id: str
    name: str
    kind: str  # "decisionTable" | "literalExpression"
    hit_policy: str = "UNIQUE"
    aggregation: str = ""
    inputs: list[_Input] = field(default_factory=list)
    outputs: list[_Output] = field(default_factory=list)
    rules: list[_Rule] = field(default_factory=list)
    literal: Any = None  # compiled feel for literalExpression
    result_name: str | None = None  # variable name for literal decisions
    required: list[str] = field(default_factory=list)  # required decision ids


@dataclass
class ParsedDrg:
    drg_id: str
    name: str
    namespace: str
    decisions: dict[str, ParsedDecision] = field(default_factory=dict)

    def decision_ids(self) -> list[str]:
        return list(self.decisions)


@dataclass
class EvaluatedInput:
    input_id: str
    input_name: str
    input_value: Any


@dataclass
class EvaluatedOutput:
    output_id: str
    output_name: str
    output_value: Any


@dataclass
class MatchedRule:
    rule_id: str
    rule_index: int
    evaluated_outputs: list[EvaluatedOutput]


@dataclass
class EvaluatedDecision:
    decision_id: str
    decision_name: str
    decision_type: str
    output: Any
    evaluated_inputs: list[EvaluatedInput] = field(default_factory=list)
    matched_rules: list[MatchedRule] = field(default_factory=list)


@dataclass
class DecisionEvaluationResult:
    """The audit trail the engine writes into DECISION_EVALUATION records."""

    output: Any = None
    failed: bool = False
    failure_message: str = ""
    failed_decision_id: str = ""
    evaluated_decisions: list[EvaluatedDecision] = field(default_factory=list)


def _strip(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _text_of(el: ET.Element | None) -> str:
    if el is None:
        return ""
    # <text> child or direct text
    for child in el:
        if _strip(child.tag) == "text":
            return (child.text or "").strip()
    return (el.text or "").strip()


def parse_dmn_xml(xml: str) -> ParsedDrg:
    """Parse one <definitions> document into a decision requirements graph."""
    try:
        root = ET.fromstring(xml)
    except ET.ParseError as exc:
        raise DmnParseError(f"invalid DMN XML: {exc}") from exc
    if _strip(root.tag) != "definitions":
        raise DmnParseError(f"expected <definitions>, got <{_strip(root.tag)}>")
    drg = ParsedDrg(
        drg_id=root.get("id", "definitions"),
        name=root.get("name", root.get("id", "definitions")),
        namespace=root.get("namespace", ""),
    )
    for el in root:
        if _strip(el.tag) != "decision":
            continue
        decision = _parse_decision(el)
        drg.decisions[decision.decision_id] = decision
    if not drg.decisions:
        raise DmnParseError("no <decision> elements in definitions")
    return drg


def _parse_decision(el: ET.Element) -> ParsedDecision:
    decision_id = el.get("id") or ""
    if not decision_id:
        raise DmnParseError("decision without id")
    name = el.get("name", decision_id)
    required: list[str] = []
    table = None
    literal = None
    result_name = None
    for child in el:
        tag = _strip(child.tag)
        if tag == "informationRequirement":
            for req in child:
                if _strip(req.tag) == "requiredDecision":
                    href = req.get("href", "")
                    required.append(href.lstrip("#"))
        elif tag == "decisionTable":
            table = child
        elif tag == "literalExpression":
            literal = child
        elif tag == "variable":
            result_name = child.get("name")
    if table is not None:
        decision = _parse_decision_table(decision_id, name, table)
    elif literal is not None:
        text = _text_of(literal)
        decision = ParsedDecision(
            decision_id, name, "literalExpression",
            literal=_compile(text, decision_id),
            result_name=result_name,
        )
    else:
        raise DmnParseError(
            f"decision '{decision_id}' has neither decisionTable nor literalExpression"
        )
    decision.required = required
    return decision


def _parse_decision_table(decision_id: str, name: str, table: ET.Element) -> ParsedDecision:
    decision = ParsedDecision(
        decision_id, name, "decisionTable",
        hit_policy=table.get("hitPolicy", "UNIQUE").upper().replace(" ", "_"),
        aggregation=table.get("aggregation", "").upper(),
    )
    for child in table:
        tag = _strip(child.tag)
        if tag == "input":
            expr_el = next((c for c in child if _strip(c.tag) == "inputExpression"), None)
            text = _text_of(expr_el)
            decision.inputs.append(_Input(
                input_id=child.get("id", f"input_{len(decision.inputs)}"),
                label=child.get("label", text),
                expression_text=text,
                expression=_compile(text, decision_id),
            ))
        elif tag == "output":
            decision.outputs.append(_Output(
                output_id=child.get("id", f"output_{len(decision.outputs)}"),
                name=child.get("name", child.get("label", f"output_{len(decision.outputs)}")),
                label=child.get("label", ""),
            ))
        elif tag == "rule":
            input_entries = []
            output_entries = []
            for entry in child:
                etag = _strip(entry.tag)
                if etag == "inputEntry":
                    input_entries.append(_text_of(entry))
                elif etag == "outputEntry":
                    output_entries.append(_text_of(entry))
            rule = _Rule(child.get("id", f"rule_{len(decision.rules)}"),
                         input_entries, output_entries)
            rule.tests = [parse_unary_tests(t, decision_id) for t in input_entries]
            rule.outputs = [_compile(t, decision_id) for t in output_entries]
            decision.rules.append(rule)
    if not decision.outputs:
        raise DmnParseError(f"decision table '{decision_id}' has no outputs")
    for rule in decision.rules:
        if len(rule.input_entries) != len(decision.inputs) or \
                len(rule.output_entries) != len(decision.outputs):
            raise DmnParseError(
                f"rule '{rule.rule_id}' arity mismatch in decision '{decision_id}'"
            )
    return decision


def _compile(text: str, decision_id: str):
    if not text:
        return None
    try:
        return parse_feel(text)
    except FeelError as exc:
        raise DmnParseError(
            f"invalid FEEL in decision '{decision_id}': {text!r}: {exc}"
        ) from exc


# -- unary tests ---------------------------------------------------------------

_CMP_OPS = ("<=", ">=", "<", ">")


def parse_unary_tests(text: str, decision_id: str = "?") -> Callable[[Any, dict], bool]:
    """FEEL unary tests → predicate(input_value, context).

    Grammar subset (reference: FEEL spec §7.3.2, camunda-feel unary tests):
    ``-`` | test{, test} | not(tests) | <op> endpoint | [a..b] | expression
    (equality, or a boolean expression over ``?``).
    """
    text = (text or "").strip()
    if text in ("", "-"):
        return lambda value, ctx: True
    # disjunction first: 'not("a"), not("b")' is a list of two negations,
    # not one big not(...) wrapper
    parts = _split_top_level(text)
    if len(parts) > 1:
        tests = [parse_unary_tests(p, decision_id) for p in parts]
        return lambda value, ctx: any(t(value, ctx) for t in tests)
    if text.startswith("not(") and text.endswith(")"):
        inner = parse_unary_tests(text[4:-1], decision_id)
        return lambda value, ctx: not inner(value, ctx)
    return _parse_single_test(text, decision_id)


def _split_top_level(text: str) -> list[str]:
    parts, depth, start, in_str = [], 0, 0, False
    for i, ch in enumerate(text):
        if ch == '"':
            in_str = not in_str
        elif not in_str:
            if ch in "([{":
                depth += 1
            elif ch in ")]}":
                depth -= 1
            elif ch == "," and depth == 0:
                parts.append(text[start:i].strip())
                start = i + 1
    parts.append(text[start:].strip())
    return [p for p in parts if p]


def _parse_single_test(text: str, decision_id: str) -> Callable[[Any, dict], bool]:
    # interval [a..b], (a..b), ]a..b[
    if text[0] in "[(]" and ".." in text and text[-1] in "])[":
        lo_closed = text[0] == "["
        hi_closed = text[-1] == "]"
        lo_text, hi_text = text[1:-1].split("..", 1)
        lo = _compile(lo_text.strip(), decision_id)
        hi = _compile(hi_text.strip(), decision_id)

        def interval(value, ctx):
            lo_v = _eval(lo, ctx)
            hi_v = _eval(hi, ctx)
            try:
                if value is None:
                    return False
                above = value >= lo_v if lo_closed else value > lo_v
                below = value <= hi_v if hi_closed else value < hi_v
                return above and below
            except TypeError:
                return False

        return interval
    for op in _CMP_OPS:
        if text.startswith(op):
            endpoint = _compile(text[len(op):].strip(), decision_id)

            def cmp(value, ctx, op=op, endpoint=endpoint):
                other = _eval(endpoint, ctx)
                try:
                    if value is None:
                        return False
                    return {
                        "<": value < other, "<=": value <= other,
                        ">": value > other, ">=": value >= other,
                    }[op]
                except TypeError:
                    return False

            return cmp
    if "?" in _strip_strings(text):
        # boolean expression over the input value, e.g. "? * 2 > 10";
        # substitute only outside string literals ('? = "N/A?"' keeps the
        # question mark inside the string)
        expr = _compile(_replace_outside_strings(text, "?", "__input__"),
                        decision_id)

        def qmark(value, ctx):
            return bool(_eval(expr, {**ctx, "__input__": value}))

        return qmark
    # plain expression: equality (or truthiness for booleans with null input)
    expr = _compile(text, decision_id)

    def eq(value, ctx):
        return _eval(expr, ctx) == value

    return eq


def _replace_outside_strings(text: str, needle: str, replacement: str) -> str:
    out, in_str = [], False
    for ch in text:
        if ch == '"':
            in_str = not in_str
            out.append(ch)
        elif not in_str and ch == needle:
            out.append(replacement)
        else:
            out.append(ch)
    return "".join(out)


def _strip_strings(text: str) -> str:
    out, in_str = [], False
    for ch in text:
        if ch == '"':
            in_str = not in_str
        elif not in_str:
            out.append(ch)
    return "".join(out)


def _eval(expr, ctx: dict):
    if expr is None:
        return None
    return expr.evaluate(ctx)


# -- evaluation ----------------------------------------------------------------


class DecisionEngine:
    """Evaluate a decision (and its required decisions) against a variable
    context; returns the full audit result."""

    def evaluate(self, drg: ParsedDrg, decision_id: str,
                 context: dict[str, Any]) -> DecisionEvaluationResult:
        result = DecisionEvaluationResult()
        if decision_id not in drg.decisions:
            result.failed = True
            result.failed_decision_id = decision_id
            result.failure_message = (
                f"no decision found for id '{decision_id}' in '{drg.drg_id}'"
            )
            return result
        ctx = dict(context)
        try:
            output = self._evaluate_decision(
                drg, drg.decisions[decision_id], ctx, result, set(), {}
            )
            result.output = output
        except DmnEvalError as exc:
            result.failed = True
            result.failed_decision_id = exc.args[1] if len(exc.args) > 1 else decision_id
            result.failure_message = str(exc.args[0])
        return result

    def _evaluate_decision(self, drg: ParsedDrg, decision: ParsedDecision,
                           ctx: dict, result: DecisionEvaluationResult,
                           visiting: set[str], memo: dict[str, Any]) -> Any:
        if decision.decision_id in memo:
            # shared requirement in a diamond-shaped DRG: evaluate once,
            # audit once (re-evaluation would duplicate both)
            return memo[decision.decision_id]
        if decision.decision_id in visiting:
            raise DmnEvalError(
                f"cyclic decision requirement at '{decision.decision_id}'",
                decision.decision_id,
            )
        visiting.add(decision.decision_id)
        # required decisions first; outputs bound by id and by name
        for req_id in decision.required:
            req = drg.decisions.get(req_id)
            if req is None:
                raise DmnEvalError(
                    f"required decision '{req_id}' not found", decision.decision_id
                )
            value = self._evaluate_decision(drg, req, ctx, result, visiting, memo)
            ctx[req.decision_id] = value
            ctx[req.name] = value
        visiting.discard(decision.decision_id)

        if decision.kind == "literalExpression":
            try:
                output = _eval(decision.literal, ctx)
            except FeelError as exc:
                raise DmnEvalError(str(exc), decision.decision_id) from exc
            result.evaluated_decisions.append(EvaluatedDecision(
                decision.decision_id, decision.name, decision.kind, output,
            ))
        else:
            output = self._evaluate_table(decision, ctx, result)
        memo[decision.decision_id] = output
        return output

    def _evaluate_table(self, decision: ParsedDecision, ctx: dict,
                        result: DecisionEvaluationResult) -> Any:
        audit = EvaluatedDecision(
            decision.decision_id, decision.name, decision.kind, None,
        )
        result.evaluated_decisions.append(audit)
        input_values = []
        for inp in decision.inputs:
            try:
                value = _eval(inp.expression, ctx)
            except FeelError as exc:
                raise DmnEvalError(
                    f"input '{inp.expression_text}' failed: {exc}",
                    decision.decision_id,
                ) from exc
            input_values.append(value)
            audit.evaluated_inputs.append(
                EvaluatedInput(inp.input_id, inp.label, value)
            )
        matched: list[tuple[int, _Rule, dict]] = []
        for index, rule in enumerate(decision.rules):
            try:
                hit = all(
                    test(value, ctx)
                    for test, value in zip(rule.tests, input_values)
                )
            except FeelError as exc:
                raise DmnEvalError(
                    f"rule '{rule.rule_id}' failed: {exc}", decision.decision_id
                ) from exc
            if not hit:
                continue
            outputs = {}
            evaluated_outputs = []
            for out_def, out_expr in zip(decision.outputs, rule.outputs):
                try:
                    out_val = _eval(out_expr, ctx)
                except FeelError as exc:
                    raise DmnEvalError(
                        f"output of rule '{rule.rule_id}' failed: {exc}",
                        decision.decision_id,
                    ) from exc
                outputs[out_def.name] = out_val
                evaluated_outputs.append(
                    EvaluatedOutput(out_def.output_id, out_def.name, out_val)
                )
            matched.append((index, rule, outputs))
            audit.matched_rules.append(
                MatchedRule(rule.rule_id, index + 1, evaluated_outputs)
            )
            if decision.hit_policy in ("FIRST",):
                break
        output = self._apply_hit_policy(decision, matched)
        audit.output = output
        return output

    def _apply_hit_policy(self, decision: ParsedDecision,
                          matched: list[tuple[int, _Rule, dict]]) -> Any:
        single_output = len(decision.outputs) == 1
        out_name = decision.outputs[0].name if single_output else None

        def shape(outputs: dict) -> Any:
            return outputs[out_name] if single_output else outputs

        policy = decision.hit_policy
        if not matched:
            return None
        if policy in ("UNIQUE",):
            if len(matched) > 1:
                raise DmnEvalError(
                    f"UNIQUE hit policy violated in '{decision.decision_id}': "
                    f"{len(matched)} rules matched", decision.decision_id,
                )
            return shape(matched[0][2])
        if policy == "ANY":
            values = [shape(m[2]) for m in matched]
            if any(v != values[0] for v in values):
                raise DmnEvalError(
                    f"ANY hit policy violated in '{decision.decision_id}': "
                    "matched rules disagree", decision.decision_id,
                )
            return values[0]
        if policy == "FIRST" or policy == "PRIORITY":
            # PRIORITY without output value ordering degrades to first-match
            return shape(matched[0][2])
        if policy in ("RULE_ORDER", "OUTPUT_ORDER"):
            return [shape(m[2]) for m in matched]
        if policy == "COLLECT":
            values = [shape(m[2]) for m in matched]
            agg = decision.aggregation
            if not agg or agg == "LIST":
                return values
            if agg == "COUNT":
                return len(values)
            non_numeric = [v for v in values
                           if not isinstance(v, (int, float)) or isinstance(v, bool)]
            if non_numeric:
                # a modeling error must surface as an evaluation failure, not a
                # plausible-looking partial aggregate (reference behavior)
                raise DmnEvalError(
                    f"COLLECT {agg} over non-numeric outputs {non_numeric!r} in "
                    f"'{decision.decision_id}'", decision.decision_id,
                )
            if agg == "SUM":
                return sum(values)
            if agg == "MIN":
                return min(values) if values else None
            if agg == "MAX":
                return max(values) if values else None
        return shape(matched[0][2])
