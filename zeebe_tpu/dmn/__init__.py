"""DMN decision engine (SURVEY §2.9 dmn/).

Reference: dmn/src/main/java/io/camunda/zeebe/dmn/ — DecisionEngine facade
(DmnScalaDecisionEngine), ParsedDecisionRequirementsGraph, DecisionEvaluation
result + audit log (EvaluatedDecision/Input/Output, MatchedRule). Re-built on
the in-repo FEEL-lite instead of the external Scala engine.
"""

from zeebe_tpu.dmn.dmn import (
    DecisionEngine,
    DecisionEvaluationResult,
    DmnParseError,
    ParsedDecision,
    ParsedDrg,
    parse_dmn_xml,
)

__all__ = [
    "DecisionEngine",
    "DecisionEvaluationResult",
    "DmnParseError",
    "ParsedDecision",
    "ParsedDrg",
    "parse_dmn_xml",
]
