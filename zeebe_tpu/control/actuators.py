"""Typed actuators: the ONLY write path from controllers to runtime knobs.

An :class:`Actuator` owns one live runtime parameter. It declares, up
front, everything an operator needs to trust it (the Autopilot posture —
bounded actuation plus a full audit trail):

- **hard bounds** (``min_value``/``max_value``): the knob provably never
  leaves them — ``apply`` clamps *before* anything touches the runtime,
  and ``min_seen``/``max_seen`` record the lifetime envelope as evidence;
- **max step per tick** (``max_step``): one bad signal sample can move the
  knob at most one bounded step, never slam it across its range;
- **hysteresis band** (``hold_band``): proposals within the band of the
  current value hold — controllers oscillating around a set point do not
  thrash the runtime;
- **stale-signal fallback** (``static``): when the loop's telemetry goes
  quiet the actuator walks the knob back toward the statically configured
  value, one bounded step per tick — a dead sensor degrades to exactly
  the hand-tuned deployment, never to the last adapted extreme.

Every value change is a ``control_adjust`` flight event and a
``zeebe_control_*`` metric (zeebe_tpu/control/audit.py). The zlint
``control-actuation-discipline`` rule statically pins this as the single
write path; the runtime sanitizer (``ZEEBE_SANITIZE=1``) additionally
asserts ``apply`` stays on the pump thread that first used it.
"""

from __future__ import annotations

from typing import Callable

from zeebe_tpu.control import audit


class Actuator:
    """One knob: read/write seams plus declared bounds and pacing."""

    def __init__(self, controller: str, knob: str,
                 read: Callable[[], float],
                 write: Callable[[float], None], *,
                 min_value: float, max_value: float, max_step: float,
                 static: float, hold_band: float = 0.0,
                 integer: bool = False) -> None:
        if not min_value <= static <= max_value:
            raise ValueError(
                f"{controller}/{knob}: static {static} outside "
                f"[{min_value}, {max_value}]")
        self.controller = controller
        self.knob = knob
        self._read = read
        self._write = write
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        self.max_step = float(max_step)
        self.static = float(static)
        self.hold_band = float(hold_band)
        self.integer = integer
        self.adjustments = 0
        self.holds = 0
        self.last_reason: str | None = None
        self.last_adjust_ms: int | None = None
        # the plane OWNS this knob from here on: a configured value outside
        # the declared bounds is clamped into them at construction and
        # written through — otherwise the runtime would sit out of bounds
        # forever (the hold band would swallow every proposal toward it)
        # while the snapshot reported the coerced value as evidence
        raw = float(read())
        current = self._coerce(raw)
        if current != raw:
            self._write(current)
        # lifetime envelope: with apply() the single write path and the
        # clamp above it, these two numbers ARE the bounds proof the
        # autotune gate asserts ("provably inside [min,max] every tick")
        self.min_seen = current
        self.max_seen = current

    # -- value plumbing --------------------------------------------------------

    def _coerce(self, value: float) -> float:
        value = min(max(value, self.min_value), self.max_value)
        if self.integer:
            value = float(int(round(value)))
        return value

    def read(self) -> float:
        return float(self._read())

    # -- the single write path -------------------------------------------------

    def apply(self, desired: float, reason: str,
              signals: dict | None = None, *, flight=None,
              partition_id: int = 0, now_ms: int | None = None) -> float:
        """Move the knob toward ``desired``: clamp to the declared bounds,
        rate-limit to ``max_step`` per call, hold inside the hysteresis
        band. Returns the (possibly unchanged) applied value; a change is
        a ``control_adjust`` audit record."""
        current = self._coerce(self.read())
        if desired != desired:  # NaN sentinel: drift toward the static value
            desired = self.static
        target = self._coerce(desired)
        if abs(target - current) <= self.hold_band:
            self.holds += 1
            return current
        step = max(min(target - current, self.max_step), -self.max_step)
        value = self._coerce(current + step)
        if value == current:
            self.holds += 1
            return current
        self._write(value)
        self.adjustments += 1
        self.last_reason = reason
        self.last_adjust_ms = now_ms
        self.min_seen = min(self.min_seen, value)
        self.max_seen = max(self.max_seen, value)
        audit.record_adjust(flight, partition_id, self.controller, self.knob,
                            before=current, after=value, reason=reason,
                            signals=signals)
        return value

    def fall_back(self, reason: str, *, flight=None,
                  now_ms: int | None = None) -> float:
        """Stale-signal posture: one bounded step back toward the static
        configured value."""
        current = self._coerce(self.read())
        if current == self._coerce(self.static):
            return current
        audit.note_stale(self.controller)
        return self.apply(self.static, f"stale-signal: {reason}",
                          {"fallbackTo": self.static}, flight=flight,
                          now_ms=now_ms)

    def sync(self) -> None:
        """Re-assert the current value through the write seam (no audit):
        lets a broker-wide actuator propagate its value onto partitions
        created after the last adjustment."""
        self._write(self._coerce(self.read()))

    def snapshot(self) -> dict:
        value = self._coerce(self.read())
        return {
            "knob": self.knob,
            "value": value,
            "static": self.static,
            "min": self.min_value,
            "max": self.max_value,
            "maxStepPerTick": self.max_step,
            "holdBand": self.hold_band,
            "minSeen": min(self.min_seen, value),
            "maxSeen": max(self.max_seen, value),
            "adjustments": self.adjustments,
            "holds": self.holds,
            "lastReason": self.last_reason,
            "lastAdjustMs": self.last_adjust_ms,
        }
