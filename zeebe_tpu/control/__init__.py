"""Closed-loop control plane (ISSUE 12, ROADMAP item 5): a self-tuning
runtime driven by the observability plane.

PRs 3–5 built the *measurement* half (Dapper tracing, the Gorilla
time-series store, GWP profiling); this package is the half that *acts*:
per-broker controllers tick off the pump, read distilled series from the
time-series store, and adjust live runtime knobs through a typed,
bounded, fully audited :class:`Actuator` framework — Google Autopilot's
posture (Rzadca et al., EuroSys 2020): conservative feedback over
windowed telemetry, bounded actuation, and an audit trail operators can
replay. See docs/control.md.
"""

from zeebe_tpu.control.actuators import Actuator
from zeebe_tpu.control.audit import note_stale, record_adjust
from zeebe_tpu.control.controllers import (
    CoalescingController,
    Controller,
    JournalFlushController,
    RoutingController,
    SignalReader,
    TieringController,
)
from zeebe_tpu.control.plane import ControlCfg, ControlPlane, maybe_build_plane

__all__ = [
    "Actuator",
    "CoalescingController",
    "ControlCfg",
    "ControlPlane",
    "Controller",
    "JournalFlushController",
    "RoutingController",
    "SignalReader",
    "TieringController",
    "maybe_build_plane",
    "note_stale",
    "record_adjust",
]
