"""The ``control_adjust`` audit vocabulary: ONE record shape for every
closed feedback loop in the runtime.

Autopilot's operators-trust-the-machine argument (Rzadca et al., EuroSys
2020) is mostly an *audit* argument: an autonomic system is adoptable only
when every decision it takes is attributable — what moved, from what to
what, on which signal, and why. This module is that contract for every
loop in this tree: the ``zeebe_tpu/control`` actuators, the PR 6 adaptive
snapshot scheduler, and the PR 11 admission shed ladder all record their
decisions through :func:`record_adjust`, so ``/flight`` dumps,
``/control``, and ``cli top``'s CONTROL section render every closed loop
in one place with one schema.

Event shape (flight-recorder kind ``control_adjust``)::

    {"kind": "control_adjust", "controller": "journal-flush",
     "knob": "raft.flushDelayMs", "before": 0.0, "after": 2.0,
     "reason": "flush utilization 0.52 over high watermark",
     "signals": {"flushPerSec": 410.2, "flushP50Ms": 1.3}}

Metric families (registered at import so the metrics-doc scenario and the
sampler see them without waiting for the first adjustment):

- ``zeebe_control_adjustments_total{controller,knob}``
- ``zeebe_control_knob_value{controller,knob}`` (the knob's live value)
- ``zeebe_control_signal_stale_total{controller}`` (fallback-to-static
  episodes: the loop's sensor went quiet and the actuator walked the knob
  back to its configured value)
"""

from __future__ import annotations

from zeebe_tpu.utils.metrics import REGISTRY as _REG

_M_ADJUSTMENTS = _REG.counter(
    "control_adjustments_total",
    "feedback-loop decisions recorded under the control_adjust vocabulary "
    "(control-plane actuators, the adaptive snapshot scheduler, the "
    "admission shed ladder)", ("controller", "knob"))
_M_KNOB_VALUE = _REG.gauge(
    "control_knob_value",
    "live value of a controller-owned runtime knob (units are the knob's "
    "own: ms, bytes, instances)", ("controller", "knob"))
_M_SIGNAL_STALE = _REG.counter(
    "control_signal_stale_total",
    "control ticks that fell back toward the static configured value "
    "because the loop's telemetry signal was stale or absent",
    ("controller",))


def record_adjust(flight, partition_id: int, controller: str, knob: str,
                  before, after, reason: str,
                  signals: dict | None = None) -> None:
    """One feedback-loop decision: a ``control_adjust`` flight event plus
    the ``zeebe_control_*`` metrics. ``flight`` may be None (loops built
    without a recorder still count in metrics)."""
    _M_ADJUSTMENTS.labels(controller, knob).inc()
    if isinstance(after, (int, float)):
        _M_KNOB_VALUE.labels(controller, knob).set(float(after))
    if flight is not None:
        flight.record(partition_id, "control_adjust", controller=controller,
                      knob=knob, before=before, after=after, reason=reason,
                      signals=dict(signals or {}))


def note_stale(controller: str) -> None:
    _M_SIGNAL_STALE.labels(controller).inc()
