"""ControlPlane: the per-broker (and per-worker) closed control loop.

Ticks off the broker's control pump — after the metrics sampler, so every
tick sees at-most-one-tick-old distilled telemetry — and drives the knob
surface through the typed :class:`Actuator` framework. Disabled
(``ZEEBE_CONTROL_ENABLED=0``) the plane is simply not constructed:
``broker.control is None`` is the whole disabled hot path, exactly the
metrics/profiling planes' cost contract.

The plane also *aggregates* the runtime's pre-existing one-off feedback
loops (the PR 6 adaptive snapshot scheduler, the PR 11 admission shed
ladder) as read-only ``loops`` entries in its snapshot: their decisions
already land in the shared ``control_adjust`` vocabulary
(zeebe_tpu/control/audit.py), so ``/control``, ``/cluster/status`` and
``cli top``'s CONTROL section show every closed loop in one place.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable

from zeebe_tpu.control.actuators import Actuator
from zeebe_tpu.control.controllers import (
    CoalescingController,
    Controller,
    JournalFlushController,
    RoutingController,
    SignalReader,
    TieringController,
)

#: hard bounds + pacing per shipped knob (docs/control.md documents them).
#: The coalescing cap covers the window that gathers TARGET_BATCH commands
#: at the LOW_RATE floor — the window a burst actually wants shrinks as
#: the rate grows (target/rate), so the cap binds at moderate rates only.
COALESCE_WINDOW_MAX_MS = 25.0
COALESCE_WINDOW_STEP_MS = 5.0
#: the flush-delay cap is deliberately tighter than the coalescing cap:
#: every deferred fsync delays a COMMIT (acks wait for it), so past a few
#: milliseconds the latency cost outruns the amortization gain
FLUSH_DELAY_MAX_MS = 8.0
FLUSH_DELAY_STEP_MS = 2.0
PARK_AFTER_MIN_MS = 1_000.0
PARK_AFTER_MAX_MS = 600_000.0
PARK_AFTER_STEP_MS = 5_000.0
SPILL_BATCH_MIN = 32.0
SPILL_BATCH_MAX = 2_048.0
SPILL_BATCH_STEP = 128.0
ROUTE_THRESHOLD_MAX_MS = 250.0
ROUTE_THRESHOLD_STEP_MS = 25.0


@dataclasses.dataclass
class ControlCfg:
    """``ZEEBE_CONTROL_*`` knobs."""

    enabled: bool = True
    #: controller tick cadence (decisions are paced — one bounded actuator
    #: step per tick per controller)
    interval_ms: int = 500
    #: the journal-flush controller's ack-latency SLO (ms)
    ack_p99_target_ms: float = 250.0
    #: the tiering controller's RSS set point (bytes); 0 derives 80% of
    #: the rss_watermark alert's bound (ZEEBE_ALERT_RSSWATERMARKBYTES)
    rss_target_bytes: int = 0
    #: a distilled sample older than this is stale → fallback-to-static
    signal_max_age_ms: int = 15_000

    @classmethod
    def from_env(cls, env: dict | None = None) -> "ControlCfg":
        env = os.environ if env is None else env

        def _f(name: str, default: float) -> float:
            try:
                return float(env.get(name, ""))
            except ValueError:
                return default

        cfg = cls()
        cfg.enabled = env.get("ZEEBE_CONTROL_ENABLED", "true").lower() in (
            "1", "true", "yes")
        cfg.interval_ms = int(_f("ZEEBE_CONTROL_INTERVALMS", 500))
        cfg.ack_p99_target_ms = _f("ZEEBE_CONTROL_ACKP99TARGETMS", 250.0)
        cfg.rss_target_bytes = int(_f("ZEEBE_CONTROL_RSSTARGETBYTES", 0))
        if cfg.rss_target_bytes <= 0:
            cfg.rss_target_bytes = int(
                0.8 * _f("ZEEBE_ALERT_RSSWATERMARKBYTES", float(4 << 30)))
        return cfg


class ControlPlane:
    """Controllers + actuators over one broker's runtime objects."""

    def __init__(self, broker, cfg: ControlCfg | None = None) -> None:
        self.broker = broker
        self.cfg = cfg or ControlCfg.from_env()
        self.flight = getattr(broker, "flight_recorder", None)
        self.clock_millis = broker.clock_millis
        self.reader = SignalReader(broker.timeseries, broker.clock_millis,
                                   max_age_ms=self.cfg.signal_max_age_ms)
        self.controllers: list[Controller] = []
        self._last_tick_ms = 0
        self.ticks = 0
        #: read-only aggregated loops: name -> snapshot fn (the snapshot
        #: scheduler and admission ladder register here so every closed
        #: loop renders in one CONTROL view)
        self._loops: dict[str, Callable[[], dict]] = {}
        self._build_default_controllers()
        self._loops["snapshot-scheduler"] = self._snapshot_scheduler_loop
        if self.flight is not None:
            self.flight.add_context_provider(
                lambda: {"control": self.snapshot()})

    # -- wiring ----------------------------------------------------------------

    def _build_default_controllers(self) -> None:
        broker = self.broker
        cfg = self.cfg

        # journal-flush: ONE broker-wide knob written through to every
        # local partition's raft node (sync() re-propagates onto
        # partitions created after the last adjustment)
        static_delay_ms = float(
            getattr(broker.cfg, "log_flush_delay_ms", 0) or 0)
        self._flush_delay_ms = static_delay_ms

        def read_flush() -> float:
            return self._flush_delay_ms

        def write_flush(value: float) -> None:
            self._flush_delay_ms = value
            for partition in list(broker.partitions.values()):
                partition.raft.flush_interval_s = value / 1000.0

        self.add_controller(JournalFlushController(
            [Actuator(JournalFlushController.name,
                      JournalFlushController.KNOB,
                      read_flush, write_flush,
                      min_value=0.0, max_value=FLUSH_DELAY_MAX_MS,
                      max_step=FLUSH_DELAY_STEP_MS,
                      static=min(static_delay_ms, FLUSH_DELAY_MAX_MS),
                      hold_band=0.5)],
            ack_p99_target_ms=cfg.ack_p99_target_ms))

        # state-tiering: the broker's shared TieringCfg (one instance for
        # every partition's manager) — only when tiering is on at all
        tiering_cfg = broker._tiering_cfg()
        if tiering_cfg is not None:
            def write_park(value: float, c=tiering_cfg) -> None:
                c.park_after_ms = int(value)

            def write_spill(value: float, c=tiering_cfg) -> None:
                c.spill_batch = int(value)

            self.add_controller(TieringController(
                [Actuator(TieringController.name,
                          TieringController.KNOB_PARK,
                          lambda: float(tiering_cfg.park_after_ms),
                          write_park,
                          min_value=PARK_AFTER_MIN_MS,
                          max_value=PARK_AFTER_MAX_MS,
                          max_step=PARK_AFTER_STEP_MS,
                          static=float(min(max(tiering_cfg.park_after_ms,
                                               PARK_AFTER_MIN_MS),
                                           PARK_AFTER_MAX_MS)),
                          hold_band=100.0, integer=True),
                 Actuator(TieringController.name,
                          TieringController.KNOB_SPILL,
                          lambda: float(tiering_cfg.spill_batch),
                          write_spill,
                          min_value=SPILL_BATCH_MIN,
                          max_value=SPILL_BATCH_MAX,
                          max_step=SPILL_BATCH_STEP,
                          static=float(min(max(tiering_cfg.spill_batch,
                                               SPILL_BATCH_MIN),
                                           SPILL_BATCH_MAX)),
                          hold_band=16.0, integer=True)],
                rss_target_bytes=cfg.rss_target_bytes))

        # kernel-routing: the process-shared backend router's threshold
        from zeebe_tpu.utils.device_link import shared_router

        router = shared_router()

        def write_route_threshold(value: float, r=router) -> None:
            r.route_threshold_s = value / 1000.0

        self.add_controller(RoutingController(
            [Actuator(RoutingController.name, RoutingController.KNOB,
                      lambda: router.route_threshold_s * 1000.0,
                      write_route_threshold,
                      min_value=0.0, max_value=ROUTE_THRESHOLD_MAX_MS,
                      max_step=ROUTE_THRESHOLD_STEP_MS, static=0.0,
                      hold_band=1.0)]))

    def add_controller(self, controller: Controller) -> None:
        self.controllers.append(controller)

    def add_coalescing_controller(self, read: Callable[[], float],
                                  write: Callable[[float], None],
                                  static_ms: float) -> None:
        """Wire the ingress batch-coalescing loop (the multiproc worker
        calls this with its own window attribute — the knob lives at the
        ingress seam, which the bare broker does not have)."""
        self.add_controller(CoalescingController(
            [Actuator(CoalescingController.name, CoalescingController.KNOB,
                      read, write,
                      min_value=0.0, max_value=COALESCE_WINDOW_MAX_MS,
                      max_step=COALESCE_WINDOW_STEP_MS,
                      static=min(static_ms, COALESCE_WINDOW_MAX_MS),
                      hold_band=2.0)]))

    def register_loop(self, name: str,
                      snapshot_fn: Callable[[], dict]) -> None:
        """Aggregate a pre-existing feedback loop (admission shed ladder)
        into the CONTROL view — read-only; the loop keeps its own
        decision engine and records through the audit vocabulary."""
        self._loops[name] = snapshot_fn

    def _snapshot_scheduler_loop(self) -> dict:
        partitions = {
            str(pid): {"adaptiveTriggers": p.adaptive_snapshot_count,
                       "replayDebtRecords": max(
                           p.stream.last_position
                           - max(p._last_snapshot_processed, 0), 0)}
            for pid, p in sorted(self.broker.partitions.items())
        }
        return {
            "knob": "snapshot.cadence",
            "description": "snapshots early when projected replay debt "
                           "threatens recovery_budget_ms (PR 6)",
            "partitions": partitions,
            "adjustments": sum(v["adaptiveTriggers"]
                               for v in partitions.values()),
        }

    # -- the tick --------------------------------------------------------------

    def maybe_tick(self, now_ms: int | None = None) -> bool:
        now = self.clock_millis() if now_ms is None else now_ms
        if now - self._last_tick_ms < self.cfg.interval_ms:
            return False
        self.tick(now)
        return True

    def tick(self, now_ms: int | None = None) -> int:
        """One control round: per controller, read fresh signals and step
        every actuator one bounded move (or fall back toward static on a
        stale sensor). Returns the number of knob changes this round."""
        now = self.clock_millis() if now_ms is None else now_ms
        self._last_tick_ms = now
        self.ticks += 1
        changed = 0
        for controller in self.controllers:
            try:
                signals = controller.read_signals(self.reader)
            except Exception:  # noqa: BLE001 — a torn store read must not
                signals = None  # kill the pump; treat as a stale sensor
            if signals is None:
                for actuator in controller.actuators:
                    before = actuator.read()
                    if actuator.fall_back(controller.name, flight=self.flight,
                                          now_ms=now) != before:
                        changed += 1
                continue
            current = {a.knob: a.read() for a in controller.actuators}
            desired = controller.decide(signals, current)
            for actuator in controller.actuators:
                target, reason = desired[actuator.knob]
                if actuator.apply(target, reason, signals,
                                  flight=self.flight,
                                  now_ms=now) != current[actuator.knob]:
                    changed += 1
                else:
                    actuator.sync()  # propagate onto late-created targets
        if changed and self.flight is not None:
            # throttled (one per reason class per 5s): the audit trail is
            # the events; the dump is the reviewable artifact CI uploads
            self.flight.dump("control")
        return changed

    # -- observability ---------------------------------------------------------

    def snapshot(self) -> dict:
        loops = {}
        for name, fn in sorted(self._loops.items()):
            try:
                loops[name] = fn()
            except Exception:  # noqa: BLE001 — a torn loop snapshot must
                loops[name] = {"error": "unavailable"}  # not break /control
        return {
            "enabled": True,
            "intervalMs": self.cfg.interval_ms,
            "ticks": self.ticks,
            "ackP99TargetMs": self.cfg.ack_p99_target_ms,
            "rssTargetBytes": self.cfg.rss_target_bytes,
            "controllers": {
                c.name: {"actuators": [a.snapshot() for a in c.actuators]}
                for c in self.controllers
            },
            "loops": loops,
        }


def maybe_build_plane(broker, env: dict | None = None) -> ControlPlane | None:
    """The broker's construction seam: None when the plane is disabled or
    the observability plane (its sensor) is off — one ``is None`` check is
    the entire disabled cost."""
    cfg = ControlCfg.from_env(env)
    if not cfg.enabled or getattr(broker, "timeseries", None) is None:
        return None
    return ControlPlane(broker, cfg)
