"""The controller catalog: conservative feedback over windowed telemetry.

Each controller is a pure decision function (`decide`) over distilled
signals read from the Gorilla time-series store (PR 4) — never the raw
registry, never partition state — plus the declarative wiring of which
:class:`~zeebe_tpu.control.actuators.Actuator` it drives. Pure decisions
keep the unit tests deterministic (synthetic series in, knob trajectory
out) and keep every runtime side effect inside the actuator's bounded,
audited ``apply``.

Shipped loops (ISSUE 12):

- **ingress-coalescing** — the worker's ingress batch-coalescing window
  follows the observed append arrival rate: at low rates the window is 0
  (no added latency); at high rates a few milliseconds of coalescing turn
  N per-command raft appends (each an fsync + a replication round) into
  one batched append.
- **journal-flush** — the raft group-commit pacing
  (``RaftNode.flush_interval_s``) follows observed fsync latency/rate vs
  the ack-p99 target: when fsync utilization threatens the SLO the
  barrier widens (more appends per fsync, acks still strictly after the
  covering fsync); when the disk is idle it narrows back to per-append.
- **state-tiering** — ``park_after_ms``/``spill_batch`` follow the RSS
  watermark and the cold-fault rate: memory pressure parks sooner and
  spills harder; fault thrash with comfortable memory backs off.
- **kernel-routing** — the host-vs-device routing threshold
  (``BackendRouter.route_threshold_s``) follows the XLA compile
  telemetry from the PR 5 compile seam: a recompile storm biases groups
  onto the host backend until the program set settles.

Signal staleness: a controller whose signals cannot be read fresh
returns None from ``read_signals`` — the plane then walks every actuator
back toward its static configured value (one bounded step per tick), so
a dead sensor degrades to the hand-tuned deployment.
"""

from __future__ import annotations

from typing import Callable

from zeebe_tpu.control.actuators import Actuator

#: a retained sample older than this is not a live signal
DEFAULT_SIGNAL_MAX_AGE_MS = 15_000


class SignalReader:
    """Distilled-series access for controllers: freshness-guarded reads
    over the broker's :class:`TimeSeriesStore` (None = no live sample)."""

    def __init__(self, store, clock_millis: Callable[[], int],
                 max_age_ms: int = DEFAULT_SIGNAL_MAX_AGE_MS) -> None:
        self.store = store
        self.clock_millis = clock_millis
        self.max_age_ms = max_age_ms

    def _fresh(self, name: str, labels_contains: str) -> list[float]:
        now = self.clock_millis()
        return [entry["value"] for entry in self.store.latest(name)
                if entry["name"] == name
                and labels_contains in entry["labels"]
                and now - entry["t"] <= self.max_age_ms]

    def latest_sum(self, name: str,
                   labels_contains: str = "") -> float | None:
        values = self._fresh(name, labels_contains)
        return sum(values) if values else None

    def latest_max(self, name: str,
                   labels_contains: str = "") -> float | None:
        values = self._fresh(name, labels_contains)
        return max(values) if values else None


class Controller:
    """One feedback loop: named, with its actuators and its pure
    ``decide``. The plane owns the tick cadence and the apply/fallback
    mechanics."""

    name = ""

    def __init__(self, actuators: list[Actuator]) -> None:
        self.actuators = list(actuators)

    def read_signals(self, reader: SignalReader) -> dict | None:
        """Fresh signal values, or None (stale/absent → fallback)."""
        raise NotImplementedError

    def decide(self, signals: dict,
               current: dict[str, float]) -> dict[str, tuple[float, str]]:
        """{knob: (desired value, reason)} — pure, unit-testable."""
        raise NotImplementedError


class CoalescingController(Controller):
    """Ingress batch-coalescing window ← observed append arrival rate."""

    name = "ingress-coalescing"

    #: below this arrival rate the window stays 0 — coalescing only ever
    #: pays when several commands arrive inside a few milliseconds
    LOW_RATE_PER_S = 60.0
    #: aim for roughly this many commands per coalesced batch: the desired
    #: window is target/rate, so it SHRINKS as the rate grows (a hotter
    #: ingress gathers its batch sooner) and the actuator's max bound
    #: binds only in the just-above-the-floor regime
    TARGET_BATCH = 2.0

    KNOB = "ingress.coalesceWindowMs"

    def read_signals(self, reader: SignalReader) -> dict | None:
        # COMMAND arrivals, not record throughput: the admission
        # controller's admitted counter is the ingress-rate ground truth
        # (log-appender counts follow-up records too — 3-5x the command
        # rate — which would shrink the window far below its optimum).
        # Fallback for admission-disabled deployments: the appended-record
        # rate, the over-counting documented in docs/control.md.
        rate = reader.latest_sum("zeebe_admission_admitted_total")
        if rate is None:
            rate = reader.latest_sum(
                "zeebe_log_appender_record_appended_total")
            if rate is None:
                return None
        return {"appendPerSec": round(rate, 1)}

    def decide(self, signals, current):
        rate = signals["appendPerSec"]
        if rate <= self.LOW_RATE_PER_S:
            return {self.KNOB: (
                0.0, f"arrival rate {rate}/s under the coalescing floor "
                     f"({self.LOW_RATE_PER_S:.0f}/s)")}
        window_ms = 1000.0 * self.TARGET_BATCH / rate
        return {self.KNOB: (
            window_ms,
            f"arrival rate {rate}/s: ~{self.TARGET_BATCH:.0f} commands per "
            f"{window_ms:.1f}ms window")}


class JournalFlushController(Controller):
    """Raft group-commit pacing ← fsync latency/rate vs the ack-p99 SLO."""

    name = "journal-flush"

    #: fsync duty cycle (flushes/s x seconds/flush) above which the
    #: barrier widens — the disk, not the engine, is pacing acks
    UTIL_HIGH = 0.35
    #: duty cycle below which the barrier narrows back toward per-append
    UTIL_LOW = 0.05
    #: flush pressure that corroborates an ack-SLO breach
    UTIL_BREACH = 0.10

    KNOB = "raft.flushDelayMs"

    def __init__(self, actuators, ack_p99_target_ms: float = 250.0) -> None:
        super().__init__(actuators)
        self.ack_p99_target_ms = ack_p99_target_ms

    def read_signals(self, reader: SignalReader) -> dict | None:
        flush_rate = reader.latest_sum("zeebe_flush_duration_seconds")
        if flush_rate is None:
            return None
        p50_s = reader.latest_max("zeebe_flush_duration_seconds:p50") or 0.0
        signals = {"flushPerSec": round(flush_rate, 1),
                   "flushP50Ms": round(p50_s * 1000.0, 3),
                   "flushUtilization": round(flush_rate * p50_s, 3)}
        ack_p99 = reader.latest_max("zeebe_admission_ack_latency_ms:p99")
        if ack_p99 is not None:
            signals["ackP99Ms"] = round(ack_p99, 1)
        return signals

    def decide(self, signals, current):
        knob = self.KNOB
        util = signals["flushUtilization"]
        ack_p99 = signals.get("ackP99Ms")
        target = self.ack_p99_target_ms
        if util > self.UTIL_HIGH or (
                ack_p99 is not None and ack_p99 > target
                and util > self.UTIL_BREACH):
            return {knob: (
                float("inf"),  # the actuator clamps to its max bound
                f"fsync utilization {util:.2f} "
                + (f"with ack p99 {ack_p99}ms over the {target:.0f}ms target"
                   if ack_p99 is not None and ack_p99 > target
                   else f"over the {self.UTIL_HIGH:.2f} watermark")
                + ": widening the group-commit barrier")}
        if util < self.UTIL_LOW and (ack_p99 is None
                                     or ack_p99 < 0.5 * target):
            return {knob: (
                0.0, f"fsync utilization {util:.2f} idle and ack p99 clear: "
                     f"narrowing toward per-append flush")}
        return {knob: (current[knob],
                       f"holding: utilization {util:.2f} inside the band")}


class TieringController(Controller):
    """Tiering park horizon / spill batch ← RSS watermark + fault rate."""

    name = "state-tiering"

    #: back off (park later) only when memory is comfortably under target
    RSS_CLEAR_FRACTION = 0.7
    #: cold faults/s that count as thrash when memory is comfortable
    FAULT_HIGH_PER_S = 25.0

    KNOB_PARK = "tiering.parkAfterMs"
    KNOB_SPILL = "tiering.spillBatch"

    def __init__(self, actuators, rss_target_bytes: float) -> None:
        super().__init__(actuators)
        self.rss_target_bytes = float(rss_target_bytes)

    def read_signals(self, reader: SignalReader) -> dict | None:
        rss = reader.latest_max("process_resident_memory_bytes")
        if rss is None:
            return None
        faults = reader.latest_sum("zeebe_state_fault_in_total") or 0.0
        return {"rssBytes": rss, "faultPerSec": round(faults, 1),
                "rssTargetBytes": self.rss_target_bytes}

    def decide(self, signals, current):
        rss = signals["rssBytes"]
        faults = signals["faultPerSec"]
        target = self.rss_target_bytes
        mib = rss / (1 << 20)
        if rss > target:
            reason = (f"RSS {mib:.0f}MiB over the "
                      f"{target / (1 << 20):.0f}MiB target: park sooner, "
                      f"spill harder")
            return {self.KNOB_PARK: (0.0, reason),
                    self.KNOB_SPILL: (float("inf"), reason)}
        if rss < self.RSS_CLEAR_FRACTION * target \
                and faults > self.FAULT_HIGH_PER_S:
            reason = (f"cold-fault thrash ({faults}/s) with RSS "
                      f"{mib:.0f}MiB comfortable: park later")
            return {self.KNOB_PARK: (float("inf"), reason),
                    self.KNOB_SPILL: (current[self.KNOB_SPILL], reason)}
        if rss < self.RSS_CLEAR_FRACTION * target:
            reason = (f"RSS {mib:.0f}MiB comfortable: drifting back to the "
                      f"configured posture")
            return {self.KNOB_PARK: (float("nan"), reason),  # nan = static
                    self.KNOB_SPILL: (float("nan"), reason)}
        reason = f"holding: RSS {mib:.0f}MiB inside the band"
        return {self.KNOB_PARK: (current[self.KNOB_PARK], reason),
                self.KNOB_SPILL: (current[self.KNOB_SPILL], reason)}


class RoutingController(Controller):
    """Host-vs-device routing threshold ← XLA compile telemetry + the
    device health ladder (ISSUE 15): a SUSPECT/QUARANTINED device biases
    kernel groups host-ward through the same actuator a recompile storm
    uses — recent device faults and compile churn are the same posture
    (don't trust the accelerator with latency-critical groups right now)."""

    name = "kernel-routing"

    #: sustained cold-compile rate that reads as a recompile storm — the
    #: same posture as the xla_recompile_storm default alert (>= 3/min)
    STORM_MISS_PER_S = 0.05

    KNOB = "router.routeThresholdMs"

    def read_signals(self, reader: SignalReader) -> dict | None:
        miss_rate = reader.latest_sum("zeebe_xla_compiles_total",
                                      labels_contains='cache="miss"')
        device_state = reader.latest_max("zeebe_device_health_state")
        if miss_rate is None and not device_state:
            # no compile telemetry and a HEALTHY (or absent) ladder: the
            # health gauge is registered at import and always fresh, so it
            # must not masquerade as a live compile signal — report stale
            # and let the actuator walk back to the configured static
            # threshold instead of actuating on a fabricated 0.0 miss rate
            return None
        signals = {"compileMissPerSec": round(miss_rate or 0.0, 3)}
        if device_state is not None:
            signals["deviceHealthState"] = device_state
        p99 = reader.latest_max("zeebe_xla_compile_seconds:p99")
        if p99 is not None:
            signals["compileP99Ms"] = round(p99 * 1000.0, 1)
        return signals

    def decide(self, signals, current):
        miss = signals["compileMissPerSec"]
        device_state = signals.get("deviceHealthState", 0.0)
        if device_state and device_state >= 1.0:
            label = "QUARANTINED" if device_state >= 2.0 else "SUSPECT"
            return {self.KNOB: (
                float("inf"),
                f"device health {label}: biasing kernel groups onto the "
                f"host backend until the ladder clears")}
        if miss > self.STORM_MISS_PER_S:
            return {self.KNOB: (
                float("inf"),
                f"recompile storm ({miss}/s cold compiles): biasing kernel "
                f"groups onto the host backend")}
        return {self.KNOB: (
            0.0, f"compile churn {miss}/s settled: unbiased routing")}
