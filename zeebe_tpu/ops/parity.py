"""Host-side decoding of device step events → per-instance intent sequences.

The parity oracle between the automaton kernel and the sequential engine
(reference test strategy: behavioral assertions on the event stream). The
batched schedule is a reordering-equivalent of one-at-a-time processing:
within an instance, the order of lifecycle events is identical; across
instances, the device's slot order replaces the log's arrival order.
"""

from __future__ import annotations

import numpy as np

from zeebe_tpu.ops.tables import ProcessTables


def decode_step_events(tables: ProcessTables, state_before: dict, events: dict) -> dict[int, list[tuple[str, str]]]:
    """Decode one step's event masks into {instance: [(element_id, intent)]}.

    Ordering within an instance: element lifecycle events first, then its
    taken flows — matching the engine's write order per processing step.
    """
    out: dict[int, list[tuple[str, str]]] = {}
    elem = np.asarray(events["elem"])
    inst = np.asarray(events["inst"])
    def_of = np.asarray(state_before["def_of"])
    full_pass = np.asarray(events["full_pass"])
    task_arrive = np.asarray(events["task_arrive"])
    task_done = np.asarray(events["task_done"])
    take_mask = np.asarray(events["take_mask"])
    newly_done = np.asarray(events["newly_done"])

    def emit(i: int, element_id: str, *intents: str) -> None:
        out.setdefault(i, []).extend((element_id, intent) for intent in intents)

    for t in range(elem.shape[0]):
        e = elem[t]
        if e < 0:
            continue
        i = int(inst[t])
        d = int(def_of[i])
        exe = tables.definitions[d]
        element = exe.elements[int(e)]
        if task_arrive[t]:
            emit(i, element.id, "ELEMENT_ACTIVATING", "ELEMENT_ACTIVATED", "JOB_CREATED")
        elif task_done[t]:
            emit(i, element.id, "JOB_COMPLETED", "ELEMENT_COMPLETING", "ELEMENT_COMPLETED")
        elif full_pass[t]:
            emit(
                i, element.id,
                "ELEMENT_ACTIVATING", "ELEMENT_ACTIVATED",
                "ELEMENT_COMPLETING", "ELEMENT_COMPLETED",
            )
        for s in range(take_mask.shape[1]):
            if take_mask[t, s]:
                fidx = int(tables.out_flow_idx[d, int(e), s])
                if fidx < 0:
                    continue  # synthetic link-jump edge: no sequence flow
                emit(i, exe.flows[fidx].id, "SEQUENCE_FLOW_TAKEN")
    for i in np.nonzero(newly_done)[0]:
        d = int(def_of[i])
        exe = tables.definitions[d]
        emit(int(i), exe.process_id, "ELEMENT_COMPLETING", "ELEMENT_COMPLETED")
    return out


def run_with_events(dt, tables: ProcessTables, state: dict, max_steps: int = 200, auto_jobs: bool = True):
    """Step until quiescent, collecting decoded events per instance."""
    from zeebe_tpu.ops.automaton import step

    sequences: dict[int, list[tuple[str, str]]] = {}
    for _ in range(max_steps):
        if not bool(np.asarray(state["elem"] >= 0).any()):
            break
        before = state
        state, events = step(dt, state, auto_jobs=auto_jobs, emit_events=True)
        decoded = decode_step_events(tables, before, events)
        for i, evs in decoded.items():
            sequences.setdefault(i, []).extend(evs)
    return state, sequences


def engine_intent_sequence(exporter, process_instance_key: int) -> list[tuple[str, str]]:
    """The comparable sequence from the sequential engine's event stream:
    PI lifecycle events + job created/completed, keyed by element id."""
    from zeebe_tpu.protocol import ValueType

    out = []
    for rec in exporter.all().events():
        value = rec.record.value
        if value.get("processInstanceKey") != process_instance_key:
            continue
        if rec.record.value_type == ValueType.PROCESS_INSTANCE:
            intent = rec.record.intent.name
            if intent in (
                "ELEMENT_ACTIVATING", "ELEMENT_ACTIVATED", "ELEMENT_COMPLETING",
                "ELEMENT_COMPLETED", "SEQUENCE_FLOW_TAKEN",
            ):
                out.append((value["elementId"], intent))
        elif rec.record.value_type == ValueType.JOB:
            if rec.record.intent.name in ("CREATED", "COMPLETED"):
                out.append((value["elementId"], f"JOB_{rec.record.intent.name}"))
    return out
