"""The data-parallel BPMN automaton kernel.

This is the BASELINE.json north star: the reference's BpmnStreamProcessor +
per-element BpmnElementProcessor handlers (engine/…/processing/bpmn/) re-
expressed as one `jax.jit` step advancing thousands of process instances
lock-step on a TPU. Design notes:

- **SoA token pool**: a token is a (element, phase, instance) triple in flat
  int32 arrays of capacity T. No Python objects, no per-token control flow —
  the element-type dispatch (the reference's switch in BpmnElementProcessors)
  is masked vector arithmetic over the deploy-time tables (tables.py).
- **Lock-step semantics**: one kernel step advances every live token through
  one element pass. Within a step tokens are independent (per-instance state
  only); the host merges device events back into the partition's event-sourced
  log in deterministic slot order, making the batched schedule a reordering-
  equivalent of the reference's one-at-a-time processing.
- **Movement is allocation**: every taken sequence flow (including parallel
  fan-out) becomes a placement request; free token slots are assigned by
  prefix-sum, parallel-join arrivals are ranked with a stable sort so exactly
  the completing arrival proceeds — the NUMBER_OF_TAKEN_SEQUENCE_FLOWS
  counters live in a dense [instances, elements] array.
- **Conditions** run on a vectorized stack VM over per-instance order-key
  variable slots (compile_condition) — two int32 planes carrying IEEE-754
  total-order keys, bit-exact against the host float64 evaluator — so
  exclusive-gateway routing needs no host round trip.
- **TPU mapping**: everything is static-shaped, pure int32, and fuses into
  a handful of XLA kernels; gathers/scatters ride the VPU while the MXU stays
  free for future DMN/decision-table batch evaluation. Scaling over a mesh is
  data-parallel over instances (see zeebe_tpu.parallel.mesh) — the partition
  axis of the reference maps to the mesh axis here.

Job handling: ``auto_jobs=True`` emulates instant workers on-device (bench
mode, isolates engine throughput); otherwise tokens park in PHASE_WAIT and the
host completes jobs between steps (``complete_jobs``), which is how the real
job-worker path drives the kernel.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from zeebe_tpu.ops.tables import (
    K_CATCH,
    K_END,
    K_EXCLUSIVE,
    K_FORK,
    K_HOST,
    K_INCLUSIVE,
    K_JOIN,
    K_MI,
    K_NONE,
    K_PASS,
    K_SCOPE,
    K_TASK,
    MAX_PROG_LEN,
    OP_AND,
    OP_EQ,
    OP_GE,
    OP_GT,
    OP_LE,
    OP_LT,
    OP_NE,
    OP_NEG,
    OP_NOP,
    OP_NOT,
    OP_OR,
    OP_PUSH_CONST,
    OP_PUSH_VAR,
    STACK_DEPTH,
    ProcessTables,
)

# token phases
PHASE_AT = 0  # at element, executes this step
PHASE_WAIT = 1  # task activated, waiting for job completion
PHASE_DONE = 2  # job completed, finish task this step
PHASE_STALLED = 3  # incident raised; host must resolve


@dataclasses.dataclass
class DeviceTables:
    """ProcessTables moved to device arrays (a pytree via tree_flatten)."""

    kernel_op: jax.Array
    in_count: jax.Array
    job_type: jax.Array
    out_count: jax.Array
    out_target: jax.Array
    out_cond: jax.Array
    out_flow_idx: jax.Array
    default_slot: jax.Array
    start_elem: jax.Array
    scope_start: jax.Array
    in_scope: jax.Array
    cond_ops: jax.Array
    cond_args: jax.Array
    mi_sequential: jax.Array

    @classmethod
    def from_tables(cls, t: ProcessTables) -> "DeviceTables":
        return cls(
            kernel_op=jnp.asarray(t.kernel_op),
            in_count=jnp.asarray(t.in_count),
            job_type=jnp.asarray(t.job_type),
            out_count=jnp.asarray(t.out_count),
            out_target=jnp.asarray(t.out_target),
            out_cond=jnp.asarray(t.out_cond),
            out_flow_idx=jnp.asarray(t.out_flow_idx),
            default_slot=jnp.asarray(t.default_slot),
            start_elem=jnp.asarray(t.start_elem),
            scope_start=jnp.asarray(t.scope_start),
            in_scope=jnp.asarray(t.in_scope),
            cond_ops=jnp.asarray(t.cond_ops),
            cond_args=jnp.asarray(t.cond_args),
            mi_sequential=jnp.asarray(t.mi_sequential),
        )


jax.tree_util.register_pytree_node(
    DeviceTables,
    lambda t: (tuple(getattr(t, f.name) for f in dataclasses.fields(t)), None),
    lambda _, children: DeviceTables(*children),
)


def _coerce_slot_planes(values) -> np.ndarray:
    """Slot input → int32 (hi, lo) plane array. A 3-D INTEGER array (any
    width) is pre-packed planes — int64 Python-int inputs must coerce, not
    silently fall into the float packer, which would reinterpret plane
    integers as float *values* and mint garbage keys. Floats pack."""
    arr = np.asarray(values)
    if arr.ndim == 3:
        if arr.shape[-1] != 2:
            raise ValueError(f"pre-packed slot planes must have trailing dim 2, "
                             f"got {arr.shape}")
        if not np.issubdtype(arr.dtype, np.integer):
            raise ValueError("3-D slot input must be integer (hi, lo) planes; "
                             "pass floats as a 2-D [instances, slots] array")
        if arr.dtype != np.int32:
            out_of_range = (arr < np.iinfo(np.int32).min) | (arr > np.iinfo(np.int32).max)
            if out_of_range.any():
                raise ValueError("slot planes exceed int32 range")
            arr = arr.astype(np.int32)
        return arr
    from zeebe_tpu.ops.tables import pack_slot_values

    return pack_slot_values(arr)


def make_state(
    tables: ProcessTables,
    num_instances: int,
    definition_of_instance: np.ndarray,
    initial_slots: np.ndarray | None = None,
    token_capacity: int | None = None,
    num_shards: int = 1,
) -> dict:
    """Fresh automaton state: one token per instance, parked at the start
    event. Arrays are a plain dict pytree so jit/donation/sharding apply.

    With ``num_shards > 1`` the layout is shard-block-aligned for axis-0
    sharding over a mesh: shard s owns instance rows [s*I/n, (s+1)*I/n) and
    the token-pool block [s*T/n, (s+1)*T/n); token ``inst`` values are
    *local* to the shard block (the kernel body runs on local shapes under
    shard_map, so per-shard indices must be self-contained)."""
    I = num_instances
    T = token_capacity or (2 * I)
    if I % num_shards or T % num_shards:
        raise ValueError(f"instances ({I}) and tokens ({T}) must divide num_shards ({num_shards})")
    E = tables.max_elements
    S = tables.num_slots
    def_of = np.asarray(definition_of_instance, np.int32)
    elem = np.full(T, -1, np.int32)
    phase = np.zeros(T, np.int32)
    inst = np.zeros(T, np.int32)
    Il, Tl = I // num_shards, T // num_shards
    if Il > Tl:
        raise ValueError("token capacity per shard smaller than instances per shard")
    for s in range(num_shards):
        block = slice(s * Tl, s * Tl + Il)
        elem[block] = tables.start_elem[def_of[s * Il : (s + 1) * Il]]
        inst[block] = np.arange(Il, dtype=np.int32)
    if initial_slots is None:
        slots = np.zeros((I, S, 2), np.int32)
    else:
        slots = _coerce_slot_planes(initial_slots)
    return {
        "elem": jnp.asarray(elem),
        "phase": jnp.asarray(phase),
        "inst": jnp.asarray(inst),
        "def_of": jnp.asarray(def_of),
        "var_slots": jnp.asarray(slots),
        "join_counts": jnp.zeros((I, E), jnp.int32),
        "mi_left": jnp.zeros((I, E), jnp.int32),
        "done": jnp.zeros(I, jnp.bool_),
        "incident": jnp.zeros(I, jnp.bool_),
        "transitions": jnp.zeros((), jnp.int32),
        "jobs_created": jnp.zeros((), jnp.int32),
        "completed": jnp.zeros((), jnp.int32),
        "overflow": jnp.zeros((), jnp.bool_),
    }


# ---------------------------------------------------------------------------
# condition VM


def _eval_program(ops: jax.Array, args: jax.Array, slots: jax.Array) -> jax.Array:
    """Evaluate one condition program against one instance's slots → bool.

    Values are 64-bit order keys carried as (hi, lo) int32 planes
    (tables.f64_key_planes): comparisons are lexicographic over the planes,
    hence BIT-EXACT against the host's float64 FEEL evaluator. Booleans are
    (0|1, 0). Arithmetic never reaches the device (compile_condition
    host-escapes it), so the VM has only push/compare/bool/negate ops."""

    def body(i, carry):
        stack, sp = carry  # stack: [DEPTH, 2] int32
        op = ops[i]
        arg = args[i]  # (hi, lo)
        push_val = jnp.where(op == OP_PUSH_VAR, slots[arg[0]], arg)
        a = stack[jnp.maximum(sp - 2, 0)]
        b = stack[jnp.maximum(sp - 1, 0)]
        # lexicographic order over (hi, lo); both planes are sign-biased so
        # plain signed int32 comparison gives the unsigned half order
        lt = (a[0] < b[0]) | ((a[0] == b[0]) & (a[1] < b[1]))
        eq = (a[0] == b[0]) & (a[1] == b[1])
        bool_hi = jnp.select(
            [
                op == OP_LT, op == OP_LE, op == OP_GT, op == OP_GE,
                op == OP_EQ, op == OP_NE, op == OP_AND, op == OP_OR,
            ],
            [
                lt, lt | eq, ~(lt | eq), ~lt,
                eq, ~eq,
                (a[0] > 0) & (b[0] > 0), (a[0] > 0) | (b[0] > 0),
            ],
            default=False,
        ).astype(jnp.int32)
        bin_val = jnp.stack([bool_hi, jnp.int32(0)])
        # NOT flips a boolean; NEG negates an order key (bitwise NOT of the
        # unbiased halves = -1 - x in the sign-biased planes). Zero stays
        # zero: key(+0.0) is (0, INT32_MIN) — the sign bit of the f64 maps
        # to hi's bias and the empty mantissa to lo's — and negating it
        # would mint key(-0.0), which compares strictly below it.
        is_zero = (b[0] == 0) & (b[1] == jnp.int32(-(2**31)))
        neg_val = jnp.where(
            is_zero, b, jnp.stack([-1 - b[0], -1 - b[1]])
        )
        un_val = jnp.where(
            op == OP_NOT,
            jnp.stack([1 - jnp.minimum(b[0], 1), jnp.int32(0)]),
            neg_val,
        )
        is_push = (op == OP_PUSH_CONST) | (op == OP_PUSH_VAR)
        is_un = (op == OP_NOT) | (op == OP_NEG)
        # binary = comparisons (3..8) + AND/OR (9..10); arithmetic never
        # reaches the device (compile_condition host-escapes it), so there
        # are no opcodes above OP_OR other than the unaries
        is_bin = (op >= OP_LT) & (op <= OP_OR)
        new_top = jnp.where(is_push, push_val, jnp.where(is_bin, bin_val, un_val))
        write_pos = jnp.where(is_push, sp, jnp.where(is_bin, sp - 2, sp - 1))
        do_write = is_push | is_bin | is_un
        # NOPs write out of bounds → dropped
        write_pos = jnp.where(do_write, jnp.clip(write_pos, 0, STACK_DEPTH - 1), STACK_DEPTH)
        stack = stack.at[write_pos].set(new_top, mode="drop")
        sp = sp + jnp.where(is_push, 1, jnp.where(is_bin, -1, 0))
        return stack, sp

    stack0 = jnp.zeros((STACK_DEPTH, 2), jnp.int32)
    stack, sp = jax.lax.fori_loop(0, MAX_PROG_LEN, body, (stack0, jnp.int32(0)))
    return stack[jnp.maximum(sp - 1, 0), 0] > 0


# vmapped over (program_id per request, slots per request)
def _eval_conditions(cond_ops, cond_args, prog_ids, slot_rows):
    def one(pid, slots):
        return jax.lax.cond(
            pid >= 0,
            lambda: _eval_program(cond_ops[jnp.maximum(pid, 0)], cond_args[jnp.maximum(pid, 0)], slots),
            lambda: jnp.bool_(False),
        )
    return jax.vmap(one)(prog_ids, slot_rows)


# ---------------------------------------------------------------------------
# scope machinery


def _scope_occupancy(tables: "DeviceTables", state: dict):
    """(occ, pend): per (instance, scope element) counts of live tokens and
    unconsumed parallel-join arrivals strictly inside each scope."""
    elem = state["elem"]
    inst = state["inst"]
    I, E = state["join_counts"].shape
    live = elem >= 0
    def_of_tok = state["def_of"][inst]
    # [T, E] row t = which scopes (transitively) contain token t's element
    containing = tables.in_scope[def_of_tok, jnp.maximum(elem, 0)].astype(jnp.int32)
    occ = jnp.zeros((I, E), jnp.int32).at[inst].add(
        containing * live.astype(jnp.int32)[:, None]
    )
    pend = jnp.einsum(
        "ie,ies->is",
        state["join_counts"],
        tables.in_scope[state["def_of"]].astype(jnp.int32),
    )
    return occ, pend


def _scope_drained(tables: "DeviceTables", state: dict,
                   include_mi: bool = False, occ_pend=None) -> jax.Array:
    """Mask of parked K_SCOPE tokens whose scope holds no live token and no
    unconsumed parallel-join arrival — they complete on the next step. Used
    by ``step`` (start-of-step state) and by ``run_collect``'s active count
    (post-step state), so a drain-pending scope never reads as quiesced.
    With ``include_mi`` the mask also covers fully-spawned K_MI bodies whose
    children all drained (body completion)."""
    elem = state["elem"]
    phase = state["phase"]
    inst = state["inst"]
    live = elem >= 0
    def_of_tok = state["def_of"][inst]
    op = jnp.where(live, tables.kernel_op[def_of_tok, jnp.maximum(elem, 0)], K_NONE)
    occ, pend = occ_pend if occ_pend is not None else _scope_occupancy(tables, state)
    scope_like = op == K_SCOPE
    if include_mi:
        spawned_out = state["mi_left"][inst, jnp.maximum(elem, 0)] == 0
        scope_like = scope_like | ((op == K_MI) & spawned_out)
    return (
        live & scope_like & (phase == PHASE_WAIT)
        & (occ[inst, jnp.maximum(elem, 0)] == 0)
        & (pend[inst, jnp.maximum(elem, 0)] == 0)
    )


def _mi_spawnable(tables: "DeviceTables", state: dict,
                  occ_pend=None) -> jax.Array:
    """Mask of parked K_MI body tokens that spawn a child next step: children
    left, and (sequential bodies only) the previous child fully drained."""
    elem = state["elem"]
    phase = state["phase"]
    inst = state["inst"]
    live = elem >= 0
    def_of_tok = state["def_of"][inst]
    e = jnp.maximum(elem, 0)
    op = jnp.where(live, tables.kernel_op[def_of_tok, e], K_NONE)
    occ, pend = occ_pend if occ_pend is not None else _scope_occupancy(tables, state)
    seq = tables.mi_sequential[def_of_tok, e] > 0
    gate = ~seq | ((occ[inst, e] == 0) & (pend[inst, e] == 0))
    return (
        live & (op == K_MI) & (phase == PHASE_WAIT)
        & (state["mi_left"][inst, e] > 0) & gate
    )


# ---------------------------------------------------------------------------
# the step kernel


@partial(jax.jit, static_argnames=("auto_jobs", "emit_events", "config"))
def step(tables: DeviceTables, state: dict, auto_jobs: bool = True, emit_events: bool = False,
         config=None):
    """One lock-step advance of every live token. Returns (state', events)
    where events is None unless emit_events (parity/integration mode).
    ``config`` (static KernelConfig) prunes join/condition machinery the
    deployed process set does not use."""
    from zeebe_tpu.ops.tables import KernelConfig

    if config is None:
        config = KernelConfig()
    T = state["elem"].shape[0]
    I = state["def_of"].shape[0]
    E = tables.kernel_op.shape[1]
    FO = tables.out_target.shape[2]

    elem = state["elem"]
    phase = state["phase"]
    inst = state["inst"]
    def_of_tok = state["def_of"][inst]

    live = elem >= 0
    op = jnp.where(live, tables.kernel_op[def_of_tok, jnp.maximum(elem, 0)], K_NONE)
    stalled = phase == PHASE_STALLED

    # --- what does each token do this step? ------------------------------
    is_task = op == K_TASK
    is_wait = is_task | (op == K_CATCH)  # parks until the host resumes it
    is_scope = op == K_SCOPE  # parks until its inner tokens drain
    is_host = op == K_HOST  # parks forever: the sequential engine owns it
    is_mi = op == K_MI  # parks like a scope; spawns mi_left children
    executing = live & (phase == PHASE_AT) & ~stalled
    arriving_task = executing & is_wait
    arriving_scope = executing & is_scope
    arriving_host = executing & is_host
    arriving_mi = executing & is_mi
    pass_attempt = executing & ~is_wait & ~is_scope & ~is_host & ~is_mi
    if auto_jobs:
        waiting_done = live & is_wait & (phase == PHASE_WAIT)
    else:
        waiting_done = live & is_wait & (phase == PHASE_DONE)

    # --- scope drain detection --------------------------------------------
    # a parked scope token resumes when no live token and no unconsumed
    # parallel-join arrival remains anywhere inside it (reference: scope
    # completion requires activeChildren == 0 and activeFlows == 0); both
    # counts are start-of-step, so a resume lands one step after the last
    # inner token dies — quiesced states stay fixed points. K_MI bodies join
    # the mask once fully spawned (mi_left == 0): the body completes when
    # its children drain.
    if config.has_scopes or config.has_mi:
        occ_pend = _scope_occupancy(tables, state)
        scope_resume = _scope_drained(tables, state, include_mi=config.has_mi,
                                      occ_pend=occ_pend)
    else:
        occ_pend = None
        scope_resume = jnp.zeros(T, jnp.bool_)
    # parked MI bodies spawn one child per step (parallel: every step until
    # mi_left == 0; sequential: only when the previous child drained)
    if config.has_mi:
        mi_spawn = _mi_spawnable(tables, state, occ_pend=occ_pend)
    else:
        mi_spawn = jnp.zeros(T, jnp.bool_)

    # --- exclusive gateway condition evaluation ---------------------------
    out_count = tables.out_count[def_of_tok, jnp.maximum(elem, 0)]
    targets = tables.out_target[def_of_tok, jnp.maximum(elem, 0)]  # [T, FO]
    conds = tables.out_cond[def_of_tok, jnp.maximum(elem, 0)]  # [T, FO]
    slot_idx = jnp.arange(FO)[None, :]

    is_excl = op == K_EXCLUSIVE
    is_incl = op == K_INCLUSIVE
    need_eval = ((is_excl | is_incl) & pass_attempt)[:, None] & (conds >= 0)
    if config.has_conditions:
        # scalar-predicated skip: in steps where no executing token sits on a
        # conditional gateway (most steps of job-completion cascades), the
        # whole vectorized VM is skipped — the pred is a scalar, so lax.cond
        # stays real control flow (unlike a vmapped cond, which would lower
        # to select and evaluate both branches for every lane)
        def eval_all(_):
            prog_ids = jnp.where(need_eval, conds, -1).reshape(-1)
            slot_rows = jnp.repeat(state["var_slots"][inst], FO, axis=0)
            out = _eval_conditions(tables.cond_ops, tables.cond_args, prog_ids, slot_rows)
            return out.reshape(T, FO) & need_eval

        cond_true = jax.lax.cond(
            jnp.any(need_eval), eval_all,
            lambda _: jnp.zeros((T, FO), jnp.bool_), operand=None,
        )
    else:
        cond_true = jnp.zeros((T, FO), jnp.bool_)

    first_true = jnp.argmax(cond_true, axis=1)
    any_true = jnp.any(cond_true, axis=1)
    default = tables.default_slot[def_of_tok, jnp.maximum(elem, 0)]
    excl_choice = jnp.where(any_true, first_true, default)  # -1 if no default
    excl_no_match = (is_excl | is_incl) & pass_attempt & ~any_true & (default < 0)

    # no-match raises an incident: the token stalls instead of completing
    full_pass = pass_attempt & ~excl_no_match
    completing = full_pass | waiting_done | scope_resume  # completes & moves

    # inclusive fork: EVERY true-condition flow; the default only when none
    # hold (reference: InclusiveGatewayProcessor.findSequenceFlowsToTake)
    incl_take = cond_true | (
        (slot_idx == default[:, None]) & ~any_true[:, None]
        & (default >= 0)[:, None]
    )
    take_mask = jnp.where(
        is_excl[:, None],
        (slot_idx == excl_choice[:, None]) & (excl_choice >= 0)[:, None],
        jnp.where(is_incl[:, None], incl_take, slot_idx < out_count[:, None]),
    )
    take_mask = take_mask & completing[:, None] & (targets >= 0)

    # --- transition counting ----------------------------------------------
    # full pass = 4 lifecycle events; task arrival = 2; task completion = 2;
    # an instance finishing adds the process element's completing/completed
    flows_taken = take_mask.sum()
    per_token = (
        jnp.where(full_pass, 4, 0)
        + jnp.where(arriving_task | arriving_scope | arriving_mi, 2, 0)
        + jnp.where(waiting_done | scope_resume, 2, 0)
    )

    # --- movement: flatten taken flows into placement requests ------------
    req_target_2d = jnp.where(take_mask, targets, -1)
    spawning = arriving_scope | arriving_mi | mi_spawn
    if config.has_scopes or config.has_mi:
        # an arriving scope (or an MI body, on arrival and on each later
        # spawn step while parked) spawns its inner token; the request rides
        # the (unused) flow slot 0 of the spawner, so placement/dest
        # machinery needs no extra channel — take_mask stays false there
        # (no SEQUENCE_FLOW_TAKEN), and dest[:, 0] records the child slot
        spawn_target = jnp.where(
            spawning,
            tables.scope_start[def_of_tok, jnp.maximum(elem, 0)],
            req_target_2d[:, 0],
        )
        req_target_2d = req_target_2d.at[:, 0].set(spawn_target)
    req_target = req_target_2d.reshape(-1)  # [T*FO]
    req_inst = jnp.repeat(inst, FO)
    req_def = jnp.repeat(def_of_tok, FO)
    req_live = req_target >= 0

    if config.has_joins:
        # parallel-join arrivals: stable-rank same-(inst, target) requests so
        # exactly the arrival that fills the join proceeds
        req_op = jnp.where(
            req_live, tables.kernel_op[req_def, jnp.maximum(req_target, 0)], K_NONE
        )
        is_join_req = req_op == K_JOIN
        flat_key = jnp.where(is_join_req, req_inst * E + req_target, 0)
        arrivals_flat = jnp.zeros((I * E,), jnp.int32).at[flat_key].add(
            jnp.where(is_join_req, 1, 0)
        )

        # the stable argsort only matters when TWO arrivals hit the same
        # (instance, join) in one step; most steps have at most one, so the
        # whole ranking machinery rides a scalar-predicated cond (real
        # control flow, like the condition VM's skip)
        def ranked(_):
            join_key = jnp.where(is_join_req, req_inst * E + req_target,
                                 jnp.int32(2**30))
            order = jnp.argsort(join_key, stable=True)
            sorted_key = join_key[order]
            new_run = jnp.concatenate(
                [jnp.ones(1, jnp.bool_), sorted_key[1:] != sorted_key[:-1]])
            idxs = jnp.arange(T * FO, dtype=jnp.int32)
            run_start = jax.lax.associative_scan(
                jnp.maximum, jnp.where(new_run, idxs, 0))
            rank_sorted = idxs - run_start
            return jnp.zeros(T * FO, jnp.int32).at[order].set(rank_sorted)

        rank = jax.lax.cond(
            jnp.any(arrivals_flat > 1), ranked,
            lambda _: jnp.zeros(T * FO, jnp.int32), operand=None,
        )

        prior = state["join_counts"][req_inst, jnp.maximum(req_target, 0)]
        arity = jnp.maximum(tables.in_count[req_def, jnp.maximum(req_target, 0)], 1)
        count_after = prior + rank + 1
        join_completes = is_join_req & (count_after % arity == 0)
        proceeds = req_live & (~is_join_req | join_completes)

        consumed_flat = jnp.zeros((I * E,), jnp.int32).at[flat_key].add(
            jnp.where(join_completes, arity, 0)
        )
        join_counts = state["join_counts"] + (arrivals_flat - consumed_flat).reshape(I, E)
    else:
        proceeds = req_live
        join_counts = state["join_counts"]

    # --- token slot allocation (prefix-sum into freed slots) --------------
    elem_after_exec = jnp.where(completing, -1, elem)
    free = elem_after_exec < 0
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    # rank → slot id map (ranks are unique per free slot; non-free dropped)
    slot_of_rank = jnp.zeros(T, jnp.int32).at[
        jnp.where(free, free_rank, T)
    ].set(jnp.arange(T, dtype=jnp.int32), mode="drop")
    place_rank = jnp.cumsum(proceeds.astype(jnp.int32)) - 1
    free_count = free.sum()
    valid = proceeds & (place_rank < free_count)
    overflow = state["overflow"] | jnp.any(proceeds & ~valid)
    dest = jnp.where(valid, slot_of_rank[jnp.clip(place_rank, 0, T - 1)], T)

    new_elem = elem_after_exec.at[dest].set(req_target, mode="drop")
    new_inst = inst.at[dest].set(req_inst, mode="drop")

    new_phase = jnp.where(
        arriving_task | arriving_scope | arriving_host | arriving_mi,
        PHASE_WAIT, phase)
    new_phase = jnp.where(excl_no_match, PHASE_STALLED, new_phase)
    new_phase = new_phase.at[dest].set(PHASE_AT, mode="drop")

    if config.has_mi:
        spawned = arriving_mi | mi_spawn
        mi_left = state["mi_left"].at[inst, jnp.maximum(elem, 0)].add(
            -spawned.astype(jnp.int32)
        )
    else:
        mi_left = state["mi_left"]

    # --- instance completion ----------------------------------------------
    live_after = new_elem >= 0
    tokens_per_inst = jnp.zeros(I, jnp.int32).at[new_inst].add(live_after.astype(jnp.int32))
    was_done = state["done"]
    # a pending parallel-join arrival is an active sequence flow: the scope
    # only completes when no tokens AND no unconsumed arrivals remain
    # (reference: scope completion requires activeFlows == 0)
    pending_arrivals = join_counts.sum(axis=1)
    newly_done = ~was_done & (tokens_per_inst == 0) & (pending_arrivals == 0)
    done = was_done | newly_done
    incident = state["incident"] | jnp.zeros(I, jnp.bool_).at[inst].max(excl_no_match)

    transitions = (
        state["transitions"]
        + per_token.sum()
        + flows_taken
        + 2 * newly_done.sum()  # process element completing/completed
    )
    jobs_created = state["jobs_created"] + (arriving_task & is_task).sum()
    completed = state["completed"] + newly_done.sum()

    new_state = {
        "elem": new_elem,
        "phase": new_phase,
        "inst": new_inst,
        "def_of": state["def_of"],
        "var_slots": state["var_slots"],
        "join_counts": join_counts,
        "mi_left": mi_left,
        "done": done,
        "incident": incident,
        "transitions": transitions,
        "jobs_created": jobs_created,
        "completed": completed,
        "overflow": overflow,
    }

    events = None
    if emit_events:
        events = {
            "full_pass": full_pass,
            # scope/MI arrivals and resumes share the task bits: the host
            # decoder disambiguates by the element's kernel opcode; mid-park
            # MI spawns carry no flag at all — the decoder reads dest[:, 0]
            # of parked K_MI rows
            "task_arrive": arriving_task | arriving_scope | arriving_mi,
            "task_done": waiting_done | scope_resume,
            "elem": elem,
            "inst": inst,
            "take_mask": take_mask,
            "newly_done": newly_done,
            "no_match": excl_no_match,
            # placement slot per flattened (token, flow-slot) request; T means
            # no token was placed (join arrival merged, or dropped) — lets the
            # host decoder track slot→logical-token identity (kernel backend)
            "dest": dest.reshape(T, FO),
        }
    return new_state, events


# bit-packed event layout bounds: elem rides col 0 in 14 bits, and dest
# (with its == T "no placement" sentinel) rides 16 bits of a dest|take
# column; callers must fall back beyond these (realistic pools sit far
# below both). The active count is NOT bound — it travels as a full int32
# tail scalar.
PACK_MAX_ELEMENTS = 1 << 14
PACK_MAX_TOKENS = (1 << 16) - 1


def _pack_events(ev: dict, I: int, T: int) -> jax.Array:
    """Pack one step's event pytree into a single int32 [T, 2 + FO] tensor —
    one device buffer per chunk transfer, bit-packed to halve the bytes the
    host fetches over the TPU tunnel (per-buffer latency AND bandwidth both
    bound the serving path):

      col 0: flags(5b) | elem << 5 — bit0 full_pass, bit1 task_arrive,
             bit2 task_done, bit3 no_match, bit4 newly_done (row t < I =
             instance t)
      col 1: inst
      cols 2..2+FO: dest(16b) | take_mask << 16 per flow slot (dest == T
                    means no token placed)
    """
    flags = (
        ev["full_pass"].astype(jnp.int32)
        | (ev["task_arrive"].astype(jnp.int32) << 1)
        | (ev["task_done"].astype(jnp.int32) << 2)
        | (ev["no_match"].astype(jnp.int32) << 3)
    )
    newly = jnp.zeros(T, jnp.int32).at[:I].set(ev["newly_done"].astype(jnp.int32))
    flags = flags | (newly << 4) | (ev["elem"].astype(jnp.int32) << 5)
    dest_take = ev["dest"].astype(jnp.int32) | (ev["take_mask"].astype(jnp.int32) << 16)
    return jnp.concatenate(
        [flags[:, None], ev["inst"][:, None], dest_take],
        axis=1,
    )


def unpack_events(packed: np.ndarray, I: int) -> dict:
    """Host-side inverse of _pack_events for one step row ([T, 2+FO])."""
    flags = packed[:, 0]
    dest_take = packed[:, 2:]
    return {
        "full_pass": (flags & 1).astype(bool),
        "task_arrive": (flags & 2).astype(bool),
        "task_done": (flags & 4).astype(bool),
        "no_match": (flags & 8).astype(bool),
        "newly_done": (flags[:I] & 16).astype(bool),
        "elem": flags >> 5,
        "inst": packed[:, 1],
        "dest": dest_take & 0xFFFF,
        "take_mask": (dest_take >> 16).astype(bool),
    }


@partial(jax.jit, static_argnames=("n_steps", "config"))
def run_collect(tables: DeviceTables, state: dict, n_steps: int = 16, config=None):
    """Advance ``n_steps`` lock-steps in ONE device program, stacking each
    step's event tensors — the integration path's batched variant of calling
    ``step(emit_events=True)`` in a host loop. A quiesced state is a fixed
    point of ``step`` (no executing tokens → all masks false, no counters
    move), so over-running costs idle FLOPs but never wrong events.

    Returns (state', packed) where packed is ONE int32
    [n_steps, T*(2+FO) + 2] tensor — per-step rows of _pack_events flattened
    to 2-D before leaving the device (a [steps, T, C] output would be
    tile-padded on the last axis — lane size 128 — and the host fetch would
    transfer ~20x the real bytes over the TPU tunnel), with the post-step
    active-token count and the overflow flag appended as the final two
    scalars of each row. The host splits those off, reshapes to
    [steps, T, 2+FO], and decodes with unpack_events."""
    from zeebe_tpu.ops.tables import KernelConfig

    if config is None:
        config = KernelConfig()  # must mirror step()'s default resolution
    I = state["def_of"].shape[0]
    T = state["elem"].shape[0]

    FO = tables.out_target.shape[2]
    row_len = T * (2 + FO) + 2

    def body(carry):
        state, out, i, _ = carry
        state, ev = step(tables, state, auto_jobs=False, emit_events=True, config=config)
        active = (
            (state["elem"] >= 0)
            & ((state["phase"] == PHASE_AT) | (state["phase"] == PHASE_DONE))
        ).sum()
        if config.has_scopes or config.has_mi:
            # a parked scope whose inside just drained resumes next step —
            # it must count as active or the chunk loop would truncate the
            # decode right before the scope's completion events
            op2 = _scope_occupancy(tables, state)
            active = active + _scope_drained(
                tables, state, include_mi=config.has_mi, occ_pend=op2).sum()
            if config.has_mi:
                # a parked MI body with children left to spawn acts next step
                active = active + _mi_spawnable(tables, state,
                                                occ_pend=op2).sum()
        packed = _pack_events(ev, I, T).reshape(-1)
        # append (active, overflow) so the host needs exactly one device
        # fetch per chunk
        tail = jnp.stack([active.astype(jnp.int32),
                          state["overflow"].astype(jnp.int32)])
        out = jax.lax.dynamic_update_index_in_dim(
            out, jnp.concatenate([packed, tail]), i, 0)
        return state, out, i + 1, active > 0

    def cond(carry):
        _state, _out, i, go = carry
        return go & (i < n_steps)

    # early-exit loop (not scan): a quiesced state is a fixed point, so the
    # remaining steps of the chunk would only burn device FLOPs — short
    # cascades (a job completion advancing 2-3 steps) skip most of the chunk.
    # Unwritten rows stay zero; their active==0 tail is exactly the host's
    # truncation signal, and the host reads overflow from the LAST WRITTEN
    # row (cumulative in state), not the final buffer row.
    out0 = jnp.zeros((n_steps, row_len), jnp.int32)
    state, packed, _, _ = jax.lax.while_loop(
        cond, body, (state, out0, jnp.int32(0), jnp.bool_(True)))
    return state, packed


@partial(jax.jit, static_argnames=("max_steps", "auto_jobs", "config"))
def run_to_completion(tables: DeviceTables, state: dict, max_steps: int = 1000,
                      auto_jobs: bool = True, config=None):
    """Run steps until every instance is done (or max_steps) in one device
    program — no host round trips (the bench path)."""

    def cond(carry):
        state, steps = carry
        return (steps < max_steps) & jnp.any(state["elem"] >= 0)

    def body(carry):
        state, steps = carry
        state, _ = step(tables, state, auto_jobs=auto_jobs, emit_events=False, config=config)
        return state, steps + 1

    state, steps = jax.lax.while_loop(cond, body, (state, jnp.int32(0)))
    return state, steps


def complete_jobs(state: dict, token_slots: jax.Array, result_slots: jax.Array | None = None,
                  result_values: jax.Array | None = None) -> dict:
    """Host-side job completion (non-auto mode): move waiting tokens to
    PHASE_DONE, optionally writing job result variables into instance slots."""
    phase = state["phase"].at[token_slots].set(PHASE_DONE)
    new_state = dict(state)
    new_state["phase"] = phase
    if result_slots is not None and result_values is not None:
        vals = np.asarray(result_values)
        if vals.ndim == 2 and np.issubdtype(vals.dtype, np.integer):
            if vals.dtype != np.int32:
                # pre-packed planes in a wider dtype: coerce with the same
                # range check as _coerce_slot_planes — silent wraparound
                # would mint garbage order keys and mis-route conditions
                info = np.iinfo(np.int32)
                if ((vals < info.min) | (vals > info.max)).any():
                    raise ValueError("slot planes exceed int32 range")
                vals = vals.astype(np.int32)
        else:
            from zeebe_tpu.ops.tables import pack_slot_values

            vals = pack_slot_values(vals)  # float convenience → key planes
        inst = state["inst"][token_slots]
        new_state["var_slots"] = state["var_slots"].at[inst, result_slots].set(vals)
    return new_state
