"""Deploy-time compilation: ExecutableProcess → dense device tables.

This is the TPU-native re-expression of the reference's per-record interpreter
(BASELINE.json north star): at deploy time each process graph is lowered to
static int32 arrays — element opcodes, CSR flow adjacency, join arities — and
every FEEL sequence-flow condition is compiled to a fixed-length stack program
over per-instance variable slots holding 64-bit IEEE-754 total-order keys as
two int32 planes — device comparisons are bit-exact against the host's
float64 FEEL evaluator. The automaton kernel
(zeebe_tpu.ops.automaton) then advances thousands of instances lock-step with
no Python in the loop: a token's behavior is a predicated gather over these
tables, the BpmnElementProcessor switch becomes masked vector ops.

Multiple process definitions share one table set (padded to the max element
count) so a mixed workload (BASELINE config #5) runs in a single kernel:
``definition_of_instance`` selects each instance's row block.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from zeebe_tpu.feel import feel as F
from zeebe_tpu.models.bpmn import ExecutableProcess
from zeebe_tpu.protocol.enums import BpmnElementType, BpmnEventType

# condition VM opcodes
OP_NOP = 0
OP_PUSH_CONST = 1
OP_PUSH_VAR = 2
OP_LT = 3
OP_LE = 4
OP_GT = 5
OP_GE = 6
OP_EQ = 7
OP_NE = 8
OP_AND = 9
OP_OR = 10
OP_NOT = 11
# 12..15 were arithmetic (ADD/SUB/MUL/DIV) before the order-key plane
# encoding; arithmetic cannot run in key space and host-escapes at compile
# time, so the opcodes are retired — the VM treats the gap as invalid
OP_NEG = 16

MAX_PROG_LEN = 24
STACK_DEPTH = 8


class ConditionNotCompilable(Exception):
    """Condition uses features outside the device subset (strings, lists,
    functions) — the element falls back to host evaluation."""


@dataclasses.dataclass
class SlotMap:
    """Variable name → device slot assignment (shared across a table set).
    Each slot has a kind: ``num`` (the float value itself) or ``str`` (an
    interned string id, see StringInterner) — a variable used both ways in
    conditions cannot ride the device path."""

    names: dict[str, int] = dataclasses.field(default_factory=dict)
    kinds: dict[str, str] = dataclasses.field(default_factory=dict)

    def slot(self, name: str, kind: str = "num") -> int:
        existing = self.kinds.get(name)
        if existing is not None and existing != kind:
            raise ConditionNotCompilable(
                f"variable {name!r} used in both numeric and string comparisons"
            )
        self.kinds[name] = kind
        if name not in self.names:
            self.names[name] = len(self.names)
        return self.names[name]

    @property
    def count(self) -> int:
        return max(1, len(self.names))


# ---------------------------------------------------------------------------
# Exact slot encoding: every slot value is a 64-bit ORDER KEY split into two
# int32 planes (hi, lo). Numeric values use the IEEE-754 total-order key of
# their float64 bits, so device comparisons are BIT-EXACT against the host's
# float64 FEEL evaluator — there is no float32 rounding anywhere on the
# device path. String values use their interned id (assigned in sorted
# order, so id order == lexicographic order for strings the tables know).
# Arithmetic inside conditions cannot run in key space and host-escapes the
# gateway instead (ConditionNotCompilable), which is what deletes the old
# "float32 within ~1e-7 of the boundary" divergence.

_U64 = np.uint64
_SIGN64 = _U64(1) << _U64(63)
_BIAS32 = np.uint32(0x80000000)

# String encoding: literal j (sorted order) → key 2j; a runtime string the
# tables never saw → 2·bisect(literals, s) − 1, i.e. an ODD key strictly
# between its lexicographic neighbors. Every comparison of a variable
# against a LITERAL is then exact (EQ: odd keys never equal even literal
# keys; order: insertion rank sits on the correct side of every literal).
# Var-vs-var string comparisons never lower: the compiler only types a slot
# "str" when the comparison's other side is a string literal, so `a = b`
# types both as numeric — admission then declines string values (or the
# gateway host-escapes on a kind conflict). Two unknown strings therefore
# never meet on device, where their colliding odd keys would diverge.


def f64_exact(v) -> bool:
    """True when ``v`` is exactly representable as a float64 (ints beyond
    2^53 collapse into a neighbor; host FEEL compares Python ints exactly,
    so such values must never be lowered to an order key)."""
    if type(v) is not int:
        return True
    try:
        return int(float(v)) == v
    except OverflowError:
        return False


def f64_key_planes(x: float) -> tuple[int, int]:
    """float64 → (hi, lo) int32 planes of its total-order key. Monotone:
    x < y  ⟺  (hi_x, lo_x) < (hi_y, lo_y) lexicographically (signed)."""
    v = np.float64(x)
    if np.isnan(v):
        raise ValueError("NaN has no order key")
    if v == 0.0:
        v = np.float64(0.0)  # canonicalize -0.0
    b = v.view(_U64)
    k = ~b if (b & _SIGN64) else (b | _SIGN64)
    hi = np.int32((np.uint32(k >> _U64(32)) ^ _BIAS32).astype(np.int32))
    lo = np.int32((np.uint32(k & _U64(0xFFFFFFFF)) ^ _BIAS32).astype(np.int32))
    return int(hi), int(lo)


def pack_slot_values(values: np.ndarray) -> np.ndarray:
    """Vectorized ``f64_key_planes``: float array [...] → int32 [..., 2]."""
    v = np.asarray(values, np.float64)
    v = np.where(v == 0.0, 0.0, v)  # canonicalize -0.0
    b = v.view(_U64)
    neg = (b & _SIGN64).astype(bool)
    k = np.where(neg, ~b, b | _SIGN64)
    hi = ((k >> _U64(32)).astype(np.uint32) ^ _BIAS32).astype(np.int32)
    lo = ((k & _U64(0xFFFFFFFF)).astype(np.uint32) ^ _BIAS32).astype(np.int32)
    return np.stack([hi, lo], axis=-1)


def str_key_planes(interned_id: int) -> tuple[int, int]:
    """Interned string id → (hi, lo) planes: literal j maps to key 2j (the
    odd keys in between belong to unknown runtime strings)."""
    return 2 * int(interned_id), 0


@dataclasses.dataclass
class StringInterner:
    """String literal → device id (the host variable-store ↔ device-slot
    split of SURVEY §7 hard part (c): documents stay host-side; conditions
    read prefetched slots holding either the numeric order key or the
    interned id of the string value). Ids are assigned in SORTED order over
    the full literal set (compile_tables pre-pass), so id comparisons agree
    with lexicographic string comparisons for known strings."""

    ids: dict[str, int] = dataclasses.field(default_factory=dict)
    _sorted: list[str] = dataclasses.field(default_factory=list)

    def intern_sorted(self, values: set[str]) -> None:
        """Assign ids for the whole literal set at once, lexicographically."""
        self._sorted = sorted(values | set(self.ids))
        for i, v in enumerate(self._sorted):
            self.ids[v] = i

    def intern(self, value: str) -> int:
        idx = self.ids.get(value)
        if idx is None:
            raise ConditionNotCompilable(
                f"string literal {value!r} missing from the interner pre-pass"
            )
        return idx

    def id_of(self, value: str) -> int | None:
        """Runtime lookup: None = the tables never saw this string."""
        return self.ids.get(value)

    def order_key_of(self, value: str) -> tuple[int, bool]:
        """Runtime string → (order-key hi plane, known). Known literal j →
        2j; unknown → the odd insertion-rank key between its neighbors."""
        import bisect

        idx = self.ids.get(value)
        if idx is not None:
            return 2 * idx, True
        return 2 * bisect.bisect_left(self._sorted, value) - 1, False


def collect_condition_strings(ast) -> set[str]:
    """Pre-pass: every string literal in a condition AST (the interner
    assigns sorted ids over the union before compilation)."""
    out: set[str] = set()

    def walk(node) -> None:
        if isinstance(node, F.Lit) and isinstance(node.value, str):
            out.add(node.value)
        elif isinstance(node, F.Bin):
            walk(node.left)
            walk(node.right)
        elif isinstance(node, F.Unary):
            walk(node.operand)
        elif isinstance(node, F.Call):
            for a in node.args:
                walk(a)

    walk(ast)
    return out


def compile_condition(ast, slots: SlotMap,
                      interner: StringInterner | None = None,
                      ) -> list[tuple[int, int, int]]:
    """Lower a FEEL AST to a postfix stack program over (hi, lo) order-key
    planes. Raises ConditionNotCompilable for constructs outside the device
    subset.

    The compile is TYPED: comparisons take value operands (variable slots,
    numeric/string/bool literals) and produce booleans; and/or/not take
    booleans only (matching host FEEL semantics, where `1.0 and true` is
    null — the old untyped min/max lowering silently diverged there).
    Arithmetic (+ - * /) cannot run in order-key space and host-escapes —
    which is exactly what makes every device comparison bit-exact against
    the host float64 evaluator."""
    prog: list[tuple[int, int, int]] = []

    def is_str_lit(node) -> bool:
        return isinstance(node, F.Lit) and isinstance(node.value, str)

    def emit_value(node) -> str:
        """Emit a value operand; returns its kind: 'num' or 'str'."""
        if isinstance(node, F.Lit):
            v = node.value
            if isinstance(v, bool):
                prog.append((OP_PUSH_CONST, *f64_key_planes(1.0 if v else 0.0)))
                return "num"
            if isinstance(v, (int, float)):
                if not f64_exact(v):
                    # not float64-representable (beyond 2^53): the key would
                    # be the rounded neighbor's and EQ against the true value
                    # would diverge from the host's exact int comparison
                    raise ConditionNotCompilable(f"int literal {v} beyond f64")
                prog.append((OP_PUSH_CONST, *f64_key_planes(float(v))))
                return "num"
            if isinstance(v, str):
                if interner is None:
                    raise ConditionNotCompilable("string literal (no interner)")
                prog.append((OP_PUSH_CONST, *str_key_planes(interner.intern(v))))
                return "str"
            raise ConditionNotCompilable(f"literal {v!r}")
        if isinstance(node, F.Var):
            if len(node.path) != 1:
                raise ConditionNotCompilable(f"path {node.path}")
            # kind is fixed by the comparison partner via _slot_kind below;
            # a bare var defaults to numeric
            prog.append((OP_PUSH_VAR, slots.slot(node.path[0], kind="num"), 0))
            return "num"
        if isinstance(node, F.Unary):
            operand = node.operand
            if isinstance(operand, F.Lit) and isinstance(operand.value, (int, float)) \
                    and not isinstance(operand.value, bool):
                ov = operand.value
                if not f64_exact(ov):
                    raise ConditionNotCompilable(f"int literal {ov} beyond f64")
                # constant-fold: push the key of the negated literal
                prog.append((OP_PUSH_CONST, *f64_key_planes(-float(ov))))
                return "num"
            kind = emit_value(operand)
            if kind != "num":
                raise ConditionNotCompilable("unary minus on non-number")
            prog.append((OP_NEG, 0, 0))
            return "num"
        raise ConditionNotCompilable(type(node).__name__)

    def emit_comparison(node) -> None:
        # a slot is typed "str" ONLY opposite a string literal, so device
        # programs never compare two string slots with each other (see the
        # string-encoding note above — unknown odd keys must not meet)
        str_side = is_str_lit(node.left) or is_str_lit(node.right)
        if str_side:
            if interner is None:
                raise ConditionNotCompilable("string literal (no interner)")
            for operand in (node.left, node.right):
                if is_str_lit(operand):
                    prog.append((OP_PUSH_CONST, *str_key_planes(interner.intern(operand.value))))
                elif isinstance(operand, F.Var) and len(operand.path) == 1:
                    prog.append((OP_PUSH_VAR, slots.slot(operand.path[0], kind="str"), 0))
                else:
                    raise ConditionNotCompilable("string comparison operand")
        else:
            emit_value(node.left)
            emit_value(node.right)
        cmp_ops = {"<": OP_LT, "<=": OP_LE, ">": OP_GT, ">=": OP_GE,
                   "=": OP_EQ, "!=": OP_NE}
        prog.append((cmp_ops[node.op], 0, 0))

    def emit_bool(node) -> None:
        if isinstance(node, F.Lit) and isinstance(node.value, bool):
            prog.append((OP_PUSH_CONST, 1 if node.value else 0, 0))
            return
        if isinstance(node, F.Call) and node.name == "not" and len(node.args) == 1:
            emit_bool(node.args[0])
            prog.append((OP_NOT, 0, 0))
            return
        if isinstance(node, F.Bin):
            if node.op in ("and", "or"):
                emit_bool(node.left)
                emit_bool(node.right)
                prog.append((OP_AND if node.op == "and" else OP_OR, 0, 0))
                return
            if node.op in ("<", "<=", ">", ">=", "=", "!="):
                emit_comparison(node)
                return
            raise ConditionNotCompilable(f"operator {node.op}")
        raise ConditionNotCompilable(f"non-boolean condition {type(node).__name__}")

    emit_bool(ast)
    if len(prog) > MAX_PROG_LEN:
        raise ConditionNotCompilable(f"program too long ({len(prog)})")
    return prog


# device opcodes per element behavior (indexes the kernel's behavior masks)
K_NONE = 0  # unused slot / process root
K_PASS = 1  # pass-through: start/end/manual/undefined/throw events
K_TASK = 2  # job-worker task: wait for job completion
K_EXCLUSIVE = 3  # exclusive gateway: conditional routing
K_FORK = 4  # parallel gateway, fan-out
K_JOIN = 5  # parallel gateway, fan-in (in_count > 1)
K_END = 6  # end event: token dies, instance may complete
K_CATCH = 7  # intermediate catch (timer/message): wait for host trigger/correlation
K_SCOPE = 8  # embedded sub-process: spawn inner token, park until scope drains
K_HOST = 9  # host escape: parks forever; the sequential engine owns the element
#            (script/io-mapping tasks, unresolvable call activities, …)
K_MI = 10  # multi-instance body: parks like a scope, spawns mi_left children
#           at its inner row (scope_start); sequential bodies respawn on drain
K_INCLUSIVE = 11  # inclusive gateway (fork-only, like the reference): takes
#                  EVERY true-condition flow; default only when none hold

# task types a synthetic device MI body may wrap (the inner instance is a
# job-worker task; MI on containers stays host-side)
_MI_BODY_TYPES = frozenset((
    BpmnElementType.SERVICE_TASK,
    BpmnElementType.SEND_TASK,
    BpmnElementType.SCRIPT_TASK,
    BpmnElementType.BUSINESS_RULE_TASK,
    BpmnElementType.USER_TASK,
))

_KERNEL_OP = {
    BpmnElementType.START_EVENT: K_PASS,
    BpmnElementType.MANUAL_TASK: K_PASS,
    BpmnElementType.TASK: K_PASS,
    BpmnElementType.INTERMEDIATE_THROW_EVENT: K_PASS,
    BpmnElementType.END_EVENT: K_END,
    BpmnElementType.SERVICE_TASK: K_TASK,
    BpmnElementType.SEND_TASK: K_TASK,
    BpmnElementType.SCRIPT_TASK: K_TASK,
    BpmnElementType.BUSINESS_RULE_TASK: K_TASK,
    BpmnElementType.USER_TASK: K_TASK,
    BpmnElementType.EXCLUSIVE_GATEWAY: K_EXCLUSIVE,
    BpmnElementType.INCLUSIVE_GATEWAY: K_INCLUSIVE,
    BpmnElementType.PARALLEL_GATEWAY: K_FORK,  # switched to K_JOIN if in_count > 1
}


@dataclasses.dataclass
class ProcessTables:
    """Dense tables for a set of process definitions (numpy; the kernel moves
    them to device). Shapes: D definitions, E max elements, FL max flows,
    C conditions, FO max fan-out."""

    # per definition × element
    kernel_op: np.ndarray  # [D, E] int32
    in_count: np.ndarray  # [D, E] int32 (join arity)
    job_type: np.ndarray  # [D, E] int32, -1 = none
    out_count: np.ndarray  # [D, E] int32
    out_target: np.ndarray  # [D, E, FO] int32 (element idx, -1 pad)
    out_cond: np.ndarray  # [D, E, FO] int32 (condition row, -1 = unconditional)
    out_flow_idx: np.ndarray  # [D, E, FO] int32 (model flow idx, for events)
    default_slot: np.ndarray  # [D, E] int32 (slot in out_* arrays, -1 none)
    start_elem: np.ndarray  # [D] int32
    elem_count: np.ndarray  # [D] int32
    # embedded sub-process scopes
    scope_start: np.ndarray  # [D, E] int32 (inner none-start of a K_SCOPE, -1;
    #                          for K_MI bodies: the synthetic inner row)
    in_scope: np.ndarray  # [D, E, E] int8: [d, e, s] = e strictly inside scope s
    # multi-instance bodies: 1 = sequential (spawn next child only after the
    # previous drains); 0 = parallel (spawn every step until mi_left == 0)
    mi_sequential: np.ndarray  # [D, E] int8
    # condition programs (order-key planes: args carry (hi, lo) per step)
    cond_ops: np.ndarray  # [C, P] int32
    cond_args: np.ndarray  # [C, P, 2] int32
    # per definition: variable names its DEVICE-compiled conditions read
    # (host-escaped gateways excluded — their variables need no prefetch)
    cond_vars_by_def: list = dataclasses.field(default_factory=list)
    # bookkeeping
    slot_map: SlotMap = dataclasses.field(default_factory=SlotMap)
    interner: StringInterner = dataclasses.field(default_factory=StringInterner)
    job_type_names: list[str] = dataclasses.field(default_factory=list)
    definitions: list[ExecutableProcess] = dataclasses.field(default_factory=list)
    # static bound on live tokens per instance, max over the set's
    # definitions; 0 = no sound bound (a parallel split on a cycle can
    # multiply tokens per iteration) — callers then size the token pool
    # with the legacy 4x safety factor
    token_width: int = 0

    @property
    def num_definitions(self) -> int:
        return self.kernel_op.shape[0]

    @property
    def max_elements(self) -> int:
        return self.kernel_op.shape[1]

    @property
    def num_slots(self) -> int:
        return self.slot_map.count

    @property
    def kernel_config(self) -> "KernelConfig":
        return KernelConfig(
            has_joins=bool((self.kernel_op == 5).any()),  # K_JOIN
            has_conditions=bool((self.out_cond >= 0).any()),
            has_scopes=bool((self.kernel_op == 8).any()),  # K_SCOPE
            has_mi=bool((self.kernel_op == 10).any()),  # K_MI
        )


@dataclasses.dataclass(frozen=True)
class KernelConfig:
    """Static (hashable) workload traits; lets XLA drop unused machinery —
    join ranking sorts, the condition VM, and the scope-occupancy reduction
    cost real time when the deployed process set never exercises them."""

    has_joins: bool = True
    has_conditions: bool = True
    has_scopes: bool = True
    has_mi: bool = False


def _live_token_width(exe: ExecutableProcess) -> int | None:
    """Sound static bound on concurrently live device tokens per instance of
    ``exe``: 1, plus (fanout-1) per parallel split, plus 1 per sub-process
    scope (the parked scope token coexists with its inner token). Additive,
    so nesting is covered.

    The per-element +1 assumes at most one concurrent activation of each
    element, which only holds when concurrency is structured. So the bound
    is claimed (non-None) only when, in the presence of parallel splits,
    every convergent element (incoming > 1) is a parallel join — an XOR
    merge downstream of a split can funnel two live tokens through one
    element (twice-activated sub-process / split), breaking the additive
    count. A parallel split on a cycle can mint tokens every iteration, so
    that also yields None. The kernel falls back to the 4x pool on None; an
    undersized pool would only cost a fallback (overflow is detected), but
    fallbacks re-run the whole group sequentially, so the bound must hold."""
    targets_of: dict[int, list[int]] = {}
    splits: list[ExecutableElement] = []
    for el in exe.elements:
        targets_of[el.idx] = [exe.flows[f].target_idx for f in el.outgoing]
        if el.link_target_idx >= 0:
            # link jumps continue the token like a flow — a backward link
            # closes a cycle the flow graph alone would not show
            targets_of[el.idx].append(el.link_target_idx)
        if (el.element_type in (BpmnElementType.PARALLEL_GATEWAY,
                                BpmnElementType.INCLUSIVE_GATEWAY)
                and len(el.outgoing) > 1):
            # an inclusive fork may take every branch — bound like a
            # parallel split
            splits.append(el)
    if splits:
        for el in exe.elements:
            if (el.incoming_count > 1
                    and el.element_type != BpmnElementType.PARALLEL_GATEWAY):
                return None  # unstructured convergence: element may run twice
    for el in exe.elements[1:]:
        if el.multi_instance is not None and el.child_start_idx >= 0:
            # a parallel MI body spawns cardinality-many children — no
            # static bound; callers size the pool from the predicted cards
            return None
    width = 1
    for el in exe.elements[1:]:
        # every scope container parks one token while its inside runs: embedded
        # sub-processes, and (synthetic inlined definitions) call activities
        # plus their child-root placeholder rows
        if el.element_type == BpmnElementType.SUB_PROCESS or (
            el.element_type in (BpmnElementType.CALL_ACTIVITY,
                                BpmnElementType.PROCESS)
            and el.child_start_idx >= 0
        ):
            width += 1
    for el in splits:
        # cycle check: DFS from the split's targets back to the split
        seen: set[int] = set()
        stack = list(targets_of[el.idx])
        while stack:
            n = stack.pop()
            if n == el.idx:
                return None
            if n in seen:
                continue
            seen.add(n)
            stack.extend(targets_of.get(n, ()))
        width += len(el.outgoing) - 1
    return width


def compile_tables(processes: list[ExecutableProcess], max_fanout: int | None = None,
                   host_idxs: list[set[int]] | None = None) -> ProcessTables:
    """Compile process definitions into one shared table set. ``max_fanout``
    defaults to the actual maximum across the definitions (smaller FO keeps
    the kernel's flattened placement arrays tight).

    ``host_idxs`` (one set of element idxs per definition) turns on the host
    escape: listed elements — and any element that fails to lower — compile
    to K_HOST instead of failing the whole definition. Without it, any
    non-lowerable element raises ConditionNotCompilable (the all-device
    contract the benchmarks and the bare-kernel tests rely on)."""
    if max_fanout is None:
        max_fanout = max(
            (len(el.outgoing) for p in processes for el in p.elements), default=1
        )
        max_fanout = max(max_fanout, 1)
    slots = SlotMap()
    interner = StringInterner()
    # pre-pass: intern ALL condition string literals in sorted order so id
    # comparisons agree with lexicographic string order
    all_strings: set[str] = set()
    for p in processes:
        for el in p.elements[1:]:
            for fidx in el.outgoing:
                cond = p.flows[fidx].condition
                if cond is not None:
                    all_strings |= collect_condition_strings(cond.ast)
    interner.intern_sorted(all_strings)
    job_types: dict[str, int] = {}
    cond_programs: list[list[tuple[int, int, int]]] = []

    D = len(processes)
    E = max(len(p.elements) for p in processes)
    kernel_op = np.zeros((D, E), np.int32)
    in_count = np.zeros((D, E), np.int32)
    job_type = np.full((D, E), -1, np.int32)
    out_count = np.zeros((D, E), np.int32)
    out_target = np.full((D, E, max_fanout), -1, np.int32)
    out_cond = np.full((D, E, max_fanout), -1, np.int32)
    out_flow_idx = np.full((D, E, max_fanout), -1, np.int32)
    default_slot = np.full((D, E), -1, np.int32)
    start_elem = np.zeros(D, np.int32)
    elem_count = np.zeros(D, np.int32)
    scope_start = np.full((D, E), -1, np.int32)
    in_scope = np.zeros((D, E, E), np.int8)
    mi_seq = np.zeros((D, E), np.int8)

    cond_vars_by_def: list[set[str]] = []
    for d, exe in enumerate(processes):
        elem_count[d] = len(exe.elements)
        start_elem[d] = exe.none_start_of(0)
        def_vars: set[str] = set()
        cond_vars_by_def.append(def_vars)
        host = set(host_idxs[d]) if host_idxs is not None else None
        for el in exe.elements[1:]:
            # structural info fills unconditionally: flows INTO a host-escaped
            # element still resolve their target through these arrays, and a
            # parked host token's incoming count is never read
            in_count[d, el.idx] = el.incoming_count
            if len(el.outgoing) > max_fanout:
                raise ConditionNotCompilable(f"fan-out {len(el.outgoing)} > {max_fanout}")
            out_count[d, el.idx] = len(el.outgoing)
            for slot_i, fidx in enumerate(el.outgoing):
                flow = exe.flows[fidx]
                out_target[d, el.idx, slot_i] = flow.target_idx
                out_flow_idx[d, el.idx, slot_i] = flow.idx
            if (
                el.element_type == BpmnElementType.INTERMEDIATE_THROW_EVENT
                and el.event_type == BpmnEventType.LINK
                and el.link_target_idx >= 0
                and not el.outgoing
            ):
                # link throw: synthetic edge to the same-scope catch link.
                # out_flow_idx = -1 marks it as a link jump — no sequence
                # flow exists, so decode emits the catch ACTIVATE without a
                # SEQUENCE_FLOW_TAKEN (engine _complete link branch parity)
                out_count[d, el.idx] = 1
                out_target[d, el.idx, 0] = el.link_target_idx
                out_flow_idx[d, el.idx, 0] = -1
            # scope chains of embedded sub-processes are supported (K_SCOPE),
            # and — in synthetic inlined definitions (kernel_backend
            # _inline_call_activities) — chains through CALL_ACTIVITY rows
            # and their non-root PROCESS placeholder rows; a chain through
            # any other container (event sub-process) means the element is
            # only reachable host-side
            chain: list[int] = []
            anc = el.parent_idx
            chain_ok = True
            while anc > 0:
                parent = exe.elements[anc]
                if parent.element_type not in (BpmnElementType.SUB_PROCESS,
                                               BpmnElementType.CALL_ACTIVITY,
                                               BpmnElementType.PROCESS) \
                        and not (parent.multi_instance is not None
                                 and parent.child_start_idx >= 0):
                    # synthetic K_MI bodies (kernel_backend._inline_mi_bodies)
                    # contain their inner row like a scope
                    chain_ok = False
                    break
                chain.append(anc)
                anc = parent.parent_idx
            if chain_ok:
                # committed even for host-escaped elements: a parked host
                # token inside a device scope must block that scope's drain
                for a in chain:
                    in_scope[d, el.idx, a] = 1
            try:
                if not chain_ok:
                    raise ConditionNotCompilable(
                        f"element inside {exe.elements[anc].element_type.name} scope"
                    )
                if host is not None and el.idx in host:
                    raise ConditionNotCompilable("host-escaped element")
                if getattr(el, "form_id", None) is not None:
                    # form resolution reads FormState at activation time (the
                    # formKey header depends on the latest deployed form)
                    raise ConditionNotCompilable("form-linked user task")
                if (el.element_type == BpmnElementType.SCRIPT_TASK
                        and el.script_expression is not None):
                    # expression-flavor script task: pass-through on device,
                    # evaluation + result write happen at decode (the
                    # job-worker flavor keeps K_TASK via _KERNEL_OP)
                    op = K_PASS
                elif el.event_type == BpmnEventType.LINK and el.element_type in (
                    BpmnElementType.INTERMEDIATE_THROW_EVENT,
                    BpmnElementType.INTERMEDIATE_CATCH_EVENT,
                ):
                    # link events are device pass-throughs: the throw rides
                    # its synthetic edge (filled above), the catch completes
                    # immediately and takes its real outgoing flows
                    op = K_PASS
                elif (el.element_type in (BpmnElementType.INTERMEDIATE_CATCH_EVENT,
                                          BpmnElementType.RECEIVE_TASK)) and (
                    (el.timer_duration is not None and not el.timer_cycle
                     and el.timer_date is None)
                    or el.message_name is not None
                    or el.signal_name is not None
                ):
                    # waits like a task; the host resumes it on TIMER TRIGGER /
                    # message correlation instead of job completion
                    op = K_CATCH
                elif el.element_type == BpmnElementType.BOUNDARY_EVENT:
                    # boundary events never receive device tokens spontaneously —
                    # triggers route through the sequential path (route_trigger),
                    # which terminates/continues via internal commands. The
                    # element only needs a valid opcode so definitions carrying
                    # boundaries still lower to tables.
                    op = K_PASS
                elif el.multi_instance is not None:
                    # synthetic MI body (kernel_backend._inline_mi_bodies):
                    # a TASK-type element whose child_start_idx names the
                    # synthetic inner row; parks like a scope and spawns
                    # mi_left children (ops/automaton K_MI). Real elements
                    # with loop characteristics (incl. MI sub-processes,
                    # whose child_start is their own scope start) are
                    # outside the device subset.
                    if (el.child_start_idx < 0
                            or el.element_type not in _MI_BODY_TYPES):
                        raise ConditionNotCompilable("multi-instance body")
                    op = K_MI
                    mi_seq[d, el.idx] = 1 if el.multi_instance.is_sequential else 0
                elif el.element_type in (BpmnElementType.SUB_PROCESS,
                                         BpmnElementType.CALL_ACTIVITY,
                                         BpmnElementType.PROCESS):
                    # CALL_ACTIVITY / non-root PROCESS rows exist only in
                    # synthetic inlined definitions: the call activity and
                    # its child-root placeholder both park as scopes over the
                    # inlined child rows (kernel_backend._inline_call_activities)
                    if el.child_start_idx < 0:
                        raise ConditionNotCompilable("scope without none start")
                    op = K_SCOPE
                elif el.element_type == BpmnElementType.EVENT_BASED_GATEWAY:
                    # parks like a catch; the first trigger routes through the
                    # sequential path (route_trigger → COMPLETE_ELEMENT with
                    # triggeredElementId), so the device never takes its flows
                    op = K_CATCH
                else:
                    op = _KERNEL_OP.get(el.element_type)
                if op is None:
                    raise ConditionNotCompilable(f"element type {el.element_type.name}")
                if el.element_type == BpmnElementType.PARALLEL_GATEWAY and el.incoming_count > 1:
                    op = K_JOIN
                if (
                    op in (K_EXCLUSIVE, K_INCLUSIVE)
                    and len(el.outgoing) == 1
                    and el.default_flow_idx < 0
                    and all(exe.flows[f].condition is None for f in el.outgoing)
                ):
                    # a single unconditional outgoing flow routes like a
                    # pass-through (the engine's generic completion path takes
                    # it; a conditional gateway with no true condition and no
                    # default would stall instead)
                    op = K_PASS
                for slot_i, fidx in enumerate(el.outgoing):
                    flow = exe.flows[fidx]
                    if fidx == el.default_flow_idx:
                        default_slot[d, el.idx] = slot_i
                    elif flow.condition is not None and op in (K_EXCLUSIVE,
                                                               K_INCLUSIVE):
                        prog = compile_condition(flow.condition.ast, slots, interner)
                        out_cond[d, el.idx, slot_i] = len(cond_programs)
                        cond_programs.append(prog)
                        id_to_name = {v: k for k, v in slots.names.items()}
                        def_vars.update(
                            id_to_name[int(hi)] for opc, hi, lo in prog
                            if opc == OP_PUSH_VAR
                        )
            except ConditionNotCompilable:
                if host is None:
                    raise
                # host escape: the device parks any token that reaches this
                # element and the sequential engine owns it from there —
                # the rest of the definition still rides the kernel
                host.add(el.idx)
                kernel_op[d, el.idx] = K_HOST
                out_cond[d, el.idx, :] = -1
                default_slot[d, el.idx] = -1
                continue
            kernel_op[d, el.idx] = op
            if op == K_SCOPE or op == K_MI:
                scope_start[d, el.idx] = el.child_start_idx
            if op == K_TASK and el.job_type is not None and el.job_type.is_static:
                name = el.job_type.source
                if name not in job_types:
                    job_types[name] = len(job_types)
                job_type[d, el.idx] = job_types[name]

    C = max(1, len(cond_programs))
    cond_ops = np.zeros((C, MAX_PROG_LEN), np.int32)
    cond_args = np.zeros((C, MAX_PROG_LEN, 2), np.int32)
    for ci, prog in enumerate(cond_programs):
        for pi, (op, hi, lo) in enumerate(prog):
            cond_ops[ci, pi] = op
            cond_args[ci, pi, 0] = hi
            cond_args[ci, pi, 1] = lo

    return ProcessTables(
        kernel_op=kernel_op,
        in_count=in_count,
        job_type=job_type,
        out_count=out_count,
        out_target=out_target,
        out_cond=out_cond,
        out_flow_idx=out_flow_idx,
        default_slot=default_slot,
        start_elem=start_elem,
        elem_count=elem_count,
        scope_start=scope_start,
        in_scope=in_scope,
        mi_sequential=mi_seq,
        cond_ops=cond_ops,
        cond_args=cond_args,
        cond_vars_by_def=cond_vars_by_def,
        slot_map=slots,
        interner=interner,
        job_type_names=list(job_types),
        definitions=list(processes),
        token_width=_set_token_width(processes),
    )


def _set_token_width(processes: list[ExecutableProcess]) -> int:
    widths = [_live_token_width(p) for p in processes]
    return 0 if None in widths else max(widths, default=1)
