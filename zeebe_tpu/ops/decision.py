"""Batched DMN decision-table evaluation on device.

The reference evaluates decision tables one context at a time
(dmn/src/main/java/io/camunda/zeebe/dmn/impl/DmnDecisionEngine + the
embedded FEEL engine); this module is the TPU-native batch path the kernel
docstring reserves: a table compiles ONCE to dense int32 atom arrays over
the same IEEE-754 total-order key planes the condition VM uses
(ops/tables.f64_key_planes), and one jitted program evaluates N contexts ×
R rules in a single fused pass — unary-test matching, hit-policy
selection, and COLLECT aggregation with no Python in the loop.

Device subset (everything else raises NotDeviceCompilable and stays on the
host evaluator, zeebe_tpu.dmn):
- inputs: bare-variable (or missing → null) numeric/string values
- unary tests: "-", numeric comparisons (< <= > >=) against literals,
  intervals with any open/closed ends, numeric/string equality, and
  top-level disjunctions of those
- hit policies: UNIQUE, FIRST, ANY, RULE ORDER/COLLECT (matched sets),
  and COLLECT SUM/MIN/MAX/COUNT over numeric output literals

Results come back as per-context RULE INDICES (or aggregates); the host
maps indices to output documents — output values never need a device
representation. Matching is BIT-EXACT against the host unary-test
evaluator for the admitted subset: both compare float64 order keys.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from zeebe_tpu.feel.feel import FeelError, Lit, Unary, parse_feel
from zeebe_tpu.ops.tables import f64_key_planes, pack_slot_values

# atom kinds
A_PAD = 0  # never matches (padding)
A_TRUE = 1  # "-" / empty: matches anything, null included
A_RANGE = 2  # lo <= value <= hi over numeric order keys (open/closed ends)
A_EQ = 3  # exact key equality (numeric or interned string)

# flags bits
F_LO_OPEN = 1
F_HI_OPEN = 2

_INT32_MIN = -(2**31)
_INT32_MAX = 2**31 - 1


class NotDeviceCompilable(Exception):
    """Table uses features outside the device subset — host evaluator owns it."""


@dataclasses.dataclass
class DeviceDecisionTable:
    """One compiled decision table: [I inputs, R rules, K atoms per cell]."""

    decision_id: str
    hit_policy: str
    aggregation: str  # "" | "SUM" | "MIN" | "MAX" | "COUNT"
    input_names: list[str]  # bare variable per input column
    input_kinds: list[str]  # "num" | "str" per column
    # atom arrays [I, R, K]
    kind: np.ndarray  # int32 A_*
    lo: np.ndarray  # [I, R, K, 2] int32 key planes
    hi: np.ndarray  # [I, R, K, 2]
    flags: np.ndarray  # int32
    # per-rule numeric FIRST-output literal key value (COLLECT aggregation);
    # NaN-free float64 — only present when aggregation != ""
    out_values: np.ndarray  # [R] float64
    # string interning for input values: literal → id in SORTED order
    str_ids: dict[str, int]
    num_rules: int

    def pack_contexts(self, contexts: list[dict]) -> tuple[np.ndarray, np.ndarray]:
        """Contexts → ([N, I, 2] key planes, [N, I] validity). A null/missing
        input or a type the column cannot key (document, unknown string in
        an EQ-only column is FINE — it gets an odd rank key) matches only
        A_TRUE atoms; validity=0 marks those."""
        import bisect

        N, I = len(contexts), len(self.input_names)
        vals = np.zeros((N, I), np.float64)
        valid = np.zeros((N, I), np.bool_)
        keys = np.zeros((N, I, 2), np.int32)
        sorted_lits = sorted(self.str_ids)
        for n, ctx in enumerate(contexts):
            for i, name in enumerate(self.input_names):
                v = ctx.get(name)
                if self.input_kinds[i] == "num":
                    if isinstance(v, bool):
                        # Python bool IS an int to the host evaluator
                        # (True == 1, True > 0) — key it as 1.0/0.0
                        vals[n, i] = 1.0 if v else 0.0
                        valid[n, i] = True
                        continue
                    if not isinstance(v, (int, float)):
                        continue
                    if isinstance(v, float) and v != v:
                        continue
                    vals[n, i] = float(v)
                    valid[n, i] = True
                else:
                    if not isinstance(v, str):
                        continue
                    idx = self.str_ids.get(v)
                    if idx is None:
                        # odd insertion-rank key: exact against every literal
                        keys[n, i, 0] = 2 * bisect.bisect_left(sorted_lits, v) - 1
                    else:
                        keys[n, i, 0] = 2 * idx
                    valid[n, i] = True
        num_mask = np.array([k == "num" for k in self.input_kinds], np.bool_)
        if num_mask.any():
            packed = pack_slot_values(vals)
            keys[:, num_mask] = packed[:, num_mask]
        return keys, valid


def _literal_of(expr) -> float | str | bool | None:
    """The python literal of a compiled FEEL endpoint, or raise."""
    ast = expr.ast
    if isinstance(ast, Lit):
        return ast.value
    if isinstance(ast, Unary) and isinstance(ast.operand, Lit) \
            and isinstance(ast.operand.value, (int, float)) \
            and not isinstance(ast.operand.value, bool):
        return -ast.operand.value
    raise NotDeviceCompilable("non-literal unary-test endpoint")


def _num_key(v) -> tuple[int, int]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise NotDeviceCompilable(f"non-numeric endpoint {v!r}")
    return f64_key_planes(float(v))


def compile_decision_table(decision, max_atoms: int = 4) -> DeviceDecisionTable:
    """Lower a ParsedDecision's table to device atom arrays. Raises
    NotDeviceCompilable outside the subset (callers keep the host path)."""
    from zeebe_tpu.dmn.dmn import _split_top_level

    if decision.kind != "decisionTable":
        raise NotDeviceCompilable("not a decision table")
    inputs = decision.inputs
    rules = decision.rules
    if not inputs or not rules:
        raise NotDeviceCompilable("empty table")
    hit = (decision.hit_policy or "UNIQUE").upper().replace("_", " ")
    agg = (decision.aggregation or "").upper()
    if hit not in ("UNIQUE", "FIRST", "ANY", "RULE ORDER", "COLLECT"):
        raise NotDeviceCompilable(f"hit policy {hit}")
    if agg and hit != "COLLECT":
        # the host applies aggregation only under COLLECT; compiling it here
        # would aggregate where the host selects
        raise NotDeviceCompilable(f"aggregation {agg} under {hit}")
    if agg and agg not in ("SUM", "MIN", "MAX", "COUNT"):
        raise NotDeviceCompilable(f"aggregation {agg}")
    if agg and len(decision.outputs) > 1:
        # the host raises a DmnEvalError for aggregated multi-output tables
        # (a modeling error must surface, not a partial aggregate)
        raise NotDeviceCompilable("aggregation over multiple outputs")

    input_names: list[str] = []
    for inp in inputs:
        src = (inp.expression_text or "").strip()
        if not src.isidentifier():
            raise NotDeviceCompilable(f"input expression {src!r}")
        input_names.append(src)

    # pre-pass: every string literal across all cells, interned sorted.
    # ANY parse failure (cells the host supports but this lexer cannot, e.g.
    # '?'-expressions) must surface as NotDeviceCompilable — the documented
    # keep-the-host-path contract
    strings: set[str] = set()
    parsed_cells: list[list[list]] = []  # [rule][input] -> list of atom specs
    for rule in rules:
        row: list[list] = []
        for text in rule.input_entries:
            try:
                row.append(_parse_cell_atoms(text, strings, _split_top_level))
            except FeelError as exc:
                raise NotDeviceCompilable(f"cell {text!r}: {exc}") from exc
        parsed_cells.append(row)
    str_ids = {s: i for i, s in enumerate(sorted(strings))}

    # column typing: a column is "str" when any atom uses a string literal;
    # mixing string and numeric atoms in one column leaves the subset
    kinds: list[str] = []
    I, R = len(inputs), len(rules)
    for i in range(I):
        col_kinds = set()
        for r in range(R):
            for spec in parsed_cells[r][i]:
                if spec[0] in ("eq_str",):
                    col_kinds.add("str")
                elif spec[0] in ("range", "eq_num"):
                    col_kinds.add("num")
        if len(col_kinds) > 1:
            raise NotDeviceCompilable("mixed string/number column")
        kinds.append(col_kinds.pop() if col_kinds else "num")

    K = max_atoms
    kind = np.zeros((I, R, K), np.int32)
    lo = np.zeros((I, R, K, 2), np.int32)
    hi = np.zeros((I, R, K, 2), np.int32)
    flags = np.zeros((I, R, K), np.int32)
    for r in range(R):
        for i in range(I):
            specs = parsed_cells[r][i]
            if len(specs) > K:
                raise NotDeviceCompilable(f"cell with {len(specs)} terms")
            for k, spec in enumerate(specs):
                if spec[0] == "true":
                    kind[i, r, k] = A_TRUE
                elif spec[0] == "eq_str":
                    kind[i, r, k] = A_EQ
                    lo[i, r, k, 0] = 2 * str_ids[spec[1]]
                elif spec[0] == "eq_num":
                    kind[i, r, k] = A_EQ
                    lo[i, r, k] = _num_key(spec[1])
                else:  # range
                    _tag, lo_v, hi_v, lo_open, hi_open = spec
                    kind[i, r, k] = A_RANGE
                    lo[i, r, k] = (_num_key(lo_v) if lo_v is not None
                                   else (_INT32_MIN, _INT32_MIN))
                    hi[i, r, k] = (_num_key(hi_v) if hi_v is not None
                                   else (_INT32_MAX, _INT32_MAX))
                    flags[i, r, k] = ((F_LO_OPEN if lo_open else 0)
                                      | (F_HI_OPEN if hi_open else 0))

    out_values = np.zeros(R, np.float64)
    if agg:
        for r, rule in enumerate(rules):
            try:
                v = _literal_of(parse_feel(rule.output_entries[0]))
            except NotDeviceCompilable:
                raise
            except Exception as exc:  # noqa: BLE001 — parse errors included
                raise NotDeviceCompilable(f"aggregated output: {exc}") from exc
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise NotDeviceCompilable("non-numeric aggregated output")
            out_values[r] = float(v)

    return DeviceDecisionTable(
        decision_id=decision.decision_id,
        hit_policy=hit,
        aggregation=agg,
        input_names=input_names,
        input_kinds=kinds,
        kind=kind, lo=lo, hi=hi, flags=flags,
        out_values=out_values,
        str_ids=str_ids,
        num_rules=R,
    )


def _parse_cell_atoms(text: str, strings: set[str], split_top_level) -> list:
    """One unary-test cell → atom specs. Raises NotDeviceCompilable."""
    text = (text or "").strip()
    if text in ("", "-"):
        return [("true",)]
    atoms: list = []
    for part in split_top_level(text):
        part = part.strip()
        if part in ("", "-"):
            atoms.append(("true",))
            continue
        if part.startswith("not("):
            raise NotDeviceCompilable("not(...) cell")
        if part[0] in "[(]" and ".." in part and part[-1] in "])[":
            lo_text, hi_text = part[1:-1].split("..", 1)
            lo_v = _literal_of(parse_feel(lo_text.strip()))
            hi_v = _literal_of(parse_feel(hi_text.strip()))
            atoms.append(("range", lo_v, hi_v,
                          part[0] != "[", part[-1] != "]"))
            continue
        matched = False
        for op in ("<=", ">=", "<", ">"):
            if part.startswith(op):
                v = _literal_of(parse_feel(part[len(op):].strip()))
                if op == "<":
                    atoms.append(("range", None, v, False, True))
                elif op == "<=":
                    atoms.append(("range", None, v, False, False))
                elif op == ">":
                    atoms.append(("range", v, None, True, False))
                else:
                    atoms.append(("range", v, None, False, False))
                matched = True
                break
        if matched:
            continue
        v = _literal_of(parse_feel(part))
        if isinstance(v, str):
            strings.add(v)
            atoms.append(("eq_str", v))
        elif isinstance(v, bool):
            atoms.append(("eq_num", 1.0 if v else 0.0))
        elif isinstance(v, (int, float)):
            atoms.append(("eq_num", float(v)))
        else:
            raise NotDeviceCompilable(f"cell literal {v!r}")
    return atoms


# ---------------------------------------------------------------------------
# the device evaluator


def _key_le(a_hi, a_lo, b_hi, b_lo):
    """Lexicographic (hi, lo) <= over sign-biased int32 planes."""
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


def _key_lt(a_hi, a_lo, b_hi, b_lo):
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo < b_lo))


def _match_matrix(kind, lo, hi, flags, keys, valid):
    """[N, R] rule-match matrix from [I, R, K] atoms and [N, I, 2] keys."""
    # broadcast to [N, I, R, K]
    v_hi = keys[:, :, None, None, 0]
    v_lo = keys[:, :, None, None, 1]
    k = kind[None, :, :, :]
    atom_true = k == A_TRUE
    ge_lo = _key_le(lo[None, ..., 0], lo[None, ..., 1], v_hi, v_lo)
    gt_lo = _key_lt(lo[None, ..., 0], lo[None, ..., 1], v_hi, v_lo)
    le_hi = _key_le(v_hi, v_lo, hi[None, ..., 0], hi[None, ..., 1])
    lt_hi = _key_lt(v_hi, v_lo, hi[None, ..., 0], hi[None, ..., 1])
    lo_ok = jnp.where((flags[None] & F_LO_OPEN) > 0, gt_lo, ge_lo)
    hi_ok = jnp.where((flags[None] & F_HI_OPEN) > 0, lt_hi, le_hi)
    in_range = (k == A_RANGE) & lo_ok & hi_ok
    eq = (k == A_EQ) & (v_hi == lo[None, ..., 0]) & (v_lo == lo[None, ..., 1])
    atom = atom_true | ((in_range | eq) & valid[:, :, None, None])
    cell = atom.any(axis=3)  # [N, I, R] disjunction over atoms
    return cell.all(axis=1)  # [N, R] conjunction over inputs


@jax.jit
def _evaluate_batch(kind, lo, hi, flags, keys, valid):
    m = _match_matrix(kind, lo, hi, flags, keys, valid)  # [N, R] bool
    counts = m.sum(axis=1)
    first = jnp.argmax(m, axis=1)
    selected = jnp.where(counts > 0, first, -1)
    return m, selected, counts


def batch_evaluate(table: DeviceDecisionTable, contexts: list[dict]):
    """Evaluate N contexts on device. Returns a list of per-context results:

    - FIRST/UNIQUE: the matched rule index (int) or None (UNIQUE with != 1
      matches is a failure → None, like the host's hit-policy error path)
    - ANY: the first matched rule index or None; output-equality validation
      across the matches stays with the caller (output documents are
      host-side — compare them for the matched index set if required)
    - RULE ORDER / COLLECT without aggregation: list of matched rule indices
    - COLLECT SUM/MIN/MAX/COUNT: the aggregate number (None when no match,
      except COUNT → 0)
    """
    keys, valid = table.pack_contexts(contexts)
    m, selected, counts = _evaluate_batch(
        jnp.asarray(table.kind), jnp.asarray(table.lo), jnp.asarray(table.hi),
        jnp.asarray(table.flags), jnp.asarray(keys), jnp.asarray(valid),
    )
    m = np.asarray(m)
    selected = np.asarray(selected)
    counts = np.asarray(counts)
    # aggregation runs host-side in float64 over the match matrix — the
    # reference aggregates exact decimals, and a float32 device reduction
    # would drift (0.1 -> 0.10000000149...)
    agg = None
    if table.aggregation == "SUM":
        agg = m.astype(np.float64) @ table.out_values
    elif table.aggregation == "MIN":
        agg = np.where(m, table.out_values[None, :], np.inf).min(axis=1)
    elif table.aggregation == "MAX":
        agg = np.where(m, table.out_values[None, :], -np.inf).max(axis=1)

    out = []
    for n in range(len(contexts)):
        if table.aggregation:
            if table.aggregation == "COUNT":
                out.append(int(counts[n]))
            elif counts[n] == 0:
                out.append(None)
            else:
                v = float(agg[n])
                out.append(int(v) if v.is_integer() else v)
        elif table.hit_policy in ("RULE ORDER", "COLLECT"):
            out.append([int(i) for i in np.flatnonzero(m[n])])
        elif table.hit_policy == "UNIQUE":
            out.append(int(selected[n]) if counts[n] == 1 else None)
        elif table.hit_policy == "ANY":
            out.append(int(selected[n]) if counts[n] > 0 else None)
        else:  # FIRST
            out.append(int(selected[n]) if counts[n] > 0 else None)
    return out
