"""OAuth / JWT authentication for the gateway edge.

Reference: gateway/src/main/java/io/camunda/zeebe/gateway/interceptors/impl/
IdentityInterceptor.java — a gRPC server interceptor that validates the
request's bearer token before any RPC handler runs, resolving the caller's
claims (authorized tenants) for downstream authorization. The reference
delegates token validation to the external Identity service (JWKS/RS256);
this zero-egress build validates HS256 JWTs against a shared secret — the
same wire surface (`Authorization: Bearer <jwt>`), the same rejection
semantics (UNAUTHENTICATED), a simpler trust root.

The client side (zeebe_tpu.client.credentials) speaks the standard OAuth2
client-credentials flow against any token endpoint, mirroring the Java/Go
clients' OAuthCredentialsProvider (ZEEBE_CLIENT_ID / ZEEBE_CLIENT_SECRET /
ZEEBE_AUTHORIZATION_SERVER_URL / ZEEBE_TOKEN_AUDIENCE).
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import hmac
import json
import time
from typing import Any


class InvalidToken(Exception):
    pass


def bearer_token(invocation_metadata) -> str:
    """The request's bearer token from gRPC metadata ('' when absent).
    Case-insensitive on both the key and the Bearer prefix (RFC 6750)."""
    for key, value in invocation_metadata or ():
        if key.lower() == "authorization":
            if value[:7].lower() == "bearer ":
                return value[7:].strip()
            return value.strip()
    return ""


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _b64url_decode(data: str) -> bytes:
    return base64.urlsafe_b64decode(data + "=" * (-len(data) % 4))


def encode_jwt(claims: dict, secret: str) -> str:
    """HS256 JWT (header.payload.signature, RFC 7519)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing_input = f"{header}.{payload}".encode("ascii")
    sig = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


def decode_jwt(token: str, secret: str, audience: str | None = None,
               now: float | None = None) -> dict:
    """Validate signature, expiry, and (optionally) audience; returns the
    claims. Raises InvalidToken on any failure — the caller maps it to
    gRPC UNAUTHENTICATED."""
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
    except ValueError as exc:
        raise InvalidToken("malformed token") from exc
    try:
        header = json.loads(_b64url_decode(header_b64))
        claims = json.loads(_b64url_decode(payload_b64))
        signature = _b64url_decode(sig_b64)
    except (ValueError, json.JSONDecodeError) as exc:
        raise InvalidToken("undecodable token") from exc
    if header.get("alg") != "HS256":
        raise InvalidToken(f"unsupported algorithm {header.get('alg')!r}")
    signing_input = f"{header_b64}.{payload_b64}".encode("ascii")
    expected = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    if not hmac.compare_digest(signature, expected):
        raise InvalidToken("bad signature")
    exp = claims.get("exp")
    if exp is not None and (now if now is not None else time.time()) >= exp:
        raise InvalidToken("token expired")
    if audience is not None:
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if audience not in auds:
            raise InvalidToken(f"audience mismatch ({aud!r})")
    return claims


@dataclasses.dataclass
class OAuthValidatorConfig:
    """`zeebe.gateway.security.authentication` subset: mode `none` (default)
    accepts everything; mode `identity` requires a valid bearer JWT."""

    mode: str = "none"  # "none" | "identity"
    secret: str = ""  # HS256 shared secret (the zero-egress trust root)
    audience: str | None = None


class OAuthValidator:
    def __init__(self, config: OAuthValidatorConfig | None = None) -> None:
        self.config = config or OAuthValidatorConfig()

    @property
    def enabled(self) -> bool:
        return self.config.mode == "identity"

    def validate(self, invocation_metadata) -> dict:
        """Claims of the request's bearer token; raises InvalidToken when
        authentication is enabled and the token is missing/invalid."""
        if not self.enabled:
            return {}
        token = bearer_token(invocation_metadata)
        if not token:
            raise InvalidToken("missing bearer token")
        return decode_jwt(token, self.config.secret,
                          audience=self.config.audience)


def auth_server_interceptor(validator: OAuthValidator):
    """gRPC server interceptor rejecting unauthenticated calls before any
    handler runs (the IdentityInterceptor seam)."""
    import grpc

    class _Interceptor(grpc.ServerInterceptor):
        def intercept_service(self, continuation, handler_call_details):
            handler = continuation(handler_call_details)
            try:
                validator.validate(handler_call_details.invocation_metadata)
                return handler
            except InvalidToken as exc:
                detail = f"Expected a valid bearer token: {exc}"

            if handler is None:  # unknown method: let gRPC answer
                return None

            def abort_unary(request, context) -> Any:
                context.abort(grpc.StatusCode.UNAUTHENTICATED, detail)

            def abort_stream(request, context):
                context.abort(grpc.StatusCode.UNAUTHENTICATED, detail)
                yield  # pragma: no cover — abort raises

            # match the original handler's cardinality so streaming RPCs
            # (ActivateJobs, StreamActivatedJobs) also reject cleanly
            if handler.response_streaming:
                factory = (grpc.stream_stream_rpc_method_handler
                           if handler.request_streaming
                           else grpc.unary_stream_rpc_method_handler)
                return factory(abort_stream,
                               request_deserializer=handler.request_deserializer,
                               response_serializer=handler.response_serializer)
            factory = (grpc.stream_unary_rpc_method_handler
                       if handler.request_streaming
                       else grpc.unary_unary_rpc_method_handler)
            return factory(abort_unary,
                           request_deserializer=handler.request_deserializer,
                           response_serializer=handler.response_serializer)

    return _Interceptor()
