"""Job push + jobs-available notifications: the gateway side of job streaming.

Reference: transport/stream/impl/ (AddStream/RemoveStream/PushStream message
flow between gateway ClientStreamManager.java:24 and the broker
RemoteStreamRegistry), broker jobstream/RemoteJobStreamer.java:19 (engine
side-effect push on job CREATED via BpmnJobActivationBehavior.java:39), and
gateway impl/job/LongPollingActivateJobsHandler.java:36 (parked long-polls
woken by a "jobsAvailable" notification instead of polling).

Design (tpu-native runtime): processing emits a post-commit jobs-available
side effect (stream/processor.py on_jobs_available) that lands here. The
``JobNotificationHub`` wakes parked ActivateJobs long-polls; the
``JobStreamDispatcher`` owns the registered client streams and, on
notification, writes a JOB_BATCH ACTIVATE through the normal command path and
delivers the activated jobs to a registered stream — so the record log is
byte-identical to pull activation and replay/exporters see nothing special.
Jobs pushed at a stream that died before delivery are handed back with
JOB YIELD (reference: YieldingJobStreamErrorHandler)."""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from dataclasses import dataclass, field

from zeebe_tpu.protocol import DEFAULT_TENANT, ValueType, command
from zeebe_tpu.protocol.intent import JobBatchIntent, JobIntent

logger = logging.getLogger("zeebe_tpu.gateway.jobstream")

PUSH_BATCH_SIZE = 32


class JobNotificationHub:
    """Versioned per-job-type wakeup: long-polls snapshot a version, check
    state, then wait for the version to move (no sleep-poll)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._versions: dict[str, int] = {}

    def notify(self, job_types: set) -> None:
        with self._cond:
            for job_type in job_types:
                self._versions[job_type] = self._versions.get(job_type, 0) + 1
            self._cond.notify_all()

    def version(self, job_type: str) -> int:
        with self._cond:
            return self._versions.get(job_type, 0)

    def wait(self, job_type: str, seen_version: int, timeout_s: float) -> bool:
        """Block until jobs of the type were made available after
        ``seen_version`` was read, or the timeout passes."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._versions.get(job_type, 0) == seen_version:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True


@dataclass
class ClientJobStream:
    """One StreamActivatedJobs call's registration (ClientStream equivalent)."""

    stream_id: int
    job_type: str
    worker: str
    timeout_ms: int
    jobs: "queue.Queue[tuple[int, dict]]" = field(default_factory=queue.Queue)
    closed: bool = False
    tenant_ids: list | None = None  # authorized-tenant filter (None = default)


from time import perf_counter as _perf_counter

from zeebe_tpu.utils.metrics import REGISTRY as _REG

# job-stream registry metrics (reference: transport/stream metrics — clients,
# servers, streams, aggregated_stream_clients; broker jobstream metrics —
# broker_open_job_stream_count, broker_jobs_pushed_count,
# broker_jobs_push_fail_count, push)
_M_STREAMS = _REG.gauge(
    "streams", "open job streams in the registry").labels()
_M_CLIENTS = _REG.gauge(
    "clients", "connected stream clients").labels()
_M_SERVERS = _REG.gauge(
    "servers", "stream servers (one per dispatcher)").labels()
_M_AGG_CLIENTS = _REG.gauge(
    "aggregated_stream_clients",
    "clients aggregated over logically equal streams").labels()
_M_OPEN_STREAMS = _REG.gauge(
    "broker_open_job_stream_count", "open job streams, broker view").labels()
_M_PUSHED = _REG.counter(
    "broker_jobs_pushed_count", "jobs pushed to client streams").labels()
_M_PUSH_FAIL = _REG.counter(
    "broker_jobs_push_fail_count",
    "jobs that failed delivery and were re-routed/yielded").labels()
_M_PUSH_LATENCY = _REG.histogram(
    "push", "seconds per pushed job delivery").labels()


class JobStreamDispatcher:
    """RemoteStreamRegistry + RemoteJobStreamer, runtime-side: registered
    client streams per job type and a dispatcher thread turning notifications
    into JOB_BATCH ACTIVATE commands whose jobs feed the streams."""

    def __init__(self, runtime) -> None:
        # runtime surface used: submit, partition_for_key, partition_count,
        # has_activatable_jobs
        self.runtime = runtime
        self._ids = itertools.count(1)
        self._lock = threading.Condition()
        self._streams: dict[str, list[ClientJobStream]] = {}
        self._rr: dict[str, int] = {}
        self._pending: set[tuple[int, str]] = set()
        self._running = False
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._running = True
        _M_SERVERS.inc()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="job-stream-dispatcher"
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        _M_SERVERS.dec()
        with self._lock:
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- stream registry (AddStream / RemoveStream) ----------------------------

    def add_stream(self, job_type: str, worker: str, timeout_ms: int,
                   tenant_ids: list | None = None) -> ClientJobStream:
        stream = ClientJobStream(next(self._ids), job_type, worker, timeout_ms,
                                 tenant_ids=tenant_ids)
        for g in (_M_STREAMS, _M_CLIENTS, _M_AGG_CLIENTS, _M_OPEN_STREAMS):
            g.inc()
        with self._lock:
            self._streams.setdefault(job_type, []).append(stream)
            # initial sweep: jobs that became activatable before the stream
            # existed must still be pushed (reference: broker re-notifies
            # streams on registration)
            for partition_id in range(1, self.runtime.partition_count + 1):
                self._pending.add((partition_id, job_type))
            self._lock.notify_all()
        return stream

    def remove_stream(self, stream: ClientJobStream,
                      in_flight: tuple[int, dict] | None = None) -> None:
        """Unregister; undelivered jobs (queued or the one being yielded to a
        now-dead client) go to another stream or back to the activatable
        queue via JOB YIELD. Drain happens under the registry lock, mutually
        exclusive with ``_deliver`` — a job can never land in the queue after
        the drain."""
        leftovers = [] if in_flight is None else [in_flight]
        with self._lock:
            stream.closed = True
            streams = self._streams.get(stream.job_type, [])
            if stream in streams:
                streams.remove(stream)
                for g in (_M_STREAMS, _M_CLIENTS, _M_AGG_CLIENTS,
                          _M_OPEN_STREAMS):
                    g.dec()
            if not streams:
                self._streams.pop(stream.job_type, None)
            while True:
                try:
                    leftovers.append(stream.jobs.get_nowait())
                except queue.Empty:
                    break
        for key, job in leftovers:
            if not self._redeliver(stream.job_type, key, job):
                self._yield_back(key)

    def has_streams(self, job_type: str) -> bool:
        with self._lock:
            return bool(self._streams.get(job_type))

    # -- notification ingress --------------------------------------------------

    def on_jobs_available(self, partition_id: int, job_types: set) -> None:
        with self._lock:
            armed = {t for t in job_types if self._streams.get(t)}
            if not armed:
                return
            self._pending.update((partition_id, t) for t in armed)
            self._lock.notify_all()

    # -- dispatcher ------------------------------------------------------------

    def _run(self) -> None:
        while self._running:
            with self._lock:
                while self._running and not self._pending:
                    self._lock.wait(0.5)
                if not self._running:
                    return
                partition_id, job_type = self._pending.pop()
            try:
                self._push(partition_id, job_type)
            except Exception:  # noqa: BLE001 — a failed push must not kill the loop
                logger.exception(
                    "job push failed (partition %s, type %r)", partition_id, job_type
                )
                # the jobs are still activatable and no fresh notification will
                # fire for them: re-arm and back off (CommandRedistributor-style
                # retry-forever; backpressure/no-leader conditions clear)
                with self._lock:
                    if self._streams.get(job_type):
                        self._pending.add((partition_id, job_type))
                time.sleep(0.05)

    @staticmethod
    def _tenant_group(stream: ClientJobStream) -> tuple:
        return tuple(sorted(stream.tenant_ids or [DEFAULT_TENANT]))

    def _tenant_groups(self, job_type: str) -> list[tuple]:
        """Distinct tenant filters across the type's streams: each group is
        pushed separately so one tenant's empty activation cannot starve
        another's (streams of different tenants see different job sets)."""
        with self._lock:
            return sorted({
                self._tenant_group(s) for s in self._streams.get(job_type, ())
            })

    def _pick_stream(self, job_type: str,
                     group: tuple | None = None) -> ClientJobStream | None:
        with self._lock:
            streams = self._streams.get(job_type)
            if streams and group is not None:
                streams = [s for s in streams if self._tenant_group(s) == group]
            if not streams:
                return None
            rr_key = (job_type, group)
            idx = self._rr.get(rr_key, 0) % len(streams)
            self._rr[rr_key] = idx + 1
            return streams[idx]

    def _push(self, partition_id: int, job_type: str) -> None:
        """Activate-and-deliver, per tenant-filter group, until the partition
        has no more activatable jobs each group can see or every stream is
        gone."""
        while self._running:
            progressed = False
            for group in self._tenant_groups(job_type):
                stream = self._pick_stream(job_type, group)
                if stream is None:
                    continue
                if not self.runtime.has_activatable_jobs(
                        partition_id, job_type, list(group)):
                    continue
                record = self.runtime.submit(
                    partition_id,
                    command(ValueType.JOB_BATCH, JobBatchIntent.ACTIVATE, {
                        "type": job_type,
                        "worker": stream.worker,
                        "timeout": stream.timeout_ms,
                        "maxJobsToActivate": PUSH_BATCH_SIZE,
                        **({"tenantIds": stream.tenant_ids}
                           if stream.tenant_ids else {}),
                    }),
                )
                if record.is_rejection:
                    continue
                keys = record.value.get("jobKeys", [])
                jobs = record.value.get("jobs", [])
                for key, job in zip(keys, jobs):
                    _t0 = _perf_counter()
                    if self._deliver(stream, key, job):
                        _M_PUSHED.inc()
                        _M_PUSH_LATENCY.observe(_perf_counter() - _t0)
                    else:
                        _M_PUSH_FAIL.inc()
                        if not self._redeliver(job_type, key, job):
                            self._yield_back(key)
                if len(keys) >= PUSH_BATCH_SIZE:
                    progressed = True  # this group may have more to drain
            if not progressed:
                return

    def _deliver(self, stream: ClientJobStream, key: int, job: dict) -> bool:
        """Enqueue under the registry lock so the closed-check and the put are
        atomic against remove_stream's drain."""
        with self._lock:
            if stream.closed:
                return False
            stream.jobs.put((key, job))
            return True

    def _redeliver(self, job_type: str, key: int, job: dict) -> bool:
        """Route an undeliverable job to another live stream of the type that
        is authorized for the job's tenant (never across tenants)."""
        tenant = job.get("tenantId", DEFAULT_TENANT)
        for _ in range(8):
            stream = self._pick_stream(job_type)
            if stream is None:
                return False
            if tenant not in (stream.tenant_ids or [DEFAULT_TENANT]):
                # no eligible stream may exist at all; scan once under lock
                with self._lock:
                    eligible = [
                        s for s in self._streams.get(job_type, ())
                        if tenant in (s.tenant_ids or [DEFAULT_TENANT])
                    ]
                if not eligible:
                    return False
                stream = eligible[0]
            if self._deliver(stream, key, job):
                return True
        return False

    def _yield_back(self, job_key: int) -> None:
        try:
            self.runtime.submit(
                self.runtime.partition_for_key(job_key),
                command(ValueType.JOB, JobIntent.YIELD, {}, key=job_key),
            )
        except Exception:  # noqa: BLE001 — the job times out eventually anyway
            logger.exception("job yield-back failed for key %s", job_key)
