"""Tenant-aware admission + cooperative load shedding (DAGOR-shaped).

Reference: Zhou et al., *Overload Control for Scaling WeChat Microservices*
(SoCC 2018) — feedback-driven admission with business priorities — applied
to this gateway's client-command ingress, in front of the per-partition
in-flight limiters (`broker/backpressure.py`). Three independent gates, in
order, each producing a **typed, fast** rejection (`RESOURCE_EXHAUSTED` at
the gRPC surface; a `resource-exhausted` error frame on the multi-process
wire) instead of a queue that collapses under overload:

1. **Priority ladder** (cooperative shedding): every client command is
   classified onto a four-rung ladder — internal completions (the
   backpressure whitelist: job COMPLETE/FAIL) > in-flight continuations
   (message publish, job batch activation, incident resolve, variable
   updates, cancels) > new work (instance creates, deployments, signals) >
   queries/unclassified. The shed level is driven by **observed ack-latency
   percentiles** (the Gorilla time-series plane where one is attached —
   shed signal latency is one sampler tick — or the controller's own
   bounded latency window otherwise) with hysteresis: `breach_ticks`
   consecutive p99 breaches raise the level one rung, `clear_ticks`
   consecutive clear ticks lower it. Completions are never shed — shedding
   work that *finishes* in-flight work makes overload worse.
2. **Per-tenant token buckets**: tenant identity comes from request
   metadata (the record value's ``tenantId``), falling back to the client
   stream id; each tenant refills at its quota rate up to a burst. A hot
   tenant saturates its own bucket and gets typed rejections while every
   other tenant's bucket stays full.
3. **Weighted-fair in-flight sharing**: when the admission window is
   contended (total in-flight at the cap), a tenant is admitted only while
   its in-flight count is below its weight share of the window — the
   work-conserving approximation of weighted-fair queuing over a
   synchronous ingress (an uncontended tenant may use the whole window).

Every shed is a flight-recorder event and a ``zeebe_admission_*`` metric;
sustained shedding at or above the new-work rung flips the controller into
a *draining* state so the gateway's ``/ready`` degrades and a load balancer
can rotate it out.

Thread model: ``try_admit``/``release`` run on gateway request threads (or
the worker ingress pump) under one controller lock; the controller never
touches partition state — committed-read discipline is moot because there
are no reads at all, only its own counters.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from zeebe_tpu.broker.backpressure import WHITELIST
from zeebe_tpu.protocol import Record, ValueType
from zeebe_tpu.utils.metrics import REGISTRY, estimate_quantile

# -- the priority ladder -------------------------------------------------------

#: internal completions: finishing in-flight work drains load — never shed
#: (exactly the backpressure whitelist, one home: broker/backpressure.py)
PRIORITY_COMPLETION = 0
#: continuations of already-admitted work (activations, correlations,
#: incident resolution, variable updates, cancels)
PRIORITY_CONTINUATION = 1
#: new work entering the system (instance creates, deployments, signals)
PRIORITY_CREATE = 2
#: queries and anything unclassified — first against the wall
PRIORITY_QUERY = 3

_CONTINUATION_TYPES = frozenset({
    ValueType.JOB,                    # non-whitelist job commands (retries…)
    ValueType.JOB_BATCH,              # workers pulling queued work
    ValueType.MESSAGE,                # publishes correlate into waiting state
    ValueType.MESSAGE_BATCH,
    ValueType.VARIABLE_DOCUMENT,
    ValueType.INCIDENT,
    ValueType.PROCESS_INSTANCE,       # cancel / modify of a live instance
    ValueType.PROCESS_INSTANCE_MODIFICATION,
    ValueType.PROCESS_INSTANCE_MIGRATION,
    ValueType.USER_TASK,
})
_CREATE_TYPES = frozenset({
    ValueType.PROCESS_INSTANCE_CREATION,
    ValueType.DEPLOYMENT,
    ValueType.SIGNAL,
    ValueType.RESOURCE_DELETION,
})

#: shed ladder: at shed level L every priority >= _SHED_FLOOR - L is shed
#: (level 1 sheds queries, 2 sheds new work too, 3 leaves only completions)
_SHED_FLOOR = 4
MAX_SHED_LEVEL = 3


def priority_of(record: Record) -> int:
    """Ladder rung for a client command (smaller = shed later)."""
    if (record.value_type, int(record.intent)) in WHITELIST:
        return PRIORITY_COMPLETION
    if record.value_type in _CONTINUATION_TYPES:
        return PRIORITY_CONTINUATION
    if record.value_type in _CREATE_TYPES:
        return PRIORITY_CREATE
    return PRIORITY_QUERY


def tenant_of(record: Record) -> str:
    """Tenant identity from request metadata: the record value's
    ``tenantId`` when the client sent one, else the client stream id — an
    anonymous client is still rate-isolated from every other stream."""
    value = record.value
    tenant = value.get("tenantId") if isinstance(value, dict) else None
    if tenant:
        return str(tenant)
    return f"stream-{record.request_stream_id}"


# -- token bucket --------------------------------------------------------------


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s up to ``burst``. ``rate <= 0``
    means unmetered (always admits). Caller holds the controller lock."""

    __slots__ = ("rate", "burst", "tokens", "last_ms")

    def __init__(self, rate: float, burst: float, now_ms: float) -> None:
        self.rate = rate
        self.burst = max(burst, 1.0)
        self.tokens = self.burst
        self.last_ms = now_ms

    def try_take(self, now_ms: float) -> bool:
        if self.rate <= 0:
            return True
        elapsed = max(now_ms - self.last_ms, 0.0) / 1000.0
        self.last_ms = now_ms
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


# -- configuration -------------------------------------------------------------


def _parse_tenant_map(spec: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, value = part.partition("=")
        if name and value:
            out[name.strip()] = value.strip()
    return out


@dataclass
class AdmissionCfg:
    """Knobs (``ZEEBE_GATEWAY_TENANT_*`` / ``ZEEBE_GATEWAY_ADMISSION_*``)."""

    enabled: bool = True
    #: default per-tenant quota (tokens/s); 0 = unmetered
    default_rate: float = 0.0
    #: default burst; 0 = derive (2x rate)
    default_burst: float = 0.0
    #: per-tenant (rate, burst) overrides
    quotas: dict[str, tuple[float, float]] = field(default_factory=dict)
    #: per-tenant weights for the fair in-flight share (default 1.0)
    weights: dict[str, float] = field(default_factory=dict)
    #: admission window for the weighted-fair share (in-flight commands)
    max_inflight: int = 256
    #: shed target: raise the shed level while observed ack p99 exceeds this
    shed_p99_ms: float = 1000.0
    #: hysteresis: recover only below this fraction of the target
    recover_fraction: float = 0.5
    breach_ticks: int = 3
    clear_ticks: int = 5
    tick_interval_ms: int = 1000
    #: /ready degrades after shedding NEW WORK for this long (0 disables)
    drain_after_ms: int = 10_000

    @classmethod
    def from_env(cls, env: dict | None = None) -> "AdmissionCfg":
        env = os.environ if env is None else env

        def _f(name: str, default: float) -> float:
            try:
                return float(env.get(name, ""))
            except ValueError:
                return default

        cfg = cls()
        cfg.enabled = env.get(
            "ZEEBE_GATEWAY_ADMISSION_ENABLED", "true").lower() in (
                "1", "true", "yes")
        cfg.default_rate = _f("ZEEBE_GATEWAY_TENANT_DEFAULTRATE", 0.0)
        cfg.default_burst = _f("ZEEBE_GATEWAY_TENANT_DEFAULTBURST", 0.0)
        cfg.max_inflight = int(_f("ZEEBE_GATEWAY_ADMISSION_MAXINFLIGHT", 256))
        cfg.shed_p99_ms = _f("ZEEBE_GATEWAY_ADMISSION_SHEDP99MS", 1000.0)
        cfg.drain_after_ms = int(
            _f("ZEEBE_GATEWAY_ADMISSION_DRAINAFTERMS", 10_000))
        for tenant, spec in _parse_tenant_map(
                env.get("ZEEBE_GATEWAY_TENANT_QUOTAS", "")).items():
            rate_s, _, burst_s = spec.partition(":")
            try:
                rate = float(rate_s)
                burst = float(burst_s) if burst_s else 0.0
            except ValueError:
                continue
            cfg.quotas[tenant] = (rate, burst)
        for tenant, spec in _parse_tenant_map(
                env.get("ZEEBE_GATEWAY_TENANT_WEIGHTS", "")).items():
            try:
                cfg.weights[tenant] = float(spec)
            except ValueError:
                continue
        return cfg


# -- metrics (module-level: families exist from first import) ------------------

#: distinct tenant label values are bounded; overflow folds into "other"
_MAX_TENANT_LABELS = 64

#: per-tenant controller state (buckets, counters) is bounded too: a client
#: minting a fresh tenantId per request must not grow memory without limit —
#: oldest-inserted entries evict first (their tenants re-enter with a fresh
#: bucket, which only ever errs toward admitting)
_MAX_TRACKED_TENANTS = 4096

_M_ADMITTED = REGISTRY.counter(
    "admission_admitted_total",
    "client commands admitted by the tenant admission controller",
    ("node", "tenant"))
_M_SHED = REGISTRY.counter(
    "admission_shed_total",
    "client commands shed by the admission controller, by reason "
    "(priority = shed ladder, tenant-quota = token bucket, "
    "fair-share = weighted in-flight share)",
    ("node", "tenant", "reason"))
_M_SHED_LEVEL = REGISTRY.gauge(
    "admission_shed_level",
    "current shed-ladder level (0 = admit all, 3 = completions only)",
    ("node",))
_M_INFLIGHT = REGISTRY.gauge(
    "admission_inflight_commands",
    "client commands in flight through the admission window", ("node",))
_M_P99 = REGISTRY.gauge(
    "admission_observed_p99_ms",
    "the ack-latency p99 the shed ladder last evaluated (ms)", ("node",))
_M_DRAINING = REGISTRY.gauge(
    "admission_draining",
    "1 while sustained shedding degrades /ready so an LB can drain this "
    "gateway", ("node",))
_M_ACK_LATENCY = REGISTRY.histogram(
    "admission_ack_latency_ms",
    "ack latency observed by the admission controller (ms) — the shed "
    "ladder's feedback signal",
    ("node",),
    buckets=(1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000))

_SHED_REASONS = ("priority", "tenant-quota", "fair-share")


class AdmissionController:
    """One admission gate: the multi-process gateway runtime holds one (its
    request threads call ``try_admit``/``release``), and every worker holds
    one in front of its partitions' backpressure limiters."""

    def __init__(self, cfg: AdmissionCfg | None = None,
                 node_id: str = "gateway",
                 clock_millis=None,
                 flight=None,
                 max_inflight_fn=None,
                 p99_source=None) -> None:
        self.cfg = cfg or AdmissionCfg()
        self.node_id = node_id
        self.clock_millis = clock_millis or (lambda: time.time() * 1000.0)
        #: flight recorder (or None): every shed and level change is an event
        self.flight = flight
        #: dynamic admission window override (the worker wires the sum of its
        #: leader partitions' backpressure limits here so the fair share sits
        #: exactly in front of the per-partition limiters)
        self.max_inflight_fn = max_inflight_fn
        #: external p99 source (ms) — the broker wires the Gorilla
        #: time-series store's retained percentile here; None falls back to
        #: the controller's own bounded window
        self.p99_source = p99_source
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}
        self._inflight: dict[str, int] = {}
        self._inflight_total = 0
        # bounded ack-latency window for the store-less fallback: histogram
        # bucket counts, reset each tick (the same estimate_quantile shape
        # the time-series sampler uses)
        self._lat_buckets = list(_M_ACK_LATENCY.buckets)
        self._lat_counts = [0] * (len(self._lat_buckets) + 1)
        self.shed_level = 0
        self.level_changes = 0
        self._breaches = 0
        self._clears = 0
        self._last_tick_ms = 0.0
        self._shedding_creates_since: float | None = None
        self.draining = False
        self.last_p99_ms = 0.0
        # per-tenant running totals for snapshot()/top (metrics carry the
        # same data; these avoid a registry scrape on every status push)
        self._admitted: dict[str, int] = {}
        self._shed: dict[str, dict[str, int]] = {}
        self._tenant_labels: set[str] = set()
        label = node_id
        self._g_level = _M_SHED_LEVEL.labels(label)
        self._g_inflight = _M_INFLIGHT.labels(label)
        self._g_p99 = _M_P99.labels(label)
        self._g_draining = _M_DRAINING.labels(label)
        self._h_latency = _M_ACK_LATENCY.labels(label)
        self._g_level.set(0)
        self._g_draining.set(0)

    # -- label hygiene ---------------------------------------------------------

    def _label(self, tenant: str) -> str:
        if tenant in self._tenant_labels:
            return tenant
        if len(self._tenant_labels) >= _MAX_TENANT_LABELS:
            return "other"
        self._tenant_labels.add(tenant)
        return tenant

    # -- admission -------------------------------------------------------------

    def _bucket(self, tenant: str, now_ms: float) -> TokenBucket:
        bucket = self._buckets.get(tenant)
        if bucket is None:
            rate, burst = self.cfg.quotas.get(
                tenant, (self.cfg.default_rate, self.cfg.default_burst))
            if burst <= 0:
                burst = max(rate, 1.0) * 2.0
            bucket = self._buckets[tenant] = TokenBucket(rate, burst, now_ms)
            for tracked in (self._buckets, self._admitted, self._shed):
                while len(tracked) > _MAX_TRACKED_TENANTS:
                    tracked.pop(next(iter(tracked)))
        return bucket

    def _fair_share(self, tenant: str) -> float:
        """This tenant's share of the admission window: weight over the sum
        of ACTIVE tenants' weights (work-conserving — an idle tenant's
        weight does not reserve capacity)."""
        weight = self.cfg.weights.get(tenant, 1.0)
        total = weight
        for other, count in self._inflight.items():
            if count > 0 and other != tenant:
                total += self.cfg.weights.get(other, 1.0)
        cap = (self.max_inflight_fn() if self.max_inflight_fn is not None
               else self.cfg.max_inflight) or self.cfg.max_inflight
        return max(1.0, cap * weight / total), cap

    def try_admit(self, record: Record,
                  now_ms: float | None = None) -> tuple[str | None, str, int]:
        """Admission decision for one client command. Returns
        ``(None, tenant, priority)`` on admit — the caller MUST pair it with
        ``release(tenant)`` once the command completes or fails — or
        ``(reason, tenant, priority)`` on shed (no release due)."""
        tenant = tenant_of(record)
        priority = priority_of(record)
        if not self.cfg.enabled:
            return None, tenant, priority
        now = self.clock_millis() if now_ms is None else now_ms
        with self._lock:
            reason = None
            if priority >= _SHED_FLOOR - self.shed_level:
                reason = "priority"
            elif (priority != PRIORITY_COMPLETION
                  and not self._bucket(tenant, now).try_take(now)):
                # completions ride for free: a tenant over quota must still
                # be able to finish the work it already holds
                reason = "tenant-quota"
            else:
                share, cap = self._fair_share(tenant)
                if (self._inflight_total >= cap
                        and priority != PRIORITY_COMPLETION
                        and self._inflight.get(tenant, 0) >= share):
                    reason = "fair-share"
            label = self._label(tenant)
            if reason is None:
                self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
                self._inflight_total += 1
                self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
            else:
                shed = self._shed.setdefault(tenant, {})
                shed[reason] = shed.get(reason, 0) + 1
        if reason is None:
            _M_ADMITTED.labels(self.node_id, label).inc()
            self._g_inflight.set(self._inflight_total)
        else:
            _M_SHED.labels(self.node_id, label, reason).inc()
            if self.flight is not None:
                self.flight.record(0, "admission_shed", tenant=tenant,
                                   reason=reason, priority=priority,
                                   level=self.shed_level,
                                   valueType=record.value_type.name)
        return reason, tenant, priority

    def release(self, tenant: str, latency_ms: float | None = None) -> None:
        """The admitted command completed (acked, rejected, or errored)."""
        if not self.cfg.enabled:
            return
        with self._lock:
            count = self._inflight.get(tenant, 0)
            if count > 1:
                self._inflight[tenant] = count - 1
                self._inflight_total -= 1
            elif count == 1:
                # drop the zero entry: idle tenants neither hold memory nor
                # count toward the active-weight denominator
                del self._inflight[tenant]
                self._inflight_total -= 1
        self._g_inflight.set(self._inflight_total)
        if latency_ms is not None:
            self.observe_ack(latency_ms)

    def observe_ack(self, latency_ms: float) -> None:
        """Feed one observed ack latency into the shed ladder's signal."""
        self._h_latency.observe(latency_ms)
        with self._lock:
            for i, bound in enumerate(self._lat_buckets):
                if latency_ms <= bound:
                    self._lat_counts[i] += 1
                    return
            self._lat_counts[-1] += 1

    # -- the shed ladder (feedback loop) ---------------------------------------

    def _window_p99(self) -> float | None:
        """p99 over the latencies observed since the last tick (the
        store-less fallback; the counts reset per tick so the signal tracks
        *recent* load, exactly like the sampler's delta percentiles)."""
        with self._lock:
            counts = list(self._lat_counts)
            self._lat_counts = [0] * len(self._lat_counts)
        if not sum(counts):
            return None
        return estimate_quantile(self._lat_buckets, counts, 0.99)

    def tick(self, now_ms: float | None = None) -> None:
        """Advance the feedback loop (call from the gateway poll loop or the
        worker pump); throttled to ``tick_interval_ms`` internally."""
        if not self.cfg.enabled:
            return
        now = self.clock_millis() if now_ms is None else now_ms
        if now - self._last_tick_ms < self.cfg.tick_interval_ms:
            return
        self._last_tick_ms = now
        p99 = None
        if self.p99_source is not None:
            try:
                p99 = self.p99_source()
            except Exception:  # noqa: BLE001 — a torn store read must not
                p99 = None     # kill the pump; fall back to the window
        if p99 is None:
            p99 = self._window_p99()
        if p99 is not None:
            self.last_p99_ms = p99
            self._g_p99.set(round(p99, 3))
        level = self.shed_level
        if p99 is not None and p99 > self.cfg.shed_p99_ms:
            self._breaches += 1
            self._clears = 0
            if self._breaches >= self.cfg.breach_ticks:
                self._breaches = 0
                level = min(level + 1, MAX_SHED_LEVEL)
        elif p99 is None or p99 <= (self.cfg.shed_p99_ms
                                    * self.cfg.recover_fraction):
            self._clears += 1
            self._breaches = 0
            if self._clears >= self.cfg.clear_ticks:
                self._clears = 0
                level = max(level - 1, 0)
        else:
            # between the recover floor and the target: hold (hysteresis)
            self._breaches = 0
            self._clears = 0
        if level != self.shed_level:
            old, self.shed_level = self.shed_level, level
            self.level_changes += 1
            self._g_level.set(level)
            # the shed ladder pre-dated the control plane but IS a closed
            # feedback loop: its decisions record under the shared
            # control_adjust vocabulary (ISSUE 12) — one audit schema for
            # every loop, rendered together by `cli top` CONTROL
            from zeebe_tpu.control.audit import record_adjust

            record_adjust(
                self.flight, 0, controller="admission-shed-ladder",
                knob="admission.shedLevel", before=old, after=level,
                reason=("ack p99 breached the shed target"
                        if level > old else
                        "ack p99 cleared the recovery floor"),
                signals={"p99Ms": round(p99 or 0.0, 1),
                         "targetMs": self.cfg.shed_p99_ms})
        # /ready drain: sustained shedding of NEW WORK (level >= 2) means
        # this gateway cannot serve its purpose — degrade readiness so the
        # LB sends tenants elsewhere while completions keep draining
        if self.shed_level >= MAX_SHED_LEVEL - 1 and self.cfg.drain_after_ms > 0:
            if self._shedding_creates_since is None:
                self._shedding_creates_since = now
            elif (not self.draining and now - self._shedding_creates_since
                  >= self.cfg.drain_after_ms):
                self.draining = True
                self._g_draining.set(1)
                if self.flight is not None:
                    self.flight.record(0, "admission_draining", draining=True)
        else:
            self._shedding_creates_since = None
            if self.draining:
                self.draining = False
                self._g_draining.set(0)
                if self.flight is not None:
                    self.flight.record(0, "admission_draining",
                                       draining=False)

    # -- observability ---------------------------------------------------------

    def snapshot(self) -> dict:
        """The admission block for ``/cluster/status`` and the worker status
        push (rendered by ``cli top``'s ADMISSION section)."""
        with self._lock:
            tenants = sorted(set(self._admitted) | set(self._shed)
                             | set(self._inflight))
            rows = {}
            for tenant in tenants:
                shed = self._shed.get(tenant, {})
                bucket = self._buckets.get(tenant)
                rows[tenant] = {
                    "admitted": self._admitted.get(tenant, 0),
                    "shed": sum(shed.values()),
                    "shedByReason": dict(shed),
                    "inflight": self._inflight.get(tenant, 0),
                    "quotaRate": bucket.rate if bucket is not None else None,
                    "weight": self.cfg.weights.get(tenant, 1.0),
                }
            return {
                "enabled": self.cfg.enabled,
                "shedLevel": self.shed_level,
                "draining": self.draining,
                "observedP99Ms": round(self.last_p99_ms, 1),
                "shedP99TargetMs": self.cfg.shed_p99_ms,
                "inflight": self._inflight_total,
                "maxInflight": (self.max_inflight_fn()
                                if self.max_inflight_fn is not None
                                else self.cfg.max_inflight),
                "tenants": rows,
            }
