"""Deployable multi-process cluster runtime: ONE broker per process over TCP.

Reference: dist/…/StandaloneBroker.java + BrokerCfg cluster section (node id,
initial contact points) and the gateway's BrokerClient routing
(impl/broker/BrokerRequestManager.java — requests go to the partition leader,
responses return to the requesting gateway).

Each process runs one Broker over TcpMessagingService; Raft, SWIM membership,
inter-partition commands, and deployment distribution all ride the same TCP
messaging the loopback tests exercise. The local gateway routes client
commands: leader-local writes go straight in; remote leaders receive the
command over the broker command-api topic, and the processing side's client
response is routed back to the ORIGIN gateway via its request_stream_id
(which encodes the origin node index — the reference does the same with
gateway stream ids over atomix messaging)."""

from __future__ import annotations

import itertools
import threading
import time

from zeebe_tpu.broker.broker import COMMAND_API_TOPIC, Broker, BrokerCfg
from zeebe_tpu.cluster.messaging import TcpMessagingService
from zeebe_tpu.gateway.broker_client import (
    GatewayRuntimeBase,
    NoLeaderError,
    ResourceExhaustedError,
)
from zeebe_tpu.protocol import Record

GATEWAY_RESPONSE_TOPIC = "gateway-response"
JOBS_AVAILABLE_TOPIC = "jobs-available"


class TcpClusterRuntime(GatewayRuntimeBase):
    """The gateway-facing runtime for one deployed broker process. Implements
    the same surface as the in-process ClusterRuntime (submit, partition
    selection, topology) against a single local Broker + TCP peers."""

    def __init__(self, node_id: str, bind: tuple[str, int],
                 peers: dict[str, tuple[str, int]],
                 partition_count: int = 1, replication_factor: int = 1,
                 directory=None, kernel_backend: bool = True,
                 tls=None, **broker_kwargs) -> None:
        self.node_id = node_id
        self.partition_count = partition_count
        members = sorted(set(peers) | {node_id})
        self._members = members
        self._node_index = members.index(node_id)
        self.messaging = TcpMessagingService(node_id, bind, peers, tls=tls)
        self.messaging.start()
        self.messaging.subscribe(GATEWAY_RESPONSE_TOPIC, self._on_remote_response)
        self.messaging.subscribe(JOBS_AVAILABLE_TOPIC, self._on_remote_jobs_available)
        cfg = BrokerCfg(
            node_id=node_id, partition_count=partition_count,
            replication_factor=replication_factor, cluster_members=members,
            kernel_backend=kernel_backend,
        )
        self.broker = Broker(
            cfg, self.messaging, directory=directory,
            response_sink=self._on_local_response, **broker_kwargs,
        )
        self._lock = threading.RLock()
        self._init_requests()
        self._init_jobstreams()
        self.broker.jobs_listener = self._on_local_jobs_available
        self._running = False
        self._thread: threading.Thread | None = None

    # -- pump ------------------------------------------------------------------

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"runtime-{self.node_id}")
        self._thread.start()
        self.job_streams.start()

    def _run(self) -> None:
        while self._running:
            with self._lock:
                moved = self.messaging.poll()
                self.broker.pump()
            if moved == 0:
                time.sleep(0.001)

    def stop(self) -> None:
        self.job_streams.stop()
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._lock:
            self.broker.close()
        self.messaging.stop()

    def await_leaders(self, timeout_s: float = 60.0) -> None:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                ready = all(
                    self.broker.known_leader(p) is not None
                    for p in range(1, self.partition_count + 1)
                )
            if ready:
                return
            time.sleep(0.05)
        raise RuntimeError("partition leaders not elected in time")

    # -- response routing ------------------------------------------------------

    def _on_local_response(self, response) -> None:
        """Processing on the LOCAL broker produced a client response: resolve
        it locally if this gateway originated the request, else route it to
        the origin gateway by its stream id."""
        origin = response.request_stream_id
        if origin == self._node_index:
            self._resolve_request(response.request_id, response.record)
            return
        if 0 <= origin < len(self._members):
            self.messaging.send(
                self._members[origin], GATEWAY_RESPONSE_TOPIC,
                {"requestId": response.request_id,
                 "record": response.record.to_bytes()},
            )

    def _on_remote_response(self, sender: str, payload: dict) -> None:
        self._resolve_request(payload["requestId"],
                              Record.from_bytes(payload["record"]))

    # -- jobs-available fan-out ------------------------------------------------

    def _on_local_jobs_available(self, partition_id: int, job_types: set) -> None:
        """A local partition made jobs activatable: wake this gateway AND the
        peer gateways (their workers may hold the streams/long-polls —
        reference: the broker gossips jobsAvailable to every gateway)."""
        self._on_jobs_available(partition_id, job_types)
        payload = {"partitionId": partition_id, "types": sorted(job_types)}
        for member in self._members:
            if member != self.node_id:
                self.messaging.send(member, JOBS_AVAILABLE_TOPIC, payload)

    def _on_remote_jobs_available(self, sender: str, payload: dict) -> None:
        self._on_jobs_available(payload["partitionId"], set(payload["types"]))

    # -- topology --------------------------------------------------------------

    def topology(self) -> dict:
        """Local broker health + gossiped peers — the full cluster view a
        `zbctl status` expects (reference: BrokerClusterState fed by gossip).
        Remote partition roles come from the membership properties the
        brokers gossip (`Broker._gossip_roles`)."""
        from zeebe_tpu.cluster.membership import MemberState

        with self._lock:
            brokers = [dict(self.broker.health(), member=self.node_id)]
            for member in list(self.broker.membership.members.values()):
                if (member.member_id == self.node_id
                        or member.state == MemberState.DEAD):
                    continue
                roles = member.properties.get("partitions") or {}
                brokers.append({
                    "member": member.member_id,
                    "nodeId": member.member_id,
                    "partitions": [
                        {"partitionId": int(pid), "role": role}
                        for pid, role in sorted(roles.items(),
                                                key=lambda kv: int(kv[0]))
                    ],
                })
            brokers.sort(key=lambda b: str(b.get("member", "")))
            return {
                "clusterSize": len(self._members),
                "partitionsCount": self.partition_count,
                "replicationFactor": self.broker.cfg.replication_factor,
                "brokers": brokers,
            }

    def has_activatable_jobs(self, partition_id: int, job_type: str,
                             tenant_ids: list[str] | None = None) -> bool:
        with self._lock:
            partition = self.broker.partitions.get(partition_id)
            if partition is not None and partition.is_leader and partition.db is not None:
                # committed-read discipline: this runs on a gateway thread —
                # opening the processing-owned transaction slot here raced
                # the pump thread's own transaction (zlint caught it)
                from zeebe_tpu.engine.engine_state import JobState

                return JobState.any_activatable_committed(
                    partition.db, job_type, tenant_ids)
        # remote leader: no cheap peek — let the long-poll try a real
        # activation (an empty JOB_BATCH comes back quickly)
        return True

    # -- request path ----------------------------------------------------------

    def submit(self, partition_id: int, record: Record,
               timeout_s: float = 10.0) -> Record:
        from zeebe_tpu.broker.partition import BackpressureExceeded

        request_id, event = self._register_request()
        rec = record.replace(request_id=request_id,
                             request_stream_id=self._node_index)
        deadline = time.time() + timeout_s
        written = False
        while time.time() < deadline and not written:
            with self._lock:
                partition = self.broker.partitions.get(partition_id)
                if partition is not None and partition.is_leader:
                    try:
                        written = partition.client_write(rec) is not None
                    except BackpressureExceeded as exc:
                        self._pending.pop(request_id, None)
                        raise ResourceExhaustedError(str(exc)) from exc
                else:
                    leader = self.broker.known_leader(partition_id)
                    if leader is not None and leader != self.node_id:
                        self.messaging.send(
                            leader, f"{COMMAND_API_TOPIC}-{partition_id}",
                            {"record": rec.to_bytes()},
                        )
                        written = True  # at-most-once try; retry on timeout
            if not written:
                time.sleep(0.02)
        if not written:
            self._pending.pop(request_id, None)
            raise NoLeaderError(f"no leader for partition {partition_id}")
        return self._take_response(request_id, event, deadline, partition_id, timeout_s)
