"""gRPC gateway: client API front-end (SURVEY §2.11)."""

from zeebe_tpu.gateway.broker_client import ClusterRuntime
from zeebe_tpu.gateway.gateway import Gateway, GatewayService

__all__ = ["ClusterRuntime", "Gateway", "GatewayService"]
