"""Gateway authorization: tenant validation at the client edge.

Reference: gateway/src/main/java/io/camunda/zeebe/gateway/interceptors/impl/
IdentityInterceptor.java (resolves the caller's authorized tenants from the
request's bearer token and rejects requests addressing other tenants) and
auth/src/main/java/io/camunda/zeebe/auth/impl/Authorization.java (the
authorized-tenants claim the gateway stamps onto broker requests, checked
engine-side by TenantAuthorizationChecker).

Skeleton scope: identity is a static bearer-token → tenants table (the
reference delegates to an external Identity service; zero-egress here), and
multi-tenancy is off by default — exactly the reference's default, where every
request must address the default tenant."""

from __future__ import annotations

import dataclasses

from zeebe_tpu.protocol import DEFAULT_TENANT


@dataclasses.dataclass
class GatewayAuthConfig:
    """`zeebe.gateway.multiTenancy` + identity subset."""

    # off (default): only the default tenant is addressable, any caller
    multi_tenancy_enabled: bool = False
    # bearer token → authorized tenant ids (IdentityInterceptor's token claims)
    token_tenants: dict[str, list[str]] = dataclasses.field(default_factory=dict)
    # tenants granted to calls with no/unknown token while multi-tenancy is on
    anonymous_tenants: list[str] = dataclasses.field(
        default_factory=lambda: [DEFAULT_TENANT])


class TenantAuthorizer:
    def __init__(self, config: GatewayAuthConfig | None = None,
                 oauth=None) -> None:
        self.config = config or GatewayAuthConfig()
        # optional OAuthValidator: with JWT authentication on, the caller's
        # tenants come from the validated token's authorized_tenants claim
        # (the reference reads the same claim via Identity)
        self.oauth = oauth

    @property
    def enabled(self) -> bool:
        return self.config.multi_tenancy_enabled

    def authorized_tenants(self, invocation_metadata) -> list[str]:
        """The caller's authorized tenants, resolved from gRPC metadata."""
        if not self.config.multi_tenancy_enabled:
            return [DEFAULT_TENANT]
        if self.oauth is not None and self.oauth.enabled:
            from zeebe_tpu.gateway.oauth import InvalidToken

            try:
                claims = self.oauth.validate(invocation_metadata)
            except InvalidToken:
                # the server interceptor rejects unauthenticated calls before
                # handlers run; reaching here means a race on config — deny
                return []
            tenants = claims.get("authorized_tenants")
            if tenants:
                return list(tenants)
            return list(self.config.anonymous_tenants)
        from zeebe_tpu.gateway.oauth import bearer_token

        token = bearer_token(invocation_metadata)
        if token and token in self.config.token_tenants:
            return list(self.config.token_tenants[token])
        return list(self.config.anonymous_tenants)

    def check(self, invocation_metadata, tenant: str) -> tuple[str | None, str]:
        """Validate one addressed tenant. Returns (error, detail): error is
        None when authorized, else "disabled" (multi-tenancy off but a
        non-default tenant was addressed) or "denied"."""
        tenant = tenant or DEFAULT_TENANT
        if not self.config.multi_tenancy_enabled:
            if tenant != DEFAULT_TENANT:
                return ("disabled",
                        f"multi-tenancy is disabled: tenant '{tenant}' cannot "
                        "be addressed (only the default tenant)")
            return (None, tenant)
        if tenant not in self.authorized_tenants(invocation_metadata):
            return ("denied", f"not authorized for tenant '{tenant}'")
        return (None, tenant)
