"""gRPC gateway: the client API front-end.

Reference: gateway/src/main/java/io/camunda/zeebe/gateway/ — Gateway boots the
gRPC server, EndpointManager.java:78 bridges rpcs to broker requests through
RequestMapper.java:66 / ResponseMapper.java:58; ActivateJobs long-polls via
LongPollingActivateJobsHandler.java:36 fanning out round-robin across
partitions (RoundRobinActivateJobsHandler).

The service is registered with ``grpc.method_handlers_generic_handler`` over
protoc-generated messages (no grpcio-tools in the image — message codegen via
``protoc --python_out``, service wiring by hand)."""

from __future__ import annotations

import json
import time
from concurrent import futures
from typing import Any, Callable

import grpc

from zeebe_tpu.gateway.proto import gateway_pb2 as pb  # noqa: E402

from zeebe_tpu.gateway.broker_client import (  # noqa: E402
    DEPLOYMENT_PARTITION,
    ClusterRuntime,
    NoLeaderError,
    RequestTimeoutError,
    ResourceExhaustedError,
)
from zeebe_tpu.gateway.auth import TenantAuthorizer  # noqa: E402
from zeebe_tpu.protocol import DEFAULT_TENANT, ValueType, command  # noqa: E402
from zeebe_tpu.protocol.intent import (  # noqa: E402
    DeploymentIntent,
    IncidentIntent,
    JobBatchIntent,
    JobIntent,
    MessageIntent,
    ProcessInstanceCreationIntent,
    ProcessInstanceIntent,
    SignalIntent,
    VariableDocumentIntent,
)

VERSION = "8.4.0-tpu"


from zeebe_tpu.utils.metrics import REGISTRY as _REG  # noqa: E402

_M_LONG_POLL_QUEUED = _REG.gauge(
    "long_polling_queued_current",
    "ActivateJobs requests parked waiting for jobs").labels()
_M_TOPOLOGY_ROLES = _REG.gauge(
    "gateway_topology_partition_roles",
    "known partition roles (3=leader 1=follower)", ("node", "partition"))


def _vars(json_str: str) -> dict:
    if not json_str:
        return {}
    parsed = json.loads(json_str)
    if not isinstance(parsed, dict):
        raise ValueError("variables must be a JSON object")
    return parsed


class GatewayService:
    """One method per rpc; raises grpc errors via context.abort."""

    def __init__(self, runtime: ClusterRuntime,
                 auth: TenantAuthorizer | None = None) -> None:
        self.runtime = runtime
        self.auth = auth or TenantAuthorizer()

    # -- tenant authorization (IdentityInterceptor equivalent) -----------------

    def _check_tenant(self, context, requested: str) -> str:
        error, detail = self.auth.check(context.invocation_metadata(), requested)
        if error == "disabled":
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, detail)
        elif error == "denied":
            context.abort(grpc.StatusCode.PERMISSION_DENIED, detail)
        return detail  # the validated tenant id

    def _tenant_fields(self, context, requested: str) -> dict:
        """Validated tenant + authorized-tenants claim for a command value.
        With multi-tenancy off and the default tenant addressed, commands stay
        in their pre-tenancy shape (no extra fields)."""
        tenant = self._check_tenant(context, requested)
        if not self.auth.enabled and tenant == DEFAULT_TENANT:
            return {}
        return {
            "tenantId": tenant,
            "authorizedTenants": self.auth.authorized_tenants(
                context.invocation_metadata()),
        }

    def _tenant_ids_field(self, context, requested_ids) -> dict:
        """ActivateJobs/StreamActivatedJobs tenantIds filter."""
        ids = [t for t in (requested_ids or []) if t] or [DEFAULT_TENANT]
        for tenant in ids:
            self._check_tenant(context, tenant)
        if not self.auth.enabled and ids == [DEFAULT_TENANT]:
            return {}
        return {"tenantIds": ids}

    # -- topology --------------------------------------------------------------

    def Topology(self, request, context):
        topo = self.runtime.topology()
        brokers = []
        for i, b in enumerate(topo["brokers"]):
            partitions = [
                pb.Partition(
                    partitionId=p["partitionId"],
                    role=pb.Partition.LEADER if p["role"] == "leader"
                    else pb.Partition.FOLLOWER,
                    health=pb.Partition.HEALTHY,
                )
                for p in b["partitions"]
            ]
            for p in b["partitions"]:
                _M_TOPOLOGY_ROLES.labels(str(i), str(p["partitionId"])).set(
                    3 if p["role"] == "leader" else 1)
            brokers.append(pb.BrokerInfo(
                nodeId=i, host="127.0.0.1", port=0, partitions=partitions,
                version=VERSION,
            ))
        return pb.TopologyResponse(
            brokers=brokers, clusterSize=topo["clusterSize"],
            partitionsCount=topo["partitionsCount"],
            replicationFactor=topo["replicationFactor"], gatewayVersion=VERSION,
        )

    # -- deployment ------------------------------------------------------------

    def DeployResource(self, request, context):
        resources = [
            {"resourceName": r.name, "resource": r.content.decode("utf-8")}
            for r in request.resources
        ]
        record = self._submit(
            context, DEPLOYMENT_PARTITION,
            command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE,
                    {"resources": resources,
                     **self._tenant_fields(context, request.tenantId)}),
        )
        deployments = [
            pb.Deployment(process=pb.ProcessMetadata(
                bpmnProcessId=m["bpmnProcessId"], version=m["version"],
                processDefinitionKey=m["processDefinitionKey"],
                resourceName=m["resourceName"],
                tenantId=m.get("tenantId") or DEFAULT_TENANT,
            ))
            for m in record.value.get("processesMetadata", [])
        ]
        for m in record.value.get("formMetadata", []):
            deployments.append(pb.Deployment(form=pb.FormMetadata(
                formId=m.get("formId", ""), version=m.get("version", 1),
                formKey=m.get("formKey", -1),
                resourceName=m.get("resourceName", ""),
                tenantId=m.get("tenantId") or DEFAULT_TENANT,
            )))
        for m in record.value.get("decisionsMetadata", []):
            deployments.append(pb.Deployment(decision=pb.DecisionMetadata(
                dmnDecisionId=m.get("decisionId", ""),
                dmnDecisionName=m.get("decisionName", ""),
                version=m.get("version", 1), decisionKey=m.get("decisionKey", -1),
                dmnDecisionRequirementsId=m.get("decisionRequirementsId", ""),
                decisionRequirementsKey=m.get("decisionRequirementsKey", -1),
                tenantId=m.get("tenantId") or DEFAULT_TENANT,
            )))
        return pb.DeployResourceResponse(
            key=record.key, deployments=deployments,
            tenantId=record.value.get("tenantId") or DEFAULT_TENANT,
        )

    # -- process instances -----------------------------------------------------

    def CreateProcessInstance(self, request, context):
        partition = self.runtime.partition_for_new_instance()
        value = {
            "bpmnProcessId": request.bpmnProcessId,
            "processDefinitionKey": request.processDefinitionKey or -1,
            "version": request.version or -1,
            "variables": self._parse_vars(context, request.variables),
            **self._tenant_fields(context, request.tenantId),
        }
        if request.startInstructions:
            value["startInstructions"] = [
                {"elementId": si.elementId} for si in request.startInstructions
            ]
        record = self._submit(
            context, partition,
            command(ValueType.PROCESS_INSTANCE_CREATION,
                    ProcessInstanceCreationIntent.CREATE, value),
        )
        return pb.CreateProcessInstanceResponse(
            processDefinitionKey=record.value.get("processDefinitionKey", -1),
            bpmnProcessId=record.value.get("bpmnProcessId", ""),
            version=record.value.get("version", -1),
            processInstanceKey=record.value.get("processInstanceKey", -1),
            tenantId=record.value.get("tenantId") or DEFAULT_TENANT,
        )

    def CreateProcessInstanceWithResult(self, request, context):
        """The engine parks the request and answers it from the root-completion
        step with the final variables (ProcessInstanceResultIntent.COMPLETED)."""
        inner = request.request
        partition = self.runtime.partition_for_new_instance()
        value = {
            "bpmnProcessId": inner.bpmnProcessId,
            "processDefinitionKey": inner.processDefinitionKey or -1,
            "version": inner.version or -1,
            "variables": self._parse_vars(context, inner.variables),
            "awaitResult": True,
            "fetchVariables": list(request.fetchVariables),
            **self._tenant_fields(context, inner.tenantId),
        }
        timeout_s = (request.requestTimeout or 10_000) / 1000
        record = self._submit(
            context, partition,
            command(ValueType.PROCESS_INSTANCE_CREATION,
                    ProcessInstanceCreationIntent.CREATE, value),
            timeout_s=timeout_s,
        )
        return pb.CreateProcessInstanceWithResultResponse(
            processDefinitionKey=record.value.get("processDefinitionKey", -1),
            bpmnProcessId=record.value.get("bpmnProcessId", ""),
            version=record.value.get("version", -1),
            processInstanceKey=record.value.get("processInstanceKey", -1),
            variables=json.dumps(record.value.get("variables", {})),
            tenantId=record.value.get("tenantId") or DEFAULT_TENANT,
        )

    def CancelProcessInstance(self, request, context):
        partition = self.runtime.partition_for_key(request.processInstanceKey)
        self._submit(
            context, partition,
            command(ValueType.PROCESS_INSTANCE, ProcessInstanceIntent.CANCEL,
                    {}, key=request.processInstanceKey),
        )
        return pb.CancelProcessInstanceResponse()

    # -- messages / signals ----------------------------------------------------

    def PublishMessage(self, request, context):
        partition = self.runtime.partition_for_correlation_key(request.correlationKey)
        record = self._submit(
            context, partition,
            command(ValueType.MESSAGE, MessageIntent.PUBLISH, {
                "name": request.name,
                "correlationKey": request.correlationKey,
                "timeToLive": request.timeToLive,
                "messageId": request.messageId,
                "variables": self._parse_vars(context, request.variables),
                **self._tenant_fields(context, request.tenantId),
            }),
        )
        return pb.PublishMessageResponse(
            key=record.key,
            tenantId=record.value.get("tenantId") or DEFAULT_TENANT)

    def BroadcastSignal(self, request, context):
        record = self._submit(
            context, DEPLOYMENT_PARTITION,
            command(ValueType.SIGNAL, SignalIntent.BROADCAST, {
                "signalName": request.signalName,
                "variables": self._parse_vars(context, request.variables),
                **self._tenant_fields(context, request.tenantId),
            }),
        )
        return pb.BroadcastSignalResponse(
            key=record.key,
            tenantId=record.value.get("tenantId") or DEFAULT_TENANT)

    # -- jobs ------------------------------------------------------------------

    def ActivateJobs(self, request, context):
        """Fan out across partitions round-robin until maxJobs or all empty;
        park until requestTimeout if nothing was activated, woken by the
        jobs-available notification (reference:
        LongPollingActivateJobsHandler.java:36 — no poll loop)."""
        deadline = time.time() + max((request.requestTimeout or 0), 0) / 1000
        remaining = request.maxJobsToActivate or 32
        tenant_filter = self._tenant_ids_field(context, request.tenantIds)
        hub = getattr(self.runtime, "jobs_hub", None)
        while context.is_active():
            seen_version = hub.version(request.type) if hub is not None else 0
            jobs = []
            for partition_id in range(1, self.runtime.partition_count + 1):
                if remaining <= 0 or not context.is_active():
                    break
                # peek before writing: an idle long-poller must not flood the
                # replicated log with empty JOB_BATCH ACTIVATE commands —
                # including when only OTHER tenants' jobs woke the hub. The
                # peek must mirror the engine's filter default ([default
                # tenant] when the field is omitted), or residual tenant jobs
                # would make every wakeup write an empty activation.
                if not self.runtime.has_activatable_jobs(
                        partition_id, request.type,
                        tenant_filter.get("tenantIds", [DEFAULT_TENANT])):
                    continue
                record = self._submit(
                    context, partition_id,
                    command(ValueType.JOB_BATCH, JobBatchIntent.ACTIVATE, {
                        "type": request.type,
                        "worker": request.worker or "default",
                        "timeout": request.timeout or 300_000,
                        "maxJobsToActivate": remaining,
                        **tenant_filter,
                    }),
                )
                for key, job in zip(record.value.get("jobKeys", []),
                                    record.value.get("jobs", [])):
                    jobs.append(self._activated_job(request, key, job))
                    remaining -= 1
            if jobs:
                yield pb.ActivateJobsResponse(jobs=jobs)
                return
            now = time.time()
            if now >= deadline:
                return
            _M_LONG_POLL_QUEUED.inc()
            try:
                if hub is not None:
                    # bounded wait so client cancellation is noticed promptly
                    hub.wait(request.type, seen_version, min(deadline - now, 1.0))
                else:
                    time.sleep(0.02)
            finally:
                _M_LONG_POLL_QUEUED.dec()

    def StreamActivatedJobs(self, request, context):
        """Job push: register a client stream with the dispatcher; the broker
        side's jobs-available side effect activates jobs and feeds them here
        with no polling (reference: StreamJobsHandler.java:36 →
        ClientStreamManager → broker RemoteStreamRegistry push)."""
        import queue as _queue

        tenant_filter = self._tenant_ids_field(context, request.tenantIds)
        streams = self.runtime.job_streams
        handle = streams.add_stream(
            request.type, request.worker or "default", request.timeout or 300_000,
            tenant_ids=tenant_filter.get("tenantIds"),
        )
        in_flight = None
        try:
            while context.is_active():
                try:
                    in_flight = handle.jobs.get(timeout=0.25)
                except _queue.Empty:
                    continue
                key, job = in_flight
                yield self._activated_job(request, key, job)
                in_flight = None
        finally:
            # in_flight: dequeued but the client died before/while receiving
            # it — hand it to another stream or yield it back
            streams.remove_stream(handle, in_flight=in_flight)

    def _activated_job(self, request, key: int, job: dict) -> "pb.ActivatedJob":
        return pb.ActivatedJob(
            key=key,
            type=job.get("type", request.type),
            processInstanceKey=job.get("processInstanceKey", -1),
            bpmnProcessId=job.get("bpmnProcessId", ""),
            processDefinitionVersion=job.get("processDefinitionVersion", -1),
            processDefinitionKey=job.get("processDefinitionKey", -1),
            elementId=job.get("elementId", ""),
            elementInstanceKey=job.get("elementInstanceKey", -1),
            customHeaders=json.dumps(job.get("customHeaders", {})),
            worker=job.get("worker", ""),
            retries=job.get("retries", 3),
            deadline=job.get("deadline", -1),
            variables=json.dumps(job.get("variables", {})),
            tenantId=job.get("tenantId") or DEFAULT_TENANT,
        )

    def CompleteJob(self, request, context):
        self._job_command(context, request.jobKey, JobIntent.COMPLETE, {
            "variables": self._parse_vars(context, request.variables),
        })
        return pb.CompleteJobResponse()

    def FailJob(self, request, context):
        self._job_command(context, request.jobKey, JobIntent.FAIL, {
            "retries": request.retries,
            "errorMessage": request.errorMessage,
            "retryBackOff": request.retryBackOff,
            "variables": self._parse_vars(context, request.variables),
        })
        return pb.FailJobResponse()

    def ThrowError(self, request, context):
        self._job_command(context, request.jobKey, JobIntent.THROW_ERROR, {
            "errorCode": request.errorCode,
            "errorMessage": request.errorMessage,
            "variables": self._parse_vars(context, request.variables),
        })
        return pb.ThrowErrorResponse()

    def UpdateJobRetries(self, request, context):
        self._job_command(context, request.jobKey, JobIntent.UPDATE_RETRIES, {
            "retries": request.retries,
        })
        return pb.UpdateJobRetriesResponse()

    def UpdateJobTimeout(self, request, context):
        self._job_command(context, request.jobKey, JobIntent.UPDATE_TIMEOUT, {
            "timeout": request.timeout,
        })
        return pb.UpdateJobTimeoutResponse()

    def _job_command(self, context, job_key: int, intent, value: dict):
        partition = self.runtime.partition_for_key(job_key)
        return self._submit(
            context, partition,
            command(ValueType.JOB, intent, value, key=job_key),
        )

    # -- variables / incidents -------------------------------------------------

    def SetVariables(self, request, context):
        partition = self.runtime.partition_for_key(request.elementInstanceKey)
        record = self._submit(
            context, partition,
            command(ValueType.VARIABLE_DOCUMENT, VariableDocumentIntent.UPDATE, {
                "scopeKey": request.elementInstanceKey,
                "variables": self._parse_vars(context, request.variables),
                "local": request.local,
            }),
        )
        return pb.SetVariablesResponse(key=record.key)

    def ResolveIncident(self, request, context):
        partition = self.runtime.partition_for_key(request.incidentKey)
        self._submit(
            context, partition,
            command(ValueType.INCIDENT, IncidentIntent.RESOLVE, {},
                    key=request.incidentKey),
        )
        return pb.ResolveIncidentResponse()

    # -- pending engine features ----------------------------------------------

    def ModifyProcessInstance(self, request, context):
        from zeebe_tpu.protocol.intent import ProcessInstanceModificationIntent

        partition = self.runtime.partition_for_key(request.processInstanceKey)
        value = {
            "activateInstructions": [
                {
                    "elementId": ai.elementId,
                    "ancestorElementInstanceKey": ai.ancestorElementInstanceKey or -1,
                    "variableInstructions": [
                        {"variables": self._parse_vars(context, vi.variables),
                         "scopeId": vi.scopeId}
                        for vi in ai.variableInstructions
                    ],
                }
                for ai in request.activateInstructions
            ],
            "terminateInstructions": [
                {"elementInstanceKey": ti.elementInstanceKey}
                for ti in request.terminateInstructions
            ],
        }
        self._submit(
            context, partition,
            command(ValueType.PROCESS_INSTANCE_MODIFICATION,
                    ProcessInstanceModificationIntent.MODIFY, value,
                    key=request.processInstanceKey),
        )
        return pb.ModifyProcessInstanceResponse()

    def MigrateProcessInstance(self, request, context):
        from zeebe_tpu.protocol.intent import ProcessInstanceMigrationIntent

        partition = self.runtime.partition_for_key(request.processInstanceKey)
        plan = request.migrationPlan
        value = {
            "migrationPlan": {
                "targetProcessDefinitionKey": plan.targetProcessDefinitionKey,
                "mappingInstructions": [
                    {"sourceElementId": m.sourceElementId,
                     "targetElementId": m.targetElementId}
                    for m in plan.mappingInstructions
                ],
            },
        }
        self._submit(
            context, partition,
            command(ValueType.PROCESS_INSTANCE_MIGRATION,
                    ProcessInstanceMigrationIntent.MIGRATE, value,
                    key=request.processInstanceKey),
        )
        return pb.MigrateProcessInstanceResponse()

    def EvaluateDecision(self, request, context):
        from zeebe_tpu.protocol.intent import DecisionEvaluationIntent

        record = self._submit(
            context, DEPLOYMENT_PARTITION,
            command(ValueType.DECISION_EVALUATION, DecisionEvaluationIntent.EVALUATE, {
                "decisionId": request.decisionId,
                "decisionKey": request.decisionKey or -1,
                "variables": self._parse_vars(context, request.variables),
                **self._tenant_fields(context, request.tenantId),
            }),
        )
        v = record.value
        return pb.EvaluateDecisionResponse(
            decisionKey=v.get("decisionKey", -1),
            decisionId=v.get("decisionId", ""),
            decisionName=v.get("decisionName", ""),
            decisionVersion=v.get("decisionVersion", -1),
            decisionRequirementsId=v.get("decisionRequirementsId", ""),
            decisionRequirementsKey=v.get("decisionRequirementsKey", -1),
            decisionOutput=json.dumps(v.get("decisionOutput")),
            failedDecisionId=v.get("failedDecisionId", ""),
            failureMessage=v.get("evaluationFailureMessage", ""),
            tenantId=v.get("tenantId") or DEFAULT_TENANT,
            decisionInstanceKey=record.key,
            evaluatedDecisions=[
                pb.EvaluatedDecision(
                    decisionId=d.get("decisionId", ""),
                    decisionName=d.get("decisionName", ""),
                    decisionType=d.get("decisionType", ""),
                    decisionOutput=json.dumps(d.get("decisionOutput")),
                    tenantId="<default>",
                    evaluatedInputs=[
                        pb.EvaluatedDecisionInput(
                            inputId=i.get("inputId", ""),
                            inputName=i.get("inputName", ""),
                            inputValue=json.dumps(i.get("inputValue")),
                        ) for i in d.get("evaluatedInputs", [])
                    ],
                    matchedRules=[
                        pb.MatchedDecisionRule(
                            ruleId=r.get("ruleId", ""),
                            ruleIndex=r.get("ruleIndex", 0),
                            evaluatedOutputs=[
                                pb.EvaluatedDecisionOutput(
                                    outputId=o.get("outputId", ""),
                                    outputName=o.get("outputName", ""),
                                    outputValue=json.dumps(o.get("outputValue")),
                                ) for o in r.get("evaluatedOutputs", [])
                            ],
                        ) for r in d.get("matchedRules", [])
                    ],
                ) for d in v.get("evaluatedDecisions", [])
            ],
        )

    def DeleteResource(self, request, context):
        from zeebe_tpu.protocol.intent import ResourceDeletionIntent

        # resources live on the partition that minted their key
        partition = self.runtime.partition_for_key(request.resourceKey)
        self._submit(
            context, partition,
            command(ValueType.RESOURCE_DELETION, ResourceDeletionIntent.DELETE,
                    {"resourceKey": request.resourceKey}),
        )
        return pb.DeleteResourceResponse()

    # -- plumbing --------------------------------------------------------------

    def _parse_vars(self, context, json_str: str) -> dict:
        try:
            return _vars(json_str)
        except (json.JSONDecodeError, ValueError) as exc:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))

    def _submit(self, context, partition_id: int, record, timeout_s: float = 10.0):
        try:
            response = self.runtime.submit(partition_id, record, timeout_s=timeout_s)
        except NoLeaderError as exc:
            context.abort(grpc.StatusCode.UNAVAILABLE, str(exc))
        except ResourceExhaustedError as exc:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc))
        except RequestTimeoutError as exc:
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
        if response.is_rejection:
            context.abort(
                _rejection_status(response.rejection_type.name),
                response.rejection_reason,
            )
        return response


def _rejection_status(rejection_type: str) -> grpc.StatusCode:
    return {
        "INVALID_ARGUMENT": grpc.StatusCode.INVALID_ARGUMENT,
        "NOT_FOUND": grpc.StatusCode.NOT_FOUND,
        "ALREADY_EXISTS": grpc.StatusCode.ALREADY_EXISTS,
        "INVALID_STATE": grpc.StatusCode.FAILED_PRECONDITION,
        "PROCESSING_ERROR": grpc.StatusCode.INTERNAL,
        "EXCEEDED_BATCH_RECORD_SIZE": grpc.StatusCode.RESOURCE_EXHAUSTED,
    }.get(rejection_type, grpc.StatusCode.UNKNOWN)


_SERVICE = "gateway_protocol.Gateway"

_UNARY = {
    "Topology": (pb.TopologyRequest, pb.TopologyResponse),
    "DeployResource": (pb.DeployResourceRequest, pb.DeployResourceResponse),
    "CreateProcessInstance": (pb.CreateProcessInstanceRequest, pb.CreateProcessInstanceResponse),
    "CreateProcessInstanceWithResult": (pb.CreateProcessInstanceWithResultRequest, pb.CreateProcessInstanceWithResultResponse),
    "CancelProcessInstance": (pb.CancelProcessInstanceRequest, pb.CancelProcessInstanceResponse),
    "PublishMessage": (pb.PublishMessageRequest, pb.PublishMessageResponse),
    "CompleteJob": (pb.CompleteJobRequest, pb.CompleteJobResponse),
    "FailJob": (pb.FailJobRequest, pb.FailJobResponse),
    "ThrowError": (pb.ThrowErrorRequest, pb.ThrowErrorResponse),
    "UpdateJobRetries": (pb.UpdateJobRetriesRequest, pb.UpdateJobRetriesResponse),
    "UpdateJobTimeout": (pb.UpdateJobTimeoutRequest, pb.UpdateJobTimeoutResponse),
    "SetVariables": (pb.SetVariablesRequest, pb.SetVariablesResponse),
    "ResolveIncident": (pb.ResolveIncidentRequest, pb.ResolveIncidentResponse),
    "BroadcastSignal": (pb.BroadcastSignalRequest, pb.BroadcastSignalResponse),
    "ModifyProcessInstance": (pb.ModifyProcessInstanceRequest, pb.ModifyProcessInstanceResponse),
    "MigrateProcessInstance": (pb.MigrateProcessInstanceRequest, pb.MigrateProcessInstanceResponse),
    "EvaluateDecision": (pb.EvaluateDecisionRequest, pb.EvaluateDecisionResponse),
    "DeleteResource": (pb.DeleteResourceRequest, pb.DeleteResourceResponse),
}

_SERVER_STREAMING = {
    "ActivateJobs": (pb.ActivateJobsRequest, pb.ActivateJobsResponse),
    "StreamActivatedJobs": (pb.StreamActivatedJobsRequest, pb.ActivatedJob),
}


class Gateway:
    """Boots the gRPC server over a ClusterRuntime (StandaloneGateway +
    embedded-broker mode in one; reference: dist StandaloneGateway.java)."""

    def __init__(self, runtime: ClusterRuntime, bind: str = "127.0.0.1:0",
                 max_workers: int = 16,
                 auth: TenantAuthorizer | None = None,
                 oauth: "OAuthValidator | None" = None,
                 extra_interceptors: tuple = ()) -> None:
        self.runtime = runtime
        if auth is None:
            auth = TenantAuthorizer(oauth=oauth)
        elif oauth is not None and auth.oauth is None:
            # the JWT's authorized_tenants claim feeds tenant authorization
            auth.oauth = oauth
        self.service = GatewayService(runtime, auth=auth)
        handlers = {}
        for name, (req_cls, resp_cls) in _UNARY.items():
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                _wrap(getattr(self.service, name)),
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )
        for name, (req_cls, resp_cls) in _SERVER_STREAMING.items():
            handlers[name] = grpc.unary_stream_rpc_method_handler(
                _wrap(getattr(self.service, name)),
                request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString,
            )
        interceptors = ()
        if oauth is not None and oauth.enabled:
            # authenticate before any handler runs (IdentityInterceptor seam)
            from zeebe_tpu.gateway.oauth import auth_server_interceptor

            interceptors = (auth_server_interceptor(oauth),)
        # externally-loaded interceptors run AFTER auth, like the
        # reference's InterceptorRepository chain (utils/external_code)
        interceptors = interceptors + tuple(extra_interceptors or ())
        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            interceptors=interceptors,
        )
        self.server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
        )
        self.port = self.server.add_insecure_port(bind)

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> None:
        self.server.start()

    def stop(self, grace: float = 1.0) -> None:
        self.server.stop(grace)


def _wrap(method: Callable) -> Callable:
    """Per-rpc request metrics (reference: the gateway's gRPC Prometheus
    interceptor — request totals + latency by method)."""
    import time as _time

    from zeebe_tpu.utils.metrics import REGISTRY

    rpc = method.__name__
    total = REGISTRY.counter(
        "gateway_total_requests", "gateway rpc invocations", ("rpc",)
    ).labels(rpc)
    failed = REGISTRY.counter(
        "gateway_failed_requests", "gateway rpc failures", ("rpc",)
    ).labels(rpc)
    latency = REGISTRY.histogram(
        "gateway_request_latency", "seconds per gateway rpc", ("rpc",)
    ).labels(rpc)

    def handler(request, context):
        total.inc()
        start = _time.perf_counter()
        try:
            return method(request, context)
        except Exception:
            failed.inc()
            raise
        finally:
            latency.observe(_time.perf_counter() - start)

    return handler
