"""BrokerClient + ClusterRuntime: the gateway's view of the broker cluster.

Reference: gateway/src/main/java/io/camunda/zeebe/gateway/impl/broker/
BrokerClient / BrokerRequestManager.java:40 — request/response correlation with
retries on leader-miss, partition selection (RequestDispatchStrategy round-robin,
PartitionIdIterator), BrokerTopologyManager fed by gossip.

``ClusterRuntime`` drives an in-process broker cluster on a background thread
(the brokers' actor loop equivalent): gRPC handler threads submit commands and
block on a response future; the pump thread advances raft/processing and
resolves futures from each broker's response sink."""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any

from zeebe_tpu.broker import Broker, BrokerCfg
from zeebe_tpu.broker.broker import resolve_leader_partition
from zeebe_tpu.cluster.messaging import LoopbackNetwork
from zeebe_tpu.parallel.partitioning import subscription_partition_id
from zeebe_tpu.protocol import Record
from zeebe_tpu.protocol.keys import decode_partition_id

logger = logging.getLogger("zeebe_tpu.gateway.runtime")

DEPLOYMENT_PARTITION = 1


class RequestTimeoutError(Exception):
    pass


class DeadlineExceededError(RequestTimeoutError):
    """The overall per-request deadline expired (bounded gateway resend
    loop, ``ZEEBE_GATEWAY_REQUEST_TIMEOUT_MS``): the request is abandoned
    with a typed error instead of retrying forever against a dead
    partition. Subclasses RequestTimeoutError so existing gRPC mappings
    (DEADLINE_EXCEEDED) and retry handlers keep working."""


class NoLeaderError(Exception):
    pass


class ResourceExhaustedError(Exception):
    pass


class GatewayRuntimeBase:
    """Shared request plumbing for gateway runtimes — in-process
    (:class:`ClusterRuntime`), one-broker-per-process TCP
    (:class:`~zeebe_tpu.gateway.tcp_runtime.TcpClusterRuntime`), and
    supervised per-core workers
    (:class:`~zeebe_tpu.multiproc.runtime.MultiProcClusterRuntime`): the
    nonce'd request-id sequence, the pending/response correlation table,
    and the partition-selection helpers."""

    def _init_jobstreams(self) -> None:
        """Jobs-available hub (long-poll wakeup) + push dispatcher (job
        streams); fed by the brokers' post-commit jobs-available side effect."""
        from zeebe_tpu.gateway.jobstream import JobNotificationHub, JobStreamDispatcher

        self.jobs_hub = JobNotificationHub()
        self.job_streams = JobStreamDispatcher(self)

    def _on_jobs_available(self, partition_id: int, job_types: set) -> None:
        self.jobs_hub.notify(job_types)
        self.job_streams.on_jobs_available(partition_id, job_types)

    def _init_requests(self) -> None:
        self._round_robin = itertools.count()
        # request ids carry a startup nonce in the high bits: a restarted
        # gateway must never resolve a backlog command's stale request_id
        # against a fresh in-flight request
        nonce = int(time.time() * 1000) & 0x3FFFFF
        self._request_seq = itertools.count((nonce << 32) + 1)
        self._pending: dict[int, threading.Event] = {}
        self._responses: dict[int, Record] = {}

    def _register_request(self) -> tuple[int, threading.Event]:
        request_id = next(self._request_seq)
        event = threading.Event()
        self._pending[request_id] = event
        return request_id, event

    def _resolve_request(self, request_id: int, record: Record) -> None:
        event = self._pending.get(request_id)
        if event is not None:
            self._responses[request_id] = record
            event.set()

    def _take_response(self, request_id: int, event: threading.Event,
                       deadline: float, partition_id: int, timeout_s: float) -> Record:
        try:
            if not event.wait(max(deadline - time.time(), 0.001)):
                raise RequestTimeoutError(
                    f"partition {partition_id} did not respond in {timeout_s}s"
                )
            return self._responses.pop(request_id)
        finally:
            self._pending.pop(request_id, None)
            self._responses.pop(request_id, None)

    def partition_for_new_instance(self) -> int:
        return next(self._round_robin) % self.partition_count + 1

    def partition_for_correlation_key(self, key: str) -> int:
        return subscription_partition_id(key, self.partition_count)

    @staticmethod
    def partition_for_key(key: int) -> int:
        return decode_partition_id(key)


class ClusterRuntime(GatewayRuntimeBase):
    """Owns N in-process brokers and the pump thread; thread-safe ingress."""

    def __init__(self, broker_count: int = 1, partition_count: int = 1,
                 replication_factor: int = 1, directory=None,
                 exporters_factory=None,
                 backpressure_algorithm: str = "vegas",
                 backpressure_enabled: bool = True,
                 disk_min_free_bytes: int = 0,
                 backup_store_directory=None,
                 backup_store=None,
                 kernel_backend: bool = True,
                 kernel_mesh_shards: int = 0) -> None:
        self.partition_count = partition_count
        self.net = LoopbackNetwork(lanes=partition_count)
        self._lock = threading.RLock()
        # per-partition ownership locks: partition p's replicas (across all
        # brokers) advance only under _plocks[p] — the single-writer
        # guarantee the reference gets from partition actors, here extended
        # so one partition's slow step (a kernel compile) no longer stalls
        # the other partitions' raft heartbeats and processing
        self._plocks = {p: threading.RLock()
                        for p in range(1, partition_count + 1)}
        self._init_requests()
        self._init_jobstreams()
        members = [f"broker-{i}" for i in range(broker_count)]
        self.brokers: dict[str, Broker] = {}
        # one mesh per process: every in-process broker's partitions submit
        # kernel groups to the SAME runner, so the whole cluster's batch
        # coalesces onto one device mesh (partition = shard, SURVEY §2.13)
        self.mesh_runner = None
        if kernel_mesh_shards > 0 and kernel_backend:
            from zeebe_tpu.parallel.mesh_runner import MeshKernelRunner

            self.mesh_runner = MeshKernelRunner(n_shards=kernel_mesh_shards)
        from pathlib import Path

        for m in members:
            cfg = BrokerCfg(node_id=m, partition_count=partition_count,
                            replication_factor=replication_factor,
                            cluster_members=members,
                            kernel_backend=kernel_backend)
            self.brokers[m] = Broker(
                cfg, self.net.join(m),
                directory=(Path(directory) / m if directory else None),
                exporters_factory=exporters_factory,
                response_sink=self._resolve,
                backpressure_algorithm=backpressure_algorithm,
                backpressure_enabled=backpressure_enabled,
                disk_min_free_bytes=disk_min_free_bytes,
                backup_store_directory=backup_store_directory,
                backup_store=backup_store,
                mesh_runner=self.mesh_runner,
            )
            self.brokers[m].jobs_listener = self._on_jobs_available
            # topology-driven partition add/remove must hold the partition's
            # ownership lock so lifecycle never races that partition's pump
            self.brokers[m].partition_guard = self._partition_guard
        self._running = False
        self._threads: list[threading.Thread] = []

    def _partition_guard(self, partition_id: int):
        import contextlib

        lock = self._plocks.get(partition_id)
        return lock if lock is not None else contextlib.nullcontext()

    # -- pump thread -----------------------------------------------------------

    def start(self) -> None:
        self._running = True
        # one ownership thread per partition + one control thread (membership,
        # topology, gossip, observability) — the reference's partition actors,
        # as threads over the same single-writer discipline
        self._threads = [
            threading.Thread(target=self._run_partition, args=(pid,),
                             daemon=True, name=f"partition-{pid}")
            for pid in range(1, self.partition_count + 1)
        ]
        self._threads.append(
            threading.Thread(target=self._run_control, daemon=True,
                             name="cluster-control")
        )
        for t in self._threads:
            t.start()
        self.job_streams.start()
        self.await_leaders()

    def _pump_brokers(self, pump, logged: set) -> None:
        # one broker's pump failure (e.g. crashed/closed but still listed)
        # must not kill the thread that drives every other broker: keep
        # pumping the rest and retry the failed one each tick (a transient
        # cause — momentary disk pressure, a mid-transition race — recovers
        # by itself); the traceback is logged once per failure streak
        for name, broker in list(self.brokers.items()):
            try:
                pump(broker)
                logged.discard(name)
            except Exception:  # noqa: BLE001
                if name not in logged:
                    logged.add(name)
                    logger.exception("broker %s pump failed; retrying "
                                     "(logged once per streak)", name)

    def _run_partition(self, pid: int) -> None:
        logged: set[str] = set()
        while self._running:
            with self._plocks[pid]:
                self._pump_brokers(lambda b: b.pump_partition(pid), logged)
                try:
                    moved = self.net.deliver_lane(pid)
                except Exception:  # noqa: BLE001 — deliver_one already guards
                    # handler errors; this guards queue-level corruption
                    logger.exception("partition %s delivery failed", pid)
                    moved = 0
            if moved == 0:
                time.sleep(0.001)

    def _run_control(self) -> None:
        logged: set[str] = set()
        while self._running:
            with self._lock:
                self._pump_brokers(lambda b: b.pump_control(), logged)
                try:
                    moved = self.net.deliver_lane(0)
                except Exception:  # noqa: BLE001
                    logger.exception("control delivery failed")
                    moved = 0
            if moved == 0:
                time.sleep(0.001)

    def stop(self) -> None:
        self.job_streams.stop()
        self._running = False
        for t in getattr(self, "_threads", []):
            t.join(timeout=5)
        with self._lock:
            for broker in self.brokers.values():
                broker.close()

    def await_leaders(self, timeout_s: float = 30.0) -> None:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            # lock-free role reads: leadership claims are plain attributes
            # maintained by the partition threads
            ready = all(
                self._leader_partition(p) is not None
                for p in range(1, self.partition_count + 1)
            )
            if ready:
                return
            time.sleep(0.01)
        raise RuntimeError("partition leaders not elected in time")

    # -- topology --------------------------------------------------------------

    def _leader_partition(self, partition_id: int):
        return resolve_leader_partition(self.brokers.values(), partition_id)

    def topology(self) -> dict:
        with self._lock:
            return {
                "clusterSize": len(self.brokers),
                "partitionsCount": self.partition_count,
                "replicationFactor": next(iter(self.brokers.values())).cfg.replication_factor,
                "brokers": [b.health() for b in self.brokers.values()],
            }

    def cluster_status(self) -> dict:
        """Cluster-wide health/alert/rate aggregation for the management
        ``GET /cluster/status`` and ``zbctl top`` — the in-process fan-out
        over every hosted broker (reference analog: the gateway's topology
        view, widened with the metrics plane)."""
        from zeebe_tpu.broker.management import cluster_status

        # lock-free reads: broker_status only touches plain attributes and
        # the thread-safe time-series store, so a stalled partition thread
        # cannot wedge the status endpoint behind the control lock
        status = cluster_status(list(self.brokers.values()))
        status["partitionsCount"] = self.partition_count
        return status

    # -- partition selection ---------------------------------------------------

    def has_activatable_jobs(self, partition_id: int, job_type: str,
                             tenant_ids: list[str] | None = None) -> bool:
        """Long-poll peek: checks the leader's state without writing a
        JOB_BATCH ACTIVATE into the replicated log (reference:
        LongPollingActivateJobsHandler parks requests until jobsAvailable).
        ``tenant_ids`` keeps a tenant-filtered long-poll from flooding the log
        with empty activations when only other tenants' jobs exist."""
        lock = self._plocks.get(partition_id)
        if lock is None or not lock.acquire(timeout=1.0):
            # unknown partition, or its ownership thread is stalled: report
            # "no jobs" — long-polls and the push dispatcher both retry
            return False
        try:
            leader = self._leader_partition(partition_id)
            if leader is None or leader.db is None:
                return False
            # committed-read discipline: long-poll peeks run off the pump
            # thread — read the committed activatable index, never the
            # processing-owned transaction slot (zlint caught the old
            # `with leader.db.transaction()` here racing processing)
            from zeebe_tpu.engine.engine_state import JobState

            return JobState.any_activatable_committed(
                leader.db, job_type, tenant_ids)
        finally:
            lock.release()

    # -- request path ----------------------------------------------------------

    def submit(self, partition_id: int, record: Record,
               timeout_s: float = 10.0) -> Record:
        """Write a command to the partition leader, await the engine response
        (retrying on leader miss — RequestRetryHandler semantics). Mints the
        trace's ROOT span: ``client_write`` returns the command's assigned
        stream position, which IS the trace id the broker-side spans
        (processing, export) key on — the gateway request joins its causal
        tree with no extra wire fields."""
        from zeebe_tpu.broker.partition import BackpressureExceeded
        from zeebe_tpu.observability.tracer import get_tracer

        tracer = get_tracer()
        # capture the enabled flag ONCE: enabling tracing while this request
        # is in flight must not feed perf_counter() minus the 0.0 sentinel
        # into the latency histogram
        traced = tracer.enabled
        t_submit = time.perf_counter() if traced else 0.0
        request_id, event = self._register_request()
        rec = record.replace(request_id=request_id, request_stream_id=0)
        deadline = time.time() + timeout_s
        written = False
        command_position = -1
        lock = self._plocks.get(partition_id)
        if lock is None:
            # a stale/crafted key can decode to a partition this cluster
            # never had — the same UNAVAILABLE surface as a leaderless one
            self._pending.pop(request_id, None)
            raise NoLeaderError(f"unknown partition {partition_id}")
        while time.time() < deadline:
            # bounded acquire: a stalled partition (held ownership lock) must
            # time this request out, not block the gRPC handler forever
            if lock.acquire(timeout=0.05):
                try:
                    leader = self._leader_partition(partition_id)
                    if leader is not None:
                        try:
                            position = leader.client_write(rec)
                            if position is not None:
                                written = True
                                command_position = position
                        except BackpressureExceeded as exc:
                            self._pending.pop(request_id, None)
                            raise ResourceExhaustedError(str(exc)) from exc
                finally:
                    lock.release()
            if written:
                break
            time.sleep(0.01)
        if not written:
            self._pending.pop(request_id, None)
            raise NoLeaderError(f"no leader for partition {partition_id}")
        response = self._take_response(request_id, event, deadline,
                                       partition_id, timeout_s)
        if traced:
            latency = time.perf_counter() - t_submit
            tracer.observe_ack("gateway", latency)
            trace_id = f"{partition_id}:{command_position}"
            if tracer.sampled(trace_id):
                attrs = {"position": command_position,
                         "requestId": request_id,
                         "valueType": record.value_type.name,
                         "intent": record.intent.name}
                if response.is_rejection:
                    attrs["rejection"] = response.rejection_type.name
                tracer.emit(trace_id, "gateway.request", latency, partition_id,
                            attrs=attrs)
        return response

    def _resolve(self, response) -> None:
        self._resolve_request(response.request_id, response.record)


