"""Model libraries: BPMN (and DMN, forthcoming) — SURVEY.md §2.9."""
