"""BPMN process model + fluent builder.

Reference: bpmn-model/src/main/java/io/camunda/zeebe/model/bpmn/Bpmn.java and
builder/* — the fluent builder API used by every engine test
(``Bpmn.createExecutableProcess("p").startEvent().serviceTask(...)…``), plus the
zeebe extension attributes (taskDefinition, ioMapping, taskHeaders).

This is the *model* layer: an id-addressed graph of elements and sequence
flows with raw (unparsed) expression strings. Deploy-time transformation and
validation into an ExecutableProcess live in executable.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from zeebe_tpu.protocol.enums import BpmnElementType, BpmnEventType


@dataclasses.dataclass(slots=True)
class Mapping:
    """One zeebe:input/zeebe:output mapping: source expression → target path."""

    source: str
    target: str


@dataclasses.dataclass(slots=True)
class TimerDefinition:
    """Raw timer definition; exactly one of the fields is set."""

    duration: str | None = None  # ISO-8601 duration or =expr
    cycle: str | None = None  # R<n>/<duration>
    date: str | None = None  # ISO-8601 datetime or =expr


@dataclasses.dataclass(slots=True)
class MessageDefinition:
    name: str
    correlation_key: str | None = None  # FEEL expr (=...) required for catch


@dataclasses.dataclass(slots=True)
class MultiInstanceDefinition:
    input_collection: str = ""
    input_element: str | None = None
    output_collection: str | None = None
    output_element: str | None = None
    is_sequential: bool = False


@dataclasses.dataclass(slots=True)
class ProcessElement:
    id: str
    element_type: BpmnElementType
    name: str = ""
    event_type: BpmnEventType = BpmnEventType.NONE
    # job-worker tasks (zeebe:taskDefinition)
    job_type: str | None = None
    job_retries: str = "3"
    task_headers: dict[str, str] = dataclasses.field(default_factory=dict)
    # gateways
    default_flow_id: str | None = None
    # events
    timer: TimerDefinition | None = None
    message: MessageDefinition | None = None
    error_code: str | None = None
    signal_name: str | None = None
    escalation_code: str | None = None
    interrupting: bool = True
    attached_to_id: str | None = None  # boundary events
    # io mappings (zeebe:ioMapping)
    inputs: list[Mapping] = dataclasses.field(default_factory=list)
    outputs: list[Mapping] = dataclasses.field(default_factory=list)
    # containers
    parent_id: str | None = None  # enclosing sub-process / process
    # multi-instance
    multi_instance: MultiInstanceDefinition | None = None
    # call activity
    called_process_id: str | None = None
    # script task with expression (non-job-worker flavor)
    script_expression: str | None = None
    script_result_variable: str | None = None
    # business rule task with called decision
    called_decision_id: str | None = None
    native_user_task: bool = False
    user_task_assignee: str | None = None
    user_task_candidate_groups: str | None = None
    decision_result_variable: str | None = None
    # linked Camunda form (zeebe:formDefinition formId)
    form_id: str | None = None
    # link events (linkEventDefinition name; throw routes to same-scope catch)
    link_name: str | None = None


@dataclasses.dataclass(slots=True)
class SequenceFlow:
    id: str
    source_id: str
    target_id: str
    condition: str | None = None  # FEEL expression body (no '=' marker)


@dataclasses.dataclass(slots=True)
class ProcessModel:
    """One <bpmn:process> — the unit of deployment (with siblings in a file)."""

    process_id: str
    name: str = ""
    elements: dict[str, ProcessElement] = dataclasses.field(default_factory=dict)
    flows: dict[str, SequenceFlow] = dataclasses.field(default_factory=dict)

    def outgoing(self, element_id: str) -> list[SequenceFlow]:
        return [f for f in self.flows.values() if f.source_id == element_id]

    def incoming(self, element_id: str) -> list[SequenceFlow]:
        return [f for f in self.flows.values() if f.target_id == element_id]


class BpmnModelError(Exception):
    pass


class ProcessBuilder:
    """Fluent builder. Each element-adding call connects the cursor element to
    the new one with an auto-named sequence flow; ``condition_expression``
    annotates the most recently created flow; ``move_to_element`` repositions
    the cursor for branching (reference: AbstractFlowNodeBuilder.moveToNode)."""

    def __init__(self, process_id: str, name: str = "") -> None:
        self.model = ProcessModel(process_id=process_id, name=name or process_id)
        self._cursor: str | None = None
        self._flow_count = 0
        self._next_flow_id: str | None = None
        self._next_condition: str | None = None
        self._next_default: bool = False
        self._scope_stack: list[str] = []  # enclosing sub-process ids

    # -- plumbing ------------------------------------------------------------

    def _add_element(self, element: ProcessElement, connect: bool = True) -> "ProcessBuilder":
        if element.id in self.model.elements:
            raise BpmnModelError(f"duplicate element id {element.id!r}")
        if self._scope_stack:
            element.parent_id = self._scope_stack[-1]
        self.model.elements[element.id] = element
        if connect and self._cursor is not None:
            self._connect(self._cursor, element.id)
        self._cursor = element.id
        return self

    def _connect(self, source: str, target: str) -> None:
        flow_id = self._next_flow_id
        self._next_flow_id = None
        if flow_id is None:
            self._flow_count += 1
            flow_id = f"flow_{self._flow_count}"
        if flow_id in self.model.flows:
            raise BpmnModelError(f"duplicate flow id {flow_id!r}")
        flow = SequenceFlow(flow_id, source, target, condition=self._next_condition)
        self._next_condition = None
        self.model.flows[flow_id] = flow
        if self._next_default:
            self.model.elements[source].default_flow_id = flow_id
            self._next_default = False

    def _auto_id(self, prefix: str) -> str:
        n = 1
        while f"{prefix}_{n}" in self.model.elements:
            n += 1
        return f"{prefix}_{n}"

    # -- events --------------------------------------------------------------

    def start_event(self, element_id: str | None = None, name: str = "") -> "ProcessBuilder":
        return self._add_element(
            ProcessElement(element_id or self._auto_id("start"), BpmnElementType.START_EVENT, name)
        )

    def timer_start_event(
        self, element_id: str, cycle: str | None = None, date: str | None = None,
        duration: str | None = None, interrupting: bool = True,
    ) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.START_EVENT, event_type=BpmnEventType.TIMER,
            interrupting=interrupting,
        )
        el.timer = TimerDefinition(cycle=cycle, date=date, duration=duration)
        return self._add_element(el)

    def message_start_event(
        self, element_id: str, message_name: str, correlation_key: str | None = None,
        interrupting: bool = True,
    ) -> "ProcessBuilder":
        """Process-level message start events have no correlation key; event
        sub-process message starts require one (reference validators)."""
        el = ProcessElement(
            element_id, BpmnElementType.START_EVENT, event_type=BpmnEventType.MESSAGE,
            interrupting=interrupting,
        )
        el.message = MessageDefinition(name=message_name, correlation_key=correlation_key)
        return self._add_element(el)

    def end_event(self, element_id: str | None = None, name: str = "") -> "ProcessBuilder":
        return self._add_element(
            ProcessElement(element_id or self._auto_id("end"), BpmnElementType.END_EVENT, name)
        )

    def signal_start_event(self, element_id: str, signal_name: str) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.START_EVENT, event_type=BpmnEventType.SIGNAL,
            signal_name=signal_name,
        )
        return self._add_element(el)

    def error_start_event(self, element_id: str, error_code: str | None = None) -> "ProcessBuilder":
        """Typed start event for an error event sub-process (always interrupting)."""
        el = ProcessElement(
            element_id, BpmnElementType.START_EVENT, event_type=BpmnEventType.ERROR,
            error_code=error_code,
        )
        return self._add_element(el)

    def escalation_start_event(
        self, element_id: str, escalation_code: str | None = None, interrupting: bool = True
    ) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.START_EVENT, event_type=BpmnEventType.ESCALATION,
            escalation_code=escalation_code, interrupting=interrupting,
        )
        return self._add_element(el)

    def interrupting(self, flag: bool) -> "ProcessBuilder":
        """Set the interrupting flag of the element at the cursor (event
        sub-process start events, boundary events)."""
        el_id = self._require_cursor()
        self.model.elements[el_id].interrupting = flag
        return self

    def end_event_terminate(self, element_id: str | None = None) -> "ProcessBuilder":
        """Terminate end event: completes, then terminates every other active
        element instance in its flow scope (reference: EndEventProcessor
        TerminateEndEventBehavior)."""
        return self._add_element(
            ProcessElement(
                element_id or self._auto_id("end"),
                BpmnElementType.END_EVENT,
                event_type=BpmnEventType.TERMINATE,
            )
        )

    def intermediate_catch_timer(
        self, element_id: str, duration: str | None = None, date: str | None = None,
        cycle: str | None = None,
    ) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.INTERMEDIATE_CATCH_EVENT, event_type=BpmnEventType.TIMER
        )
        el.timer = TimerDefinition(duration=duration, date=date, cycle=cycle)
        return self._add_element(el)

    def intermediate_catch_message(
        self, element_id: str, message_name: str, correlation_key: str
    ) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.INTERMEDIATE_CATCH_EVENT, event_type=BpmnEventType.MESSAGE
        )
        el.message = MessageDefinition(name=message_name, correlation_key=correlation_key)
        return self._add_element(el)

    def boundary_timer(
        self, element_id: str, attached_to: str, duration: str | None = None,
        interrupting: bool = True, date: str | None = None, cycle: str | None = None,
    ) -> "ProcessBuilder":
        el = ProcessElement(
            element_id,
            BpmnElementType.BOUNDARY_EVENT,
            event_type=BpmnEventType.TIMER,
            interrupting=interrupting,
            attached_to_id=attached_to,
        )
        el.timer = TimerDefinition(duration=duration, date=date, cycle=cycle)
        return self._add_element(el, connect=False)

    def boundary_message(
        self, element_id: str, attached_to: str, message_name: str, correlation_key: str,
        interrupting: bool = True,
    ) -> "ProcessBuilder":
        el = ProcessElement(
            element_id,
            BpmnElementType.BOUNDARY_EVENT,
            event_type=BpmnEventType.MESSAGE,
            interrupting=interrupting,
            attached_to_id=attached_to,
        )
        el.message = MessageDefinition(name=message_name, correlation_key=correlation_key)
        return self._add_element(el, connect=False)

    def boundary_error(
        self, element_id: str, attached_to: str, error_code: str
    ) -> "ProcessBuilder":
        el = ProcessElement(
            element_id,
            BpmnElementType.BOUNDARY_EVENT,
            event_type=BpmnEventType.ERROR,
            attached_to_id=attached_to,
            error_code=error_code,
        )
        return self._add_element(el, connect=False)

    def intermediate_throw_event(self, element_id: str | None = None) -> "ProcessBuilder":
        return self._add_element(
            ProcessElement(element_id or self._auto_id("throw"), BpmnElementType.INTERMEDIATE_THROW_EVENT)
        )

    def intermediate_throw_link(self, element_id: str, link_name: str) -> "ProcessBuilder":
        """Link throw: the token jumps to the same-scope catch link with this
        name (reference: builder IntermediateThrowEventBuilder.link)."""
        el = ProcessElement(
            element_id, BpmnElementType.INTERMEDIATE_THROW_EVENT,
            event_type=BpmnEventType.LINK, link_name=link_name,
        )
        return self._add_element(el)

    def intermediate_catch_link(self, element_id: str, link_name: str) -> "ProcessBuilder":
        """Link catch: entered only via a matching link throw — no incoming
        sequence flow; the cursor moves here so the continuation chains on."""
        el = ProcessElement(
            element_id, BpmnElementType.INTERMEDIATE_CATCH_EVENT,
            event_type=BpmnEventType.LINK, link_name=link_name,
        )
        return self._add_element(el, connect=False)

    def boundary_signal(
        self, element_id: str, attached_to: str, signal_name: str, interrupting: bool = True
    ) -> "ProcessBuilder":
        el = ProcessElement(
            element_id,
            BpmnElementType.BOUNDARY_EVENT,
            event_type=BpmnEventType.SIGNAL,
            interrupting=interrupting,
            attached_to_id=attached_to,
            signal_name=signal_name,
        )
        return self._add_element(el, connect=False)

    def boundary_escalation(
        self, element_id: str, attached_to: str, escalation_code: str | None = None,
        interrupting: bool = True,
    ) -> "ProcessBuilder":
        el = ProcessElement(
            element_id,
            BpmnElementType.BOUNDARY_EVENT,
            event_type=BpmnEventType.ESCALATION,
            interrupting=interrupting,
            attached_to_id=attached_to,
            escalation_code=escalation_code,
        )
        return self._add_element(el, connect=False)

    def intermediate_catch_signal(self, element_id: str, signal_name: str) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.INTERMEDIATE_CATCH_EVENT,
            event_type=BpmnEventType.SIGNAL, signal_name=signal_name,
        )
        return self._add_element(el)

    def intermediate_throw_escalation(self, element_id: str, escalation_code: str) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.INTERMEDIATE_THROW_EVENT,
            event_type=BpmnEventType.ESCALATION, escalation_code=escalation_code,
        )
        return self._add_element(el)

    def intermediate_throw_signal(self, element_id: str, signal_name: str) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.INTERMEDIATE_THROW_EVENT,
            event_type=BpmnEventType.SIGNAL, signal_name=signal_name,
        )
        return self._add_element(el)

    def end_event_escalation(self, element_id: str, escalation_code: str) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.END_EVENT,
            event_type=BpmnEventType.ESCALATION, escalation_code=escalation_code,
        )
        return self._add_element(el)

    def end_event_signal(self, element_id: str, signal_name: str) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.END_EVENT,
            event_type=BpmnEventType.SIGNAL, signal_name=signal_name,
        )
        return self._add_element(el)

    def end_event_error(self, element_id: str, error_code: str) -> "ProcessBuilder":
        el = ProcessElement(
            element_id, BpmnElementType.END_EVENT, event_type=BpmnEventType.ERROR, error_code=error_code
        )
        return self._add_element(el)

    # -- tasks ---------------------------------------------------------------

    def _job_task(
        self, element_id: str | None, etype: BpmnElementType, prefix: str,
        job_type: str, retries: str | int = "3", headers: dict[str, str] | None = None,
    ) -> "ProcessBuilder":
        el = ProcessElement(element_id or self._auto_id(prefix), etype)
        el.job_type = job_type
        el.job_retries = str(retries)
        el.task_headers = dict(headers or {})
        return self._add_element(el)

    def service_task(self, element_id: str | None = None, job_type: str = "", **kw: Any) -> "ProcessBuilder":
        if not job_type:
            raise BpmnModelError("service task requires job_type")
        return self._job_task(element_id, BpmnElementType.SERVICE_TASK, "task", job_type, **kw)

    def send_task(self, element_id: str | None = None, job_type: str = "", **kw: Any) -> "ProcessBuilder":
        if not job_type:
            raise BpmnModelError("send task requires job_type")
        return self._job_task(element_id, BpmnElementType.SEND_TASK, "send", job_type, **kw)

    def user_task(self, element_id: str | None = None, *,
                  native: bool = False, assignee: str | None = None,
                  candidate_groups: str | None = None,
                  form_id: str | None = None) -> "ProcessBuilder":
        """Job-based by default (reference 8.4 default worker contract);
        ``native=True`` uses the zeebe:userTask native lifecycle records;
        ``form_id`` links a deployed Camunda form (zeebe:formDefinition)."""
        el = ProcessElement(element_id or self._auto_id("user"), BpmnElementType.USER_TASK)
        el.form_id = form_id
        if native:
            el.native_user_task = True
            el.user_task_assignee = assignee
            el.user_task_candidate_groups = candidate_groups
        else:
            el.job_type = "io.camunda.zeebe:userTask"
        return self._add_element(el)

    def manual_task(self, element_id: str | None = None) -> "ProcessBuilder":
        return self._add_element(
            ProcessElement(element_id or self._auto_id("manual"), BpmnElementType.MANUAL_TASK)
        )

    def undefined_task(self, element_id: str | None = None) -> "ProcessBuilder":
        return self._add_element(
            ProcessElement(element_id or self._auto_id("task"), BpmnElementType.TASK)
        )

    def script_task(
        self, element_id: str | None = None, *, job_type: str | None = None,
        expression: str | None = None, result_variable: str | None = None, **kw: Any,
    ) -> "ProcessBuilder":
        if job_type:
            return self._job_task(element_id, BpmnElementType.SCRIPT_TASK, "script", job_type, **kw)
        el = ProcessElement(element_id or self._auto_id("script"), BpmnElementType.SCRIPT_TASK)
        el.script_expression = expression
        el.script_result_variable = result_variable
        return self._add_element(el)

    def business_rule_task(
        self, element_id: str | None = None, *, job_type: str | None = None,
        called_decision_id: str | None = None, result_variable: str | None = None, **kw: Any,
    ) -> "ProcessBuilder":
        if job_type:
            return self._job_task(element_id, BpmnElementType.BUSINESS_RULE_TASK, "rule", job_type, **kw)
        el = ProcessElement(element_id or self._auto_id("rule"), BpmnElementType.BUSINESS_RULE_TASK)
        el.called_decision_id = called_decision_id
        el.decision_result_variable = result_variable
        return self._add_element(el)

    def receive_task(self, element_id: str, message_name: str, correlation_key: str) -> "ProcessBuilder":
        el = ProcessElement(element_id, BpmnElementType.RECEIVE_TASK,
                            event_type=BpmnEventType.MESSAGE)
        el.message = MessageDefinition(name=message_name, correlation_key=correlation_key)
        return self._add_element(el)

    def call_activity(self, element_id: str, process_id: str) -> "ProcessBuilder":
        el = ProcessElement(element_id, BpmnElementType.CALL_ACTIVITY)
        el.called_process_id = process_id
        return self._add_element(el)

    # -- containers ----------------------------------------------------------

    def sub_process(self, element_id: str) -> "ProcessBuilder":
        self._add_element(ProcessElement(element_id, BpmnElementType.SUB_PROCESS))
        self._scope_stack.append(element_id)
        self._cursor = None  # next element starts the embedded flow
        return self

    def event_sub_process(self, element_id: str) -> "ProcessBuilder":
        """Event sub-process: no incoming/outgoing flows; starts from its own
        typed start event when that event triggers in the enclosing scope
        (reference: bpmn/container/EventSubProcessProcessor). Close the scope
        with sub_process_done()."""
        self._add_element(
            ProcessElement(element_id, BpmnElementType.EVENT_SUB_PROCESS), connect=False
        )
        self._scope_stack.append(element_id)
        self._cursor = None
        return self

    def sub_process_done(self) -> "ProcessBuilder":
        if not self._scope_stack:
            raise BpmnModelError("sub_process_done without open sub_process")
        scope = self._scope_stack.pop()
        self._cursor = scope
        return self

    # -- gateways ------------------------------------------------------------

    def exclusive_gateway(self, element_id: str | None = None) -> "ProcessBuilder":
        return self._add_element(
            ProcessElement(element_id or self._auto_id("gw"), BpmnElementType.EXCLUSIVE_GATEWAY)
        )

    def parallel_gateway(self, element_id: str | None = None) -> "ProcessBuilder":
        return self._add_element(
            ProcessElement(element_id or self._auto_id("fork"), BpmnElementType.PARALLEL_GATEWAY)
        )

    def inclusive_gateway(self, element_id: str | None = None) -> "ProcessBuilder":
        return self._add_element(
            ProcessElement(element_id or self._auto_id("inc"), BpmnElementType.INCLUSIVE_GATEWAY)
        )

    def event_based_gateway(self, element_id: str | None = None) -> "ProcessBuilder":
        return self._add_element(
            ProcessElement(element_id or self._auto_id("evgw"), BpmnElementType.EVENT_BASED_GATEWAY)
        )

    # -- flow annotations ----------------------------------------------------

    def sequence_flow_id(self, flow_id: str) -> "ProcessBuilder":
        """Name the *next* created flow."""
        self._next_flow_id = flow_id
        return self

    def condition_expression(self, condition: str) -> "ProcessBuilder":
        """Attach a FEEL condition to the *next* created flow (reference
        builder semantics: annotations precede the flow's target element)."""
        self._next_condition = condition
        return self

    def default_flow(self) -> "ProcessBuilder":
        """Mark the *next* created flow as its gateway's default."""
        self._next_default = True
        return self

    # -- io mappings / multi-instance ----------------------------------------

    def zeebe_input(self, source: str, target: str) -> "ProcessBuilder":
        self.model.elements[self._require_cursor()].inputs.append(Mapping(source, target))
        return self

    def zeebe_output(self, source: str, target: str) -> "ProcessBuilder":
        self.model.elements[self._require_cursor()].outputs.append(Mapping(source, target))
        return self

    def multi_instance(
        self, input_collection: str, input_element: str | None = None,
        output_collection: str | None = None, output_element: str | None = None,
        sequential: bool = False,
    ) -> "ProcessBuilder":
        self.model.elements[self._require_cursor()].multi_instance = MultiInstanceDefinition(
            input_collection, input_element, output_collection, output_element, sequential
        )
        return self

    # -- navigation ----------------------------------------------------------

    def move_to_element(self, element_id: str) -> "ProcessBuilder":
        if element_id not in self.model.elements:
            raise BpmnModelError(f"unknown element {element_id!r}")
        self._cursor = element_id
        return self

    def connect_to(self, element_id: str) -> "ProcessBuilder":
        """Add a flow from the cursor to an existing element (joins)."""
        if element_id not in self.model.elements:
            raise BpmnModelError(f"unknown element {element_id!r}")
        self._connect(self._require_cursor(), element_id)
        self._cursor = element_id
        return self

    def _require_cursor(self) -> str:
        if self._cursor is None:
            raise BpmnModelError("no current element")
        return self._cursor

    def done(self) -> ProcessModel:
        if self._scope_stack:
            raise BpmnModelError(f"unclosed sub_process {self._scope_stack[-1]!r}")
        return self.model


class Bpmn:
    """Entry point mirroring the reference's Bpmn facade."""

    @staticmethod
    def create_executable_process(process_id: str, name: str = "") -> ProcessBuilder:
        return ProcessBuilder(process_id, name)
