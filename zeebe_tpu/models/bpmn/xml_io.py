"""BPMN 2.0 XML read/write with zeebe extension elements.

Reference: bpmn-model's XML object model (instance/ + impl/, camunda-xml-model
based) and the zeebe extension namespace (zeebe:taskDefinition, zeebe:ioMapping,
zeebe:taskHeaders, zeebe:calledElement, zeebe:subscription, …). This module maps
the XML to/from the ProcessModel dataclasses in model.py — deliberately schema-
lite: unknown elements are ignored on read (diagram interchange etc.), and the
writer emits only what the engine executes.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Iterable

from zeebe_tpu.models.bpmn.model import (
    BpmnModelError,
    MessageDefinition,
    Mapping,
    MultiInstanceDefinition,
    ProcessElement,
    ProcessModel,
    SequenceFlow,
    TimerDefinition,
)
from zeebe_tpu.protocol.enums import BpmnElementType, BpmnEventType

BPMN_NS = "http://www.omg.org/spec/BPMN/20100524/MODEL"
ZEEBE_NS = "http://camunda.org/schema/zeebe/1.0"

_B = f"{{{BPMN_NS}}}"
_Z = f"{{{ZEEBE_NS}}}"

_TAG_TO_TYPE = {
    "startEvent": BpmnElementType.START_EVENT,
    "endEvent": BpmnElementType.END_EVENT,
    "serviceTask": BpmnElementType.SERVICE_TASK,
    "sendTask": BpmnElementType.SEND_TASK,
    "userTask": BpmnElementType.USER_TASK,
    "manualTask": BpmnElementType.MANUAL_TASK,
    "task": BpmnElementType.TASK,
    "scriptTask": BpmnElementType.SCRIPT_TASK,
    "businessRuleTask": BpmnElementType.BUSINESS_RULE_TASK,
    "receiveTask": BpmnElementType.RECEIVE_TASK,
    "exclusiveGateway": BpmnElementType.EXCLUSIVE_GATEWAY,
    "parallelGateway": BpmnElementType.PARALLEL_GATEWAY,
    "inclusiveGateway": BpmnElementType.INCLUSIVE_GATEWAY,
    "eventBasedGateway": BpmnElementType.EVENT_BASED_GATEWAY,
    "intermediateCatchEvent": BpmnElementType.INTERMEDIATE_CATCH_EVENT,
    "intermediateThrowEvent": BpmnElementType.INTERMEDIATE_THROW_EVENT,
    "boundaryEvent": BpmnElementType.BOUNDARY_EVENT,
    "subProcess": BpmnElementType.SUB_PROCESS,
    "callActivity": BpmnElementType.CALL_ACTIVITY,
}
_TYPE_TO_TAG = {v: k for k, v in _TAG_TO_TYPE.items()}
# an event sub-process is a subProcess with triggeredByEvent="true"
_TYPE_TO_TAG[BpmnElementType.EVENT_SUB_PROCESS] = "subProcess"


def parse_bpmn_xml(xml_text: str | bytes) -> list[ProcessModel]:
    """Parse a BPMN definitions document into its executable processes."""
    try:
        root = ET.fromstring(xml_text)
    except ET.ParseError as exc:
        raise BpmnModelError(f"invalid BPMN XML: {exc}") from exc
    if root.tag != f"{_B}definitions":
        raise BpmnModelError(f"expected bpmn:definitions root, got {root.tag}")
    # messages declared at definitions level: id -> name
    messages: dict[str, str] = {}
    for msg in root.findall(f"{_B}message"):
        messages[msg.get("id", "")] = msg.get("name", "")
    errors: dict[str, str] = {}
    for err in root.findall(f"{_B}error"):
        errors[err.get("id", "")] = err.get("errorCode", "")
    signals: dict[str, str] = {}
    for sig in root.findall(f"{_B}signal"):
        signals[sig.get("id", "")] = sig.get("name", "")
    escalations: dict[str, str] = {}
    for esc in root.findall(f"{_B}escalation"):
        escalations[esc.get("id", "")] = esc.get("escalationCode", "")

    out = []
    for proc in root.findall(f"{_B}process"):
        if proc.get("isExecutable", "true") not in ("true", "1"):
            continue
        model = ProcessModel(process_id=proc.get("id", ""), name=proc.get("name", ""))
        _parse_scope(proc, model, parent_id=None, messages=messages, errors=errors, signals=signals, escalations=escalations)
        out.append(model)
    if not out:
        raise BpmnModelError("no executable process in document")
    return out


def _parse_scope(scope_el, model: ProcessModel, parent_id, messages, errors, signals, escalations) -> None:
    for child in scope_el:
        tag = child.tag.removeprefix(_B)
        if tag == "sequenceFlow":
            flow = SequenceFlow(
                id=child.get("id", ""),
                source_id=child.get("sourceRef", ""),
                target_id=child.get("targetRef", ""),
            )
            cond = child.find(f"{_B}conditionExpression")
            if cond is not None and cond.text:
                text = cond.text.strip()
                flow.condition = text[1:].strip() if text.startswith("=") else text
            model.flows[flow.id] = flow
            continue
        etype = _TAG_TO_TYPE.get(tag)
        if etype is None:
            continue
        if etype == BpmnElementType.SUB_PROCESS and child.get("triggeredByEvent") in ("true", "1"):
            etype = BpmnElementType.EVENT_SUB_PROCESS
        el = ProcessElement(id=child.get("id", ""), element_type=etype, name=child.get("name", ""))
        el.parent_id = parent_id
        if etype == BpmnElementType.BOUNDARY_EVENT:
            el.attached_to_id = child.get("attachedToRef")
            el.interrupting = child.get("cancelActivity", "true") in ("true", "1")
        if etype == BpmnElementType.START_EVENT:
            el.interrupting = child.get("isInterrupting", "true") in ("true", "1")
        if etype == BpmnElementType.EXCLUSIVE_GATEWAY or etype == BpmnElementType.INCLUSIVE_GATEWAY:
            el.default_flow_id = child.get("default")
        _parse_event_definitions(child, el, messages, errors, signals, escalations)
        _parse_extensions(child, el)
        if (el.element_type == BpmnElementType.USER_TASK and not el.native_user_task
                and el.job_type is None):
            # job-based user tasks use the implicit worker contract (reference:
            # UserTaskTransformer's default zeebe:userTask job type); element
            # level, not extensions level — a plain <userTask/> has no
            # extensionElements at all
            el.job_type = "io.camunda.zeebe:userTask"
        model.elements[el.id] = el
        if etype in (BpmnElementType.SUB_PROCESS, BpmnElementType.EVENT_SUB_PROCESS):
            _parse_scope(child, model, parent_id=el.id, messages=messages, errors=errors, signals=signals, escalations=escalations)


def _parse_event_definitions(el_xml, el: ProcessElement, messages, errors, signals, escalations) -> None:
    timer = el_xml.find(f"{_B}timerEventDefinition")
    if timer is not None:
        el.event_type = BpmnEventType.TIMER
        t = TimerDefinition()
        for field, tag in (("duration", "timeDuration"), ("cycle", "timeCycle"), ("date", "timeDate")):
            node = timer.find(f"{_B}{tag}")
            if node is not None and node.text:
                setattr(t, field, node.text.strip())
        el.timer = t
    msg = el_xml.find(f"{_B}messageEventDefinition")
    # receive tasks reference their message by ATTRIBUTE (BPMN), events by a
    # nested messageEventDefinition — same resolution either way
    msg_ref = (msg.get("messageRef", "") if msg is not None
               else el_xml.get("messageRef")
               if el.element_type == BpmnElementType.RECEIVE_TASK else None)
    if msg_ref is not None:
        el.event_type = BpmnEventType.MESSAGE
        el.message = MessageDefinition(name=messages.get(msg_ref, msg_ref))
    err = el_xml.find(f"{_B}errorEventDefinition")
    if err is not None:
        el.event_type = BpmnEventType.ERROR
        el.error_code = errors.get(err.get("errorRef", ""), err.get("errorRef", ""))
    sig = el_xml.find(f"{_B}signalEventDefinition")
    if sig is not None:
        el.event_type = BpmnEventType.SIGNAL
        el.signal_name = signals.get(sig.get("signalRef", ""), sig.get("signalRef", ""))
    esc = el_xml.find(f"{_B}escalationEventDefinition")
    if esc is not None:
        el.event_type = BpmnEventType.ESCALATION
        ref = esc.get("escalationRef")
        el.escalation_code = escalations.get(ref, ref) if ref else None
    link = el_xml.find(f"{_B}linkEventDefinition")
    if link is not None:
        el.event_type = BpmnEventType.LINK
        el.link_name = link.get("name", "")
    if el_xml.find(f"{_B}terminateEventDefinition") is not None:
        el.event_type = BpmnEventType.TERMINATE


def _parse_extensions(el_xml, el: ProcessElement) -> None:
    ext = el_xml.find(f"{_B}extensionElements")
    if ext is None:
        # receive tasks / message events may still carry subscriptions
        return
    task_def = ext.find(f"{_Z}taskDefinition")
    if task_def is not None:
        el.job_type = task_def.get("type")
        el.job_retries = task_def.get("retries", "3")
    headers = ext.find(f"{_Z}taskHeaders")
    if headers is not None:
        for h in headers.findall(f"{_Z}header"):
            el.task_headers[h.get("key", "")] = h.get("value", "")
    io = ext.find(f"{_Z}ioMapping")
    if io is not None:
        for node in io.findall(f"{_Z}input"):
            el.inputs.append(Mapping(node.get("source", ""), node.get("target", "")))
        for node in io.findall(f"{_Z}output"):
            el.outputs.append(Mapping(node.get("source", ""), node.get("target", "")))
    sub = ext.find(f"{_Z}subscription")
    if sub is not None and el.message is not None:
        el.message.correlation_key = sub.get("correlationKey")
    called = ext.find(f"{_Z}calledElement")
    if called is not None:
        el.called_process_id = called.get("processId")
    decision = ext.find(f"{_Z}calledDecision")
    if decision is not None:
        el.called_decision_id = decision.get("decisionId")
        el.decision_result_variable = decision.get("resultVariable")
    script = ext.find(f"{_Z}script")
    if script is not None:
        el.script_expression = script.get("expression")
        el.script_result_variable = script.get("resultVariable")
    form_def = ext.find(f"{_Z}formDefinition")
    if form_def is not None:
        el.form_id = form_def.get("formId")
    native_ut = ext.find(f"{_Z}userTask")
    if native_ut is not None:
        el.native_user_task = True
        assignment = ext.find(f"{_Z}assignmentDefinition")
        if assignment is not None:
            el.user_task_assignee = assignment.get("assignee")
            el.user_task_candidate_groups = assignment.get("candidateGroups")
    loop = el_xml.find(f"{_B}multiInstanceLoopCharacteristics")
    if loop is not None:
        mi = MultiInstanceDefinition(is_sequential=loop.get("isSequential", "false") in ("true", "1"))
        z_loop = None
        lext = loop.find(f"{_B}extensionElements")
        if lext is not None:
            z_loop = lext.find(f"{_Z}loopCharacteristics")
        if z_loop is not None:
            mi.input_collection = z_loop.get("inputCollection", "")
            mi.input_element = z_loop.get("inputElement")
            mi.output_collection = z_loop.get("outputCollection")
            mi.output_element = z_loop.get("outputElement")
        el.multi_instance = mi


# ---------------------------------------------------------------------------
# Writer


def to_bpmn_xml(models: Iterable[ProcessModel] | ProcessModel) -> str:
    if isinstance(models, ProcessModel):
        models = [models]
    ET.register_namespace("bpmn", BPMN_NS)
    ET.register_namespace("zeebe", ZEEBE_NS)
    root = ET.Element(f"{_B}definitions", {"targetNamespace": "http://zeebe-tpu/bpmn"})
    message_names: dict[str, str] = {}
    error_codes: dict[str, str] = {}
    signal_names: dict[str, str] = {}
    escalation_codes: dict[str, str] = {}
    for model in models:
        for el in model.elements.values():
            if el.message is not None:
                message_names.setdefault(el.message.name, f"msg_{len(message_names)}")
            if el.error_code:
                error_codes.setdefault(el.error_code, f"err_{len(error_codes)}")
            if el.signal_name:
                signal_names.setdefault(el.signal_name, f"sig_{len(signal_names)}")
            if el.escalation_code:
                escalation_codes.setdefault(el.escalation_code, f"esc_{len(escalation_codes)}")
    for name, mid in message_names.items():
        ET.SubElement(root, f"{_B}message", {"id": mid, "name": name})
    for code, eid in error_codes.items():
        ET.SubElement(root, f"{_B}error", {"id": eid, "errorCode": code})
    for name, sid in signal_names.items():
        ET.SubElement(root, f"{_B}signal", {"id": sid, "name": name})
    for code, eid in escalation_codes.items():
        ET.SubElement(root, f"{_B}escalation", {"id": eid, "escalationCode": code})
    for model in models:
        proc = ET.SubElement(
            root, f"{_B}process",
            {"id": model.process_id, "name": model.name, "isExecutable": "true"},
        )
        scopes: dict[str | None, ET.Element] = {None: proc}
        # parents first so children have a scope element to attach to
        ordered = sorted(model.elements.values(), key=lambda e: _depth(model, e))
        for el in ordered:
            parent = scopes[el.parent_id]
            node = _element_to_xml(parent, el, message_names, error_codes,
                                   signal_names, escalation_codes)
            if el.element_type in (BpmnElementType.SUB_PROCESS, BpmnElementType.EVENT_SUB_PROCESS):
                scopes[el.id] = node
        for flow in model.flows.values():
            scope_id = model.elements[flow.source_id].parent_id
            node = ET.SubElement(
                scopes[scope_id], f"{_B}sequenceFlow",
                {"id": flow.id, "sourceRef": flow.source_id, "targetRef": flow.target_id},
            )
            if flow.condition:
                cond = ET.SubElement(node, f"{_B}conditionExpression")
                cond.text = f"= {flow.condition}"
    ET.indent(root)
    return ET.tostring(root, encoding="unicode", xml_declaration=True)


def _depth(model: ProcessModel, el: ProcessElement) -> int:
    d = 0
    cur = el
    while cur.parent_id is not None:
        d += 1
        cur = model.elements[cur.parent_id]
    return d


def _element_to_xml(parent, el: ProcessElement, message_names, error_codes,
                    signal_names, escalation_codes) -> ET.Element:
    attrs = {"id": el.id}
    if el.name:
        attrs["name"] = el.name
    if el.element_type == BpmnElementType.BOUNDARY_EVENT:
        attrs["attachedToRef"] = el.attached_to_id or ""
        attrs["cancelActivity"] = "true" if el.interrupting else "false"
    if el.element_type == BpmnElementType.START_EVENT and not el.interrupting:
        attrs["isInterrupting"] = "false"
    if el.element_type == BpmnElementType.EVENT_SUB_PROCESS:
        attrs["triggeredByEvent"] = "true"
    if el.default_flow_id:
        attrs["default"] = el.default_flow_id
    if el.element_type == BpmnElementType.RECEIVE_TASK and el.message is not None:
        # receive tasks reference their message by ATTRIBUTE in BPMN (unlike
        # events, which nest a messageEventDefinition)
        attrs["messageRef"] = message_names[el.message.name]
    node = ET.SubElement(parent, f"{_B}{_TYPE_TO_TAG[el.element_type]}", attrs)

    ext = None

    def ext_el() -> ET.Element:
        nonlocal ext
        if ext is None:
            ext = ET.SubElement(node, f"{_B}extensionElements")
        return ext

    if el.job_type and el.element_type != BpmnElementType.USER_TASK:
        ET.SubElement(
            ext_el(), f"{_Z}taskDefinition", {"type": el.job_type, "retries": el.job_retries}
        )
    if el.task_headers:
        headers = ET.SubElement(ext_el(), f"{_Z}taskHeaders")
        for k, v in el.task_headers.items():
            ET.SubElement(headers, f"{_Z}header", {"key": k, "value": v})
    if el.inputs or el.outputs:
        io = ET.SubElement(ext_el(), f"{_Z}ioMapping")
        for m in el.inputs:
            ET.SubElement(io, f"{_Z}input", {"source": m.source, "target": m.target})
        for m in el.outputs:
            ET.SubElement(io, f"{_Z}output", {"source": m.source, "target": m.target})
    if el.message is not None and el.message.correlation_key:
        ET.SubElement(ext_el(), f"{_Z}subscription", {"correlationKey": el.message.correlation_key})
    if el.called_process_id:
        ET.SubElement(ext_el(), f"{_Z}calledElement", {"processId": el.called_process_id})
    if el.called_decision_id:
        attrs = {"decisionId": el.called_decision_id}
        if el.decision_result_variable:
            attrs["resultVariable"] = el.decision_result_variable
        ET.SubElement(ext_el(), f"{_Z}calledDecision", attrs)
    if el.script_expression:
        attrs = {"expression": el.script_expression}
        if el.script_result_variable:
            attrs["resultVariable"] = el.script_result_variable
        ET.SubElement(ext_el(), f"{_Z}script", attrs)
    if el.form_id:
        ET.SubElement(ext_el(), f"{_Z}formDefinition", {"formId": el.form_id})
    if el.native_user_task:
        ET.SubElement(ext_el(), f"{_Z}userTask", {})
        assignment = {}
        if el.user_task_assignee:
            assignment["assignee"] = el.user_task_assignee
        if el.user_task_candidate_groups:
            assignment["candidateGroups"] = el.user_task_candidate_groups
        if assignment:
            ET.SubElement(ext_el(), f"{_Z}assignmentDefinition", assignment)

    if el.event_type == BpmnEventType.TIMER and el.timer is not None:
        timer = ET.SubElement(node, f"{_B}timerEventDefinition")
        if el.timer.duration:
            ET.SubElement(timer, f"{_B}timeDuration").text = el.timer.duration
        if el.timer.cycle:
            ET.SubElement(timer, f"{_B}timeCycle").text = el.timer.cycle
        if el.timer.date:
            ET.SubElement(timer, f"{_B}timeDate").text = el.timer.date
    elif (el.event_type == BpmnEventType.MESSAGE and el.message is not None
          and el.element_type != BpmnElementType.RECEIVE_TASK):
        ET.SubElement(
            node, f"{_B}messageEventDefinition", {"messageRef": message_names[el.message.name]}
        )
    elif el.event_type == BpmnEventType.ERROR:
        err_attrs = {"errorRef": error_codes[el.error_code]} if el.error_code else {}
        ET.SubElement(node, f"{_B}errorEventDefinition", err_attrs)
    elif el.event_type == BpmnEventType.SIGNAL and el.signal_name:
        ET.SubElement(
            node, f"{_B}signalEventDefinition", {"signalRef": signal_names[el.signal_name]}
        )
    elif el.event_type == BpmnEventType.ESCALATION:
        esc_attrs = (
            {"escalationRef": escalation_codes[el.escalation_code]} if el.escalation_code else {}
        )
        ET.SubElement(node, f"{_B}escalationEventDefinition", esc_attrs)
    elif el.event_type == BpmnEventType.TERMINATE:
        ET.SubElement(node, f"{_B}terminateEventDefinition")
    elif el.event_type == BpmnEventType.LINK and el.link_name is not None:
        ET.SubElement(node, f"{_B}linkEventDefinition",
                      {"name": el.link_name})

    if el.multi_instance is not None:
        mi = el.multi_instance
        loop = ET.SubElement(
            node, f"{_B}multiInstanceLoopCharacteristics",
            {"isSequential": "true" if mi.is_sequential else "false"},
        )
        lext = ET.SubElement(loop, f"{_B}extensionElements")
        attrs = {"inputCollection": mi.input_collection}
        if mi.input_element:
            attrs["inputElement"] = mi.input_element
        if mi.output_collection:
            attrs["outputCollection"] = mi.output_collection
        if mi.output_element:
            attrs["outputElement"] = mi.output_element
        ET.SubElement(lext, f"{_Z}loopCharacteristics", attrs)
    return node
