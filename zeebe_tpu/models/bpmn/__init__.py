"""BPMN model library: fluent builder, XML I/O, deploy-time transformer
(SURVEY.md §2.9 bpmn-model + engine deployment transformation)."""

from zeebe_tpu.models.bpmn.executable import (
    ExecutableElement,
    ExecutableFlow,
    ExecutableProcess,
    ProcessValidationError,
    transform,
)
from zeebe_tpu.models.bpmn.model import (
    Bpmn,
    BpmnModelError,
    ProcessBuilder,
    ProcessElement,
    ProcessModel,
    SequenceFlow,
)
from zeebe_tpu.models.bpmn.xml_io import parse_bpmn_xml, to_bpmn_xml

__all__ = [
    "Bpmn",
    "BpmnModelError",
    "ExecutableElement",
    "ExecutableFlow",
    "ExecutableProcess",
    "ProcessBuilder",
    "ProcessElement",
    "ProcessModel",
    "ProcessValidationError",
    "SequenceFlow",
    "parse_bpmn_xml",
    "to_bpmn_xml",
    "transform",
]
