"""Deploy-time transformation: ProcessModel → ExecutableProcess.

Reference: engine/src/main/java/io/camunda/zeebe/engine/processing/deployment/
model/transformer/ (27 transformers) and model/element/Executable* (33 classes),
plus the Zeebe-specific validators that reject bad deployments.

An ExecutableProcess is the dense, index-addressed form the engine (and the
device table compiler in zeebe_tpu.ops.tables) executes:
- elements are numbered 0..n-1 (0 is the process itself); all references are
  indices, not ids;
- every expression string is parsed once here (FEEL parse errors reject the
  deployment, reference behavior);
- per-element adjacency (outgoing flow indices, incoming counts) is
  precomputed — the parallel-gateway join count is ``incoming_count``.
"""

from __future__ import annotations

import dataclasses
import hashlib

from zeebe_tpu.feel import Expression, FeelParseError, parse_expression, parse_feel
from zeebe_tpu.models.bpmn.model import (
    BpmnModelError,
    ProcessElement,
    ProcessModel,
)
from zeebe_tpu.protocol.enums import BpmnElementType, BpmnEventType


class ProcessValidationError(BpmnModelError):
    """Deployment-rejecting validation failure; message lists all problems."""


@dataclasses.dataclass(slots=True)
class ExecutableFlow:
    idx: int
    id: str
    source_idx: int
    target_idx: int
    condition: Expression | None = None


@dataclasses.dataclass(slots=True)
class ExecutableElement:
    idx: int
    id: str
    element_type: BpmnElementType
    event_type: BpmnEventType = BpmnEventType.NONE
    parent_idx: int = -1  # flow scope (process or sub-process element index)
    outgoing: list[int] = dataclasses.field(default_factory=list)  # flow idxs
    incoming_count: int = 0
    default_flow_idx: int = -1
    # job-worker task attributes (parsed)
    job_type: Expression | None = None
    job_retries: Expression | None = None
    task_headers: dict[str, str] = dataclasses.field(default_factory=dict)
    # events
    timer_duration: Expression | None = None
    timer_cycle: Expression | None = None
    timer_date: Expression | None = None
    message_name: str | None = None
    correlation_key: Expression | None = None
    error_code: str | None = None
    signal_name: str | None = None
    escalation_code: str | None = None
    interrupting: bool = True
    attached_to_idx: int = -1
    boundary_idxs: list[int] = dataclasses.field(default_factory=list)
    # containers
    child_start_idx: int = -1  # none start event of a sub-process/process scope
    # io mappings: (source expression, target path)
    inputs: list[tuple[Expression, str]] = dataclasses.field(default_factory=list)
    outputs: list[tuple[Expression, str]] = dataclasses.field(default_factory=list)
    # misc
    called_process_id: str | None = None
    called_decision_id: str | None = None
    native_user_task: bool = False
    user_task_assignee: str | None = None
    user_task_candidate_groups: str | None = None
    decision_result_variable: str | None = None
    form_id: str | None = None
    script_expression: Expression | None = None
    script_result_variable: str | None = None
    multi_instance: "ExecutableMultiInstance | None" = None
    # link events: the throw's matching same-scope catch (element idx)
    link_name: str | None = None
    link_target_idx: int = -1


@dataclasses.dataclass(slots=True)
class ExecutableMultiInstance:
    input_collection: Expression
    input_element: str | None
    output_collection: str | None
    output_element: Expression | None
    is_sequential: bool


@dataclasses.dataclass(slots=True)
class ExecutableProcess:
    process_id: str
    elements: list[ExecutableElement]
    flows: list[ExecutableFlow]
    by_id: dict[str, int]
    digest: str  # content hash for deployment dedup (reference: DigestGenerator)

    @property
    def root(self) -> ExecutableElement:
        return self.elements[0]

    def element(self, element_id: str) -> ExecutableElement:
        return self.elements[self.by_id[element_id]]

    def flow(self, flow_id: str) -> ExecutableFlow:
        for f in self.flows:
            if f.id == flow_id:
                return f
        raise KeyError(flow_id)

    def none_start_of(self, scope_idx: int) -> int:
        return self.elements[scope_idx].child_start_idx

    def event_sub_processes_of(self, scope_idx: int) -> list[ExecutableElement]:
        return [
            e
            for e in self.elements
            if e.element_type == BpmnElementType.EVENT_SUB_PROCESS and e.parent_idx == scope_idx
        ]


def _parse(source: str | None, errors: list[str], where: str) -> Expression | None:
    if source is None:
        return None
    try:
        return parse_expression(source)
    except FeelParseError as exc:
        errors.append(f"{where}: {exc}")
        return None


def _parse_condition(source: str, errors: list[str], where: str) -> Expression | None:
    try:
        return parse_feel(source)
    except FeelParseError as exc:
        errors.append(f"{where}: {exc}")
        return None


def transform(model: ProcessModel) -> ExecutableProcess:
    """Validate and lower a ProcessModel. Raises ProcessValidationError with
    every problem found (not just the first — reference validator behavior)."""
    errors: list[str] = []
    if not model.process_id:
        errors.append("process has no id")

    # index assignment: process root = 0, then elements in model order
    elements: list[ExecutableElement] = [
        ExecutableElement(0, model.process_id, BpmnElementType.PROCESS)
    ]
    by_id: dict[str, int] = {model.process_id: 0}
    for el in model.elements.values():
        if el.id in by_id:
            errors.append(f"duplicate element id {el.id!r}")
            continue
        idx = len(elements)
        by_id[el.id] = idx
        elements.append(ExecutableElement(idx, el.id, el.element_type))

    flows: list[ExecutableFlow] = []
    for flow in model.flows.values():
        src = by_id.get(flow.source_id)
        tgt = by_id.get(flow.target_id)
        if src is None or tgt is None:
            errors.append(f"flow {flow.id!r} references unknown element")
            continue
        fidx = len(flows)
        cond = _parse_condition(flow.condition, errors, f"flow {flow.id!r}") if flow.condition else None
        flows.append(ExecutableFlow(fidx, flow.id, src, tgt, cond))
        elements[src].outgoing.append(fidx)
        elements[tgt].incoming_count += 1

    for el in model.elements.values():
        exe = elements[by_id[el.id]]
        _lower_element(el, exe, model, by_id, elements, flows, errors)

    _validate(model, elements, flows, by_id, errors)

    if errors:
        raise ProcessValidationError("; ".join(errors))

    digest = hashlib.sha256(
        repr([(e.id, e.element_type, e.outgoing) for e in elements]).encode()
        + repr([(f.id, f.source_idx, f.target_idx, f.condition and f.condition.source) for f in flows]).encode()
    ).hexdigest()
    return ExecutableProcess(model.process_id, elements, flows, by_id, digest)


def _lower_element(
    el: ProcessElement,
    exe: ExecutableElement,
    model: ProcessModel,
    by_id: dict[str, int],
    elements: list[ExecutableElement],
    flows: list[ExecutableFlow],
    errors: list[str],
) -> None:
    where = f"element {el.id!r}"
    exe.event_type = el.event_type
    exe.interrupting = el.interrupting
    exe.error_code = el.error_code
    exe.signal_name = el.signal_name
    exe.escalation_code = el.escalation_code
    exe.task_headers = dict(el.task_headers)
    exe.called_process_id = el.called_process_id
    exe.called_decision_id = el.called_decision_id
    exe.link_name = el.link_name
    exe.native_user_task = el.native_user_task
    exe.form_id = el.form_id
    exe.user_task_assignee = el.user_task_assignee
    exe.user_task_candidate_groups = el.user_task_candidate_groups
    exe.decision_result_variable = el.decision_result_variable
    exe.script_result_variable = el.script_result_variable
    if el.parent_id is not None:
        parent_idx = by_id.get(el.parent_id)
        if parent_idx is None:
            errors.append(f"{where}: unknown parent scope {el.parent_id!r}")
        else:
            exe.parent_idx = parent_idx
    else:
        exe.parent_idx = 0
    if el.job_type is not None:
        exe.job_type = _parse(el.job_type, errors, where)
        exe.job_retries = _parse(el.job_retries, errors, where)
    if el.script_expression is not None:
        exe.script_expression = _parse(
            el.script_expression if el.script_expression.startswith("=") else "=" + el.script_expression,
            errors, where,
        )
    if el.timer is not None:
        exe.timer_duration = _parse(el.timer.duration, errors, where)
        exe.timer_cycle = _parse(el.timer.cycle, errors, where)
        exe.timer_date = _parse(el.timer.date, errors, where)
    if el.message is not None:
        exe.message_name = el.message.name
        if el.message.correlation_key is not None:
            key = el.message.correlation_key
            exe.correlation_key = _parse(
                key if key.startswith("=") else "=" + key, errors, where
            )
    if el.default_flow_id is not None:
        for f in flows:
            if f.id == el.default_flow_id and f.source_idx == exe.idx:
                exe.default_flow_idx = f.idx
                break
        else:
            errors.append(f"{where}: default flow {el.default_flow_id!r} not an outgoing flow")
    if el.attached_to_id is not None:
        host_idx = by_id.get(el.attached_to_id)
        if host_idx is None:
            errors.append(f"{where}: boundary attached to unknown element {el.attached_to_id!r}")
        else:
            exe.attached_to_idx = host_idx
            elements[host_idx].boundary_idxs.append(exe.idx)
    for m in el.inputs:
        src = _parse(m.source if m.source.startswith("=") else "=" + m.source, errors, where)
        if src is not None:
            exe.inputs.append((src, m.target))
    for m in el.outputs:
        src = _parse(m.source if m.source.startswith("=") else "=" + m.source, errors, where)
        if src is not None:
            exe.outputs.append((src, m.target))
    if el.multi_instance is not None:
        mi = el.multi_instance
        col = mi.input_collection
        col_expr = _parse(col if col.startswith("=") else "=" + col, errors, where)
        out_el_expr = None
        if mi.output_element is not None:
            oe = mi.output_element
            out_el_expr = _parse(oe if oe.startswith("=") else "=" + oe, errors, where)
        if col_expr is not None:
            exe.multi_instance = ExecutableMultiInstance(
                col_expr, mi.input_element, mi.output_collection, out_el_expr, mi.is_sequential
            )


def _validate(
    model: ProcessModel,
    elements: list[ExecutableElement],
    flows: list[ExecutableFlow],
    by_id: dict[str, int],
    errors: list[str],
) -> None:
    # none start events per scope
    scope_starts: dict[int, list[int]] = {}
    for exe in elements[1:]:
        if exe.element_type == BpmnElementType.START_EVENT and exe.event_type == BpmnEventType.NONE:
            scope_starts.setdefault(exe.parent_idx, []).append(exe.idx)
    root_starts = scope_starts.get(0, [])
    has_msg_or_timer_start = any(
        e.element_type == BpmnElementType.START_EVENT
        and e.parent_idx == 0
        and e.event_type in (BpmnEventType.TIMER, BpmnEventType.MESSAGE, BpmnEventType.SIGNAL)
        for e in elements[1:]
    )
    if len(root_starts) == 0 and not has_msg_or_timer_start:
        errors.append("process has no start event")
    if len(root_starts) > 1:
        errors.append("process has multiple none start events")
    if root_starts:
        elements[0].child_start_idx = root_starts[0]
    for exe in elements[1:]:
        if exe.element_type == BpmnElementType.SUB_PROCESS:
            starts = scope_starts.get(exe.idx, [])
            if len(starts) != 1:
                errors.append(f"sub-process {exe.id!r} needs exactly one none start event")
            else:
                exe.child_start_idx = starts[0]
        elif exe.element_type == BpmnElementType.EVENT_SUB_PROCESS:
            # exactly one TYPED start event (reference: EventSubProcess
            # validators — timer/message/error/signal/escalation starts)
            starts = [
                e.idx
                for e in elements[1:]
                if e.element_type == BpmnElementType.START_EVENT and e.parent_idx == exe.idx
            ]
            if len(starts) != 1:
                errors.append(
                    f"event sub-process {exe.id!r} needs exactly one start event"
                )
                continue
            start = elements[starts[0]]
            if start.event_type not in (
                BpmnEventType.TIMER,
                BpmnEventType.MESSAGE,
                BpmnEventType.ERROR,
                BpmnEventType.SIGNAL,
                BpmnEventType.ESCALATION,
            ):
                errors.append(
                    f"event sub-process {exe.id!r} start event must be typed "
                    "(timer/message/error/signal/escalation)"
                )
            if start.event_type == BpmnEventType.ERROR and not start.interrupting:
                errors.append(
                    f"error event sub-process {exe.id!r} must be interrupting"
                )
            if start.event_type == BpmnEventType.MESSAGE and start.correlation_key is None:
                errors.append(
                    f"event sub-process {exe.id!r} message start needs a correlation key"
                )
            if exe.incoming_count > 0 or exe.outgoing:
                errors.append(
                    f"event sub-process {exe.id!r} must not have sequence flows"
                )
            exe.child_start_idx = starts[0]
            exe.event_type = start.event_type
            exe.interrupting = start.interrupting

    # link events: every throw routes to THE same-scope catch with its name
    # (reference: bpmn-model/…/validation/zeebe/LinkEventValidator — catch
    # names unique per scope, each throw has exactly one matching catch;
    # engine/…/bpmn/event/IntermediateThrowEventProcessor.java:201-208)
    catch_links: dict[tuple[int, str], list[int]] = {}
    for exe in elements[1:]:
        if (
            exe.element_type == BpmnElementType.INTERMEDIATE_CATCH_EVENT
            and exe.event_type == BpmnEventType.LINK
        ):
            if not exe.link_name:
                errors.append(f"element {exe.id!r}: link event needs a name")
                continue
            catch_links.setdefault((exe.parent_idx, exe.link_name), []).append(exe.idx)
    for (scope_idx, name), idxs in catch_links.items():
        if len(idxs) > 1:
            errors.append(
                f"multiple catch link events named {name!r} in scope "
                f"{elements[scope_idx].id!r}"
            )
    for exe in elements[1:]:
        if (
            exe.element_type == BpmnElementType.INTERMEDIATE_THROW_EVENT
            and exe.event_type == BpmnEventType.LINK
        ):
            where = f"element {exe.id!r}"
            if not exe.link_name:
                errors.append(f"{where}: link event needs a name")
                continue
            if exe.outgoing:
                errors.append(f"{where}: link throw event cannot have outgoing flows")
            targets = catch_links.get((exe.parent_idx, exe.link_name), [])
            if not targets:
                errors.append(
                    f"{where}: no catch link event named {exe.link_name!r} in its scope"
                )
            else:
                exe.link_target_idx = targets[0]

    for exe in elements[1:]:
        where = f"element {exe.id!r}"
        et = exe.element_type
        if et == BpmnElementType.START_EVENT and exe.incoming_count > 0:
            errors.append(f"{where}: start event cannot have incoming flows")
        if et == BpmnElementType.END_EVENT and exe.outgoing:
            errors.append(f"{where}: end event cannot have outgoing flows")
        if et in (BpmnElementType.SERVICE_TASK, BpmnElementType.SEND_TASK) and exe.job_type is None:
            errors.append(f"{where}: missing zeebe:taskDefinition job type")
        if (
            et in (BpmnElementType.EXCLUSIVE_GATEWAY, BpmnElementType.INCLUSIVE_GATEWAY)
            and len(exe.outgoing) > 1
        ):
            for fidx in exe.outgoing:
                f = flows[fidx]
                if f.condition is None and fidx != exe.default_flow_idx:
                    errors.append(
                        f"{where}: outgoing flow {f.id!r} needs a condition (or default)"
                    )
        if et == BpmnElementType.INCLUSIVE_GATEWAY and exe.incoming_count > 1:
            # fork-only in the reference version (bpmn-model/…/validation/zeebe/
            # InclusiveGatewayValidator.java:41-45)
            errors.append(
                f"{where}: currently the inclusive gateway can only have one incoming sequence flow"
            )
        if et == BpmnElementType.EVENT_BASED_GATEWAY:
            # reference: bpmn-model/…/validation/zeebe/EventBasedGatewayValidator.java:55-65
            if len(exe.outgoing) < 2:
                errors.append(
                    f"{where}: event-based gateway must have at least 2 outgoing sequence flows"
                )
            for fidx in exe.outgoing:
                target = elements[flows[fidx].target_idx]
                if target.element_type != BpmnElementType.INTERMEDIATE_CATCH_EVENT or (
                    target.event_type
                    not in (BpmnEventType.TIMER, BpmnEventType.MESSAGE, BpmnEventType.SIGNAL)
                ):
                    errors.append(
                        f"{where}: event-based gateway must not have an outgoing sequence flow "
                        "to other elements than message/timer/signal intermediate catch events"
                    )
                elif any(
                    elements[f.source_idx].element_type != BpmnElementType.EVENT_BASED_GATEWAY
                    for f in flows
                    if f.target_idx == target.idx
                ):
                    # a triggered catch event activates without its sequence
                    # flow being taken; mixing in normal incoming flows would
                    # make token accounting ambiguous (the engine's applier
                    # derives the no-token-consumed rule from this shape)
                    errors.append(
                        f"{where}: catch event {target.id!r} after an event-based gateway "
                        "must not have other incoming sequence flows"
                    )
        if (
            exe.message_name is not None
            and exe.correlation_key is None
            and et in (
                BpmnElementType.INTERMEDIATE_CATCH_EVENT,
                BpmnElementType.RECEIVE_TASK,
                BpmnElementType.BOUNDARY_EVENT,
            )
        ):
            errors.append(f"{where}: message catch needs a correlation key")
        if et == BpmnElementType.BOUNDARY_EVENT and exe.attached_to_idx < 0:
            errors.append(f"{where}: boundary event not attached")
        if et == BpmnElementType.CALL_ACTIVITY and not exe.called_process_id:
            errors.append(f"{where}: call activity needs a called process id")
        # reachability-lite: non-start, non-boundary elements need an incoming flow
        if (
            exe.incoming_count == 0
            and et not in (
                BpmnElementType.START_EVENT,
                BpmnElementType.BOUNDARY_EVENT,
                BpmnElementType.EVENT_SUB_PROCESS,
            )
            # catch link events are entered via the matching throw, not a flow
            and not (
                et == BpmnElementType.INTERMEDIATE_CATCH_EVENT
                and exe.event_type == BpmnEventType.LINK
            )
        ):
            errors.append(f"{where}: unreachable (no incoming sequence flow)")
