"""Backup store: where completed backups live.

Reference: backup/src/main/java/io/camunda/zeebe/backup/api/BackupStore.java —
save / getStatus / list / delete / restore over BackupIdentifier
(checkpointId, partitionId, nodeId) with status DOES_NOT_EXIST / IN_PROGRESS /
COMPLETED / FAILED; S3 (backup-stores/s3) and GCS (backup-stores/gcs) remote
implementations. This module provides the filesystem implementation (object
layout mirrors the S3 key scheme ``<prefix>/<partitionId>/<checkpointId>/``)
— a remote store is the same interface over a blob client.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import shutil
from pathlib import Path

from zeebe_tpu.utils import storage_io


class BackupStatusCode(enum.Enum):
    DOES_NOT_EXIST = "DOES_NOT_EXIST"
    IN_PROGRESS = "IN_PROGRESS"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"


@dataclasses.dataclass
class BackupStatus:
    checkpoint_id: int
    partition_id: int
    status: BackupStatusCode
    descriptor: dict = dataclasses.field(default_factory=dict)
    failure_reason: str = ""


@dataclasses.dataclass
class Backup:
    """One partition's contribution to a checkpoint backup."""

    checkpoint_id: int
    partition_id: int
    node_id: str
    checkpoint_position: int
    descriptor: dict
    # name → bytes: the state snapshot files and log segment files
    snapshot_files: dict[str, bytes]
    segment_files: dict[str, bytes]


class FileSystemBackupStore:
    def __init__(self, directory: str | Path) -> None:
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)

    def _backup_dir(self, partition_id: int, checkpoint_id: int) -> Path:
        return self.directory / str(partition_id) / str(checkpoint_id)

    def save(self, backup: Backup) -> BackupStatus:
        target = self._backup_dir(backup.partition_id, backup.checkpoint_id)
        if target.exists():
            shutil.rmtree(target)
        in_progress = target.with_suffix(".tmp")
        if in_progress.exists():
            shutil.rmtree(in_progress)
        (in_progress / "snapshot").mkdir(parents=True)
        (in_progress / "segments").mkdir(parents=True)
        for name, data in backup.snapshot_files.items():
            storage_io.write_bytes(in_progress / "snapshot" / name, data)
        for name, data in backup.segment_files.items():
            storage_io.write_bytes(in_progress / "segments" / name, data)
        manifest = {
            "checkpointId": backup.checkpoint_id,
            "partitionId": backup.partition_id,
            "nodeId": backup.node_id,
            "checkpointPosition": backup.checkpoint_position,
            "descriptor": backup.descriptor,
            "snapshotFiles": sorted(backup.snapshot_files),
            "segmentFiles": sorted(backup.segment_files),
        }
        storage_io.write_text(in_progress / "manifest.json",
                              json.dumps(manifest, indent=2))
        storage_io.replace(in_progress, target)  # atomic publish ("COMPLETED")
        return self.get_status(backup.checkpoint_id, backup.partition_id)

    def get_status(self, checkpoint_id: int, partition_id: int) -> BackupStatus:
        target = self._backup_dir(partition_id, checkpoint_id)
        if target.with_suffix(".tmp").exists():
            return BackupStatus(checkpoint_id, partition_id,
                                BackupStatusCode.IN_PROGRESS)
        manifest_path = target / "manifest.json"
        if not manifest_path.exists():
            return BackupStatus(checkpoint_id, partition_id,
                                BackupStatusCode.DOES_NOT_EXIST)
        try:
            manifest = json.loads(manifest_path.read_text())
        except json.JSONDecodeError as exc:
            return BackupStatus(checkpoint_id, partition_id,
                                BackupStatusCode.FAILED,
                                failure_reason=f"corrupt manifest: {exc}")
        return BackupStatus(checkpoint_id, partition_id,
                            BackupStatusCode.COMPLETED, descriptor=manifest)

    def list_backups(self, partition_id: int | None = None) -> list[BackupStatus]:
        out = []
        partitions = (
            [self.directory / str(partition_id)] if partition_id is not None
            else sorted((p for p in self.directory.iterdir() if p.is_dir()),
                        key=lambda p: int(p.name))
        )
        for pdir in partitions:
            if not pdir.exists():
                continue
            for cdir in sorted(pdir.iterdir(),
                               key=lambda p: int(p.name.removesuffix(".tmp"))):
                if cdir.is_dir() and not cdir.name.endswith(".tmp"):
                    out.append(self.get_status(int(cdir.name), int(pdir.name)))
        return out

    def delete(self, checkpoint_id: int, partition_id: int) -> None:
        target = self._backup_dir(partition_id, checkpoint_id)
        if target.exists():
            shutil.rmtree(target)

    def read(self, checkpoint_id: int, partition_id: int) -> Backup:
        target = self._backup_dir(partition_id, checkpoint_id)
        manifest = json.loads((target / "manifest.json").read_text())
        return Backup(
            checkpoint_id=manifest["checkpointId"],
            partition_id=manifest["partitionId"],
            node_id=manifest["nodeId"],
            checkpoint_position=manifest["checkpointPosition"],
            descriptor=manifest["descriptor"],
            snapshot_files={
                name: (target / "snapshot" / name).read_bytes()
                for name in manifest["snapshotFiles"]
            },
            segment_files={
                name: (target / "segments" / name).read_bytes()
                for name in manifest["segmentFiles"]
            },
        )
