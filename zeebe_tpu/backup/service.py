"""Backup + restore services over a partition's files.

Reference: backup/src/main/java/io/camunda/zeebe/backup/management/
BackupServiceImpl (snapshot + segment files → BackupStore, reserving the
snapshot during the copy) and restore/…/PartitionRestoreService.java:36
(download backup, reconstitute the partition data directories so a broker
boots from them).
"""

from __future__ import annotations

from pathlib import Path

from zeebe_tpu.backup.store import Backup, BackupStatus, FileSystemBackupStore


from zeebe_tpu.utils.metrics import REGISTRY as _REG

_M_BACKUP_TOTAL = _REG.counter(
    "backup_operations_total", "backup operations by outcome",
    ("operation", "outcome"))
_M_BACKUP_LATENCY = _REG.histogram(
    "backup_operations_latency", "seconds per backup operation",
    ("operation",))
_M_BACKUP_IN_PROGRESS = _REG.gauge(
    "backup_operations_in_progress", "backup operations running").labels()


class BackupService:
    """Takes one partition's backup at a checkpoint."""

    def __init__(self, store: FileSystemBackupStore, node_id: str) -> None:
        self.store = store
        self.node_id = node_id

    def take_backup(self, partition, checkpoint_id: int,
                    checkpoint_position: int) -> BackupStatus:
        import time as _time

        start = _time.perf_counter()
        _M_BACKUP_IN_PROGRESS.inc()
        try:
            status = self._take_backup(partition, checkpoint_id,
                                       checkpoint_position)
            _M_BACKUP_TOTAL.labels("take", "completed").inc()
            return status
        except Exception:
            _M_BACKUP_TOTAL.labels("take", "failed").inc()
            raise
        finally:
            _M_BACKUP_IN_PROGRESS.dec()
            _M_BACKUP_LATENCY.labels("take").observe(_time.perf_counter() - start)

    def _take_backup(self, partition, checkpoint_id: int,
                     checkpoint_position: int) -> BackupStatus:
        """Backup = current persisted snapshot + the stream journal suffix
        (events after the snapshot up to the checkpoint). The partition keeps
        processing — the checkpoint record already fixed the logical cut."""
        # force_full: a backup must be self-contained — a delta tip would
        # reference a base snapshot that exists only in the live data dir
        partition.take_snapshot(force_full=True)
        snapshot_files = {}
        descriptor = {"snapshotId": None}
        chain = partition.snapshot_store.latest_valid_chain()
        if chain is not None:
            tip = chain[-1]
            descriptor["snapshotId"] = str(tip.id)
            if len(chain) == 1:
                snapshot_files = {p.name: p.read_bytes() for p in tip.files()}
            else:
                # the force_full above declined (nothing newer to snapshot)
                # and the tip is still a delta: materialize base+deltas into
                # one self-contained snapshot, manifest recomputed to match
                from zeebe_tpu.state.snapshot import (
                    STATE_FILE,
                    load_chain_db,
                    manifest_bytes,
                )

                snapshot_files = {
                    STATE_FILE: load_chain_db(chain).to_snapshot_bytes(),
                    "meta.bin": tip.read_file("meta.bin"),
                }
                snapshot_files["CHECKSUM.sfv"] = manifest_bytes(snapshot_files)
        partition.stream_journal.flush()
        segment_files = {
            p.name: p.read_bytes()
            for p in sorted(partition.stream_journal.dir.iterdir())
            if p.is_file()
        }
        backup = Backup(
            checkpoint_id=checkpoint_id,
            partition_id=partition.partition_id,
            node_id=self.node_id,
            checkpoint_position=checkpoint_position,
            descriptor=descriptor,
            snapshot_files=snapshot_files,
            segment_files=segment_files,
        )
        return self.store.save(backup)


class PartitionRestoreService:
    """Reconstitute a partition data directory from a backup; a broker started
    over the directory recovers via the normal snapshot+replay path."""

    def __init__(self, store: FileSystemBackupStore) -> None:
        self.store = store

    def restore(self, checkpoint_id: int, partition_id: int,
                target_directory: str | Path) -> None:
        backup = self.store.read(checkpoint_id, partition_id)
        target = Path(target_directory)
        stream_dir = target / "stream"
        snapshot_dir = target / "snapshots" / "snapshots"
        stream_dir.mkdir(parents=True, exist_ok=True)
        for name, data in backup.segment_files.items():
            (stream_dir / name).write_bytes(data)
        # cut the restored log at the checkpoint: records appended after the
        # CHECKPOINT command (the backup raced ongoing processing) would move
        # the logical cut point and break cross-partition consistency
        from zeebe_tpu.journal import SegmentedJournal

        journal = SegmentedJournal(stream_dir)
        try:
            cut_index = journal.seek_to_asqn(backup.checkpoint_position)
            if cut_index > 0:
                journal.truncate_after(cut_index)
        finally:
            journal.close()
        snapshot_id = backup.descriptor.get("snapshotId")
        if snapshot_id and backup.snapshot_files:
            snap_target = snapshot_dir / snapshot_id
            snap_target.mkdir(parents=True, exist_ok=True)
            for name, data in backup.snapshot_files.items():
                (snap_target / name).write_bytes(data)
