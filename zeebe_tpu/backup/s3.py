"""S3 backup store: the BackupStore interface over the S3 REST API.

Reference: backup-stores/s3/src/main/java/io/camunda/zeebe/backup/s3/
S3BackupStore.java — objects under ``<basePath>/<partitionId>/<checkpointId>/``
(manifest + named contents), manifest written last so its presence is the
COMPLETED marker. The reference uses the AWS SDK; this build has zero
third-party dependencies, so the client below speaks the REST API directly
over stdlib ``http.client`` with AWS Signature Version 4 request signing
(path-style addressing — works against AWS, MinIO, localstack).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import json
import urllib.parse
import xml.etree.ElementTree as ET

from zeebe_tpu.backup.store import Backup, BackupStatus, BackupStatusCode

_ALGO = "AWS4-HMAC-SHA256"


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


def sign_v4(method: str, host: str, path: str, query: dict[str, str],
            headers: dict[str, str], payload_hash: str, region: str,
            service: str, access_key: str, secret_key: str,
            amz_date: str) -> str:
    """AWS Signature Version 4: returns the Authorization header value.
    Split out (and pure) so the canonicalization is unit-testable against
    AWS's published test vectors."""
    date_stamp = amz_date[:8]
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in sorted(query.items())
    )
    all_headers = {**{k.lower(): v.strip() for k, v in headers.items()},
                   "host": host}
    signed_headers = ";".join(sorted(all_headers))
    canonical_headers = "".join(
        f"{k}:{all_headers[k]}\n" for k in sorted(all_headers)
    )
    canonical_request = "\n".join([
        method, urllib.parse.quote(path), canonical_query,
        canonical_headers, signed_headers, payload_hash,
    ])
    scope = f"{date_stamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        _ALGO, amz_date, scope,
        hashlib.sha256(canonical_request.encode("utf-8")).hexdigest(),
    ])
    k_date = _hmac(("AWS4" + secret_key).encode("utf-8"), date_stamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode("utf-8"),
                         hashlib.sha256).hexdigest()
    return (f"{_ALGO} Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}")


class S3Error(Exception):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"S3 request failed: HTTP {status}: {body[:500]}")
        self.status = status


class PersistentHttpClient:
    """Shared blob-client transport: endpoint parsing, one persistent
    connection (a backup save uploads many objects to the same endpoint and
    must not pay a handshake per file), reconnect-once on a stale
    keep-alive."""

    def __init__(self, endpoint: str, timeout_s: float = 30.0) -> None:
        parsed = urllib.parse.urlparse(endpoint)
        if parsed.scheme not in ("http", "https"):
            raise ValueError(f"endpoint must be http(s)://…, got {endpoint!r}")
        self._secure = parsed.scheme == "https"
        self._host = parsed.netloc
        self.timeout_s = timeout_s
        self._conn: http.client.HTTPConnection | None = None

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            conn_cls = (http.client.HTTPSConnection if self._secure
                        else http.client.HTTPConnection)
            self._conn = conn_cls(self._host, timeout=self.timeout_s)
        return self._conn

    def _send(self, method: str, target: str, body: bytes,
              headers: dict[str, str]) -> tuple[int, bytes]:
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request(method, target, body=body, headers=headers)
                response = conn.getresponse()
                return response.status, response.read()
            except (http.client.HTTPException, OSError):
                self._conn = None  # stale keep-alive: reconnect once
                if attempt:
                    raise
        raise AssertionError("unreachable")


class S3Client(PersistentHttpClient):
    """Minimal path-style S3 client: put/get/delete/list with SigV4."""

    def __init__(self, endpoint: str, bucket: str, access_key: str,
                 secret_key: str, region: str = "us-east-1",
                 timeout_s: float = 30.0) -> None:
        super().__init__(endpoint, timeout_s)
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def _request(self, method: str, key: str = "",
                 query: dict[str, str] | None = None,
                 body: bytes = b"") -> tuple[int, bytes]:
        query = query or {}
        path = f"/{self.bucket}" + (f"/{key}" if key else "")
        payload_hash = hashlib.sha256(body).hexdigest()
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y%m%dT%H%M%SZ")
        headers = {
            "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash,
        }
        headers["Authorization"] = sign_v4(
            method, self._host, path, query, headers, payload_hash,
            self.region, "s3", self.access_key, self.secret_key, amz_date,
        )
        target = urllib.parse.quote(path)
        if query:
            # EXACTLY the canonical encoding sign_v4 used (quote, not
            # urlencode/quote_plus): a space must be %20 on the wire too, or
            # the signature covers a different string than the request
            target += "?" + "&".join(
                f"{urllib.parse.quote(k, safe='')}="
                f"{urllib.parse.quote(v, safe='')}"
                for k, v in sorted(query.items())
            )
        return self._send(method, target, body, headers)

    def put_object(self, key: str, data: bytes) -> None:
        status, body = self._request("PUT", key, body=data)
        if status not in (200, 201):
            raise S3Error(status, body.decode("utf-8", "replace"))

    def get_object(self, key: str) -> bytes | None:
        status, body = self._request("GET", key)
        if status == 404:
            return None
        if status != 200:
            raise S3Error(status, body.decode("utf-8", "replace"))
        return body

    def delete_object(self, key: str) -> None:
        status, body = self._request("DELETE", key)
        if status not in (200, 204, 404):
            raise S3Error(status, body.decode("utf-8", "replace"))

    def list_keys(self, prefix: str) -> list[str]:
        """ListObjectsV2 with continuation (reference: the SDK paginates)."""
        keys: list[str] = []
        token = ""
        while True:
            query = {"list-type": "2", "prefix": prefix}
            if token:
                query["continuation-token"] = token
            status, body = self._request("GET", query=query)
            if status != 200:
                raise S3Error(status, body.decode("utf-8", "replace"))
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            for contents in root.findall(f"{ns}Contents"):
                key = contents.find(f"{ns}Key")
                if key is not None and key.text:
                    keys.append(key.text)
            next_token = root.find(f"{ns}NextContinuationToken")
            if next_token is None or not next_token.text:
                return keys
            token = next_token.text


class BlobBackupStore:
    """BackupStore over any blob client exposing put_object/get_object/
    delete_object/list_keys; same manifest-last COMPLETED semantics as the
    filesystem store (and the reference's S3/GCS implementations)."""

    def __init__(self, client, base_path: str = "backups") -> None:
        self.client = client
        self.base_path = base_path.strip("/")

    def _prefix(self, partition_id: int, checkpoint_id: int) -> str:
        return f"{self.base_path}/{partition_id}/{checkpoint_id}"

    def save(self, backup: Backup) -> BackupStatus:
        prefix = self._prefix(backup.partition_id, backup.checkpoint_id)
        for name, data in backup.snapshot_files.items():
            self.client.put_object(f"{prefix}/snapshot/{name}", data)
        for name, data in backup.segment_files.items():
            self.client.put_object(f"{prefix}/segments/{name}", data)
        manifest = {
            "checkpointId": backup.checkpoint_id,
            "partitionId": backup.partition_id,
            "nodeId": backup.node_id,
            "checkpointPosition": backup.checkpoint_position,
            "descriptor": backup.descriptor,
            "snapshotFiles": sorted(backup.snapshot_files),
            "segmentFiles": sorted(backup.segment_files),
        }
        # manifest LAST: its presence is the COMPLETED marker
        self.client.put_object(
            f"{prefix}/manifest.json", json.dumps(manifest).encode("utf-8"))
        return self.get_status(backup.checkpoint_id, backup.partition_id)

    def get_status(self, checkpoint_id: int, partition_id: int) -> BackupStatus:
        prefix = self._prefix(partition_id, checkpoint_id)
        manifest_bytes = self.client.get_object(f"{prefix}/manifest.json")
        if manifest_bytes is None:
            if self.client.list_keys(prefix + "/"):
                return BackupStatus(checkpoint_id, partition_id,
                                    BackupStatusCode.IN_PROGRESS)
            return BackupStatus(checkpoint_id, partition_id,
                                BackupStatusCode.DOES_NOT_EXIST)
        try:
            manifest = json.loads(manifest_bytes)
        except json.JSONDecodeError as exc:
            return BackupStatus(checkpoint_id, partition_id,
                                BackupStatusCode.FAILED,
                                failure_reason=f"corrupt manifest: {exc}")
        return BackupStatus(checkpoint_id, partition_id,
                            BackupStatusCode.COMPLETED, descriptor=manifest)

    def list_backups(self, partition_id: int | None = None) -> list[BackupStatus]:
        prefix = self.base_path + "/"
        if partition_id is not None:
            prefix += f"{partition_id}/"
        out = []
        for key in self.client.list_keys(prefix):
            if not key.endswith("/manifest.json"):
                continue
            parts = key[len(self.base_path) + 1:].split("/")
            out.append(self.get_status(int(parts[1]), int(parts[0])))
        out.sort(key=lambda s: (s.partition_id, s.checkpoint_id))
        return out

    def delete(self, checkpoint_id: int, partition_id: int) -> None:
        prefix = self._prefix(partition_id, checkpoint_id)
        # manifest FIRST: a half-deleted backup must read as not-completed
        self.client.delete_object(f"{prefix}/manifest.json")
        for key in self.client.list_keys(prefix + "/"):
            self.client.delete_object(key)

    def read(self, checkpoint_id: int, partition_id: int) -> Backup:
        prefix = self._prefix(partition_id, checkpoint_id)
        manifest_bytes = self.client.get_object(f"{prefix}/manifest.json")
        if manifest_bytes is None:
            raise FileNotFoundError(
                f"backup {checkpoint_id} for partition {partition_id} does not "
                f"exist (no {prefix}/manifest.json)"
            )
        manifest = json.loads(manifest_bytes)

        def require(key: str) -> bytes:
            data = self.client.get_object(key)
            if data is None:
                # manifest-last save order makes this impossible for an
                # intact store: a listed object vanished after completion
                raise FileNotFoundError(
                    f"backup {checkpoint_id}/{partition_id} is corrupt: "
                    f"object {key} listed in the manifest is missing"
                )
            return data

        return Backup(
            checkpoint_id=manifest["checkpointId"],
            partition_id=manifest["partitionId"],
            node_id=manifest["nodeId"],
            checkpoint_position=manifest["checkpointPosition"],
            descriptor=manifest["descriptor"],
            snapshot_files={
                name: require(f"{prefix}/snapshot/{name}")
                for name in manifest["snapshotFiles"]
            },
            segment_files={
                name: require(f"{prefix}/segments/{name}")
                for name in manifest["segmentFiles"]
            },
        )


class S3BackupStore(BlobBackupStore):
    """BackupStore over an S3Client (reference: backup-stores/s3)."""

    def __init__(self, client: S3Client, base_path: str = "backups") -> None:
        super().__init__(client, base_path)
