"""Backup/restore: cluster-consistent checkpoints shipped to a backup store.

Reference: backup/ + backup-stores/{s3,gcs} + restore/ (SURVEY §2.12, §5.4) —
CheckpointRecordsProcessor.java:34 (CHECKPOINT records interleaved on the
stream; inter-partition commands carry checkpoint ids so a cluster-wide
consistent checkpoint forms without stopping processing), BackupServiceImpl
(snapshot + segments → BackupStore), PartitionRestoreService.java:36.
"""

from zeebe_tpu.backup.checkpoint import CheckpointProcessor, CheckpointState
from zeebe_tpu.backup.store import Backup, BackupStatus, FileSystemBackupStore
from zeebe_tpu.backup.service import BackupService, PartitionRestoreService

__all__ = [
    "Backup",
    "BackupService",
    "BackupStatus",
    "CheckpointProcessor",
    "CheckpointState",
    "FileSystemBackupStore",
    "PartitionRestoreService",
]
