"""Backup/restore: cluster-consistent checkpoints shipped to a backup store.

Reference: backup/ + backup-stores/{s3,gcs} + restore/ (SURVEY §2.12, §5.4) —
CheckpointRecordsProcessor.java:34 (CHECKPOINT records interleaved on the
stream; inter-partition commands carry checkpoint ids so a cluster-wide
consistent checkpoint forms without stopping processing), BackupServiceImpl
(snapshot + segments → BackupStore), PartitionRestoreService.java:36.
"""

import os

from zeebe_tpu.backup.checkpoint import CheckpointProcessor, CheckpointState
from zeebe_tpu.backup.gcs import GcsBackupStore, GcsClient
from zeebe_tpu.backup.s3 import S3BackupStore, S3Client
from zeebe_tpu.backup.store import Backup, BackupStatus, FileSystemBackupStore
from zeebe_tpu.backup.service import BackupService, PartitionRestoreService

def backup_store_from_env(env: dict | None = None):
    """Construct a backup store from ``ZEEBE_BROKER_DATA_BACKUP_*`` env vars
    (reference: broker data.backup config — store selection NONE/S3/GCS with
    per-store sub-sections). Returns None when no remote store is configured.

    S3:  ZEEBE_BROKER_DATA_BACKUP_STORE=S3 + _S3_ENDPOINT, _S3_BUCKETNAME,
         _S3_ACCESSKEY, _S3_SECRETKEY [, _S3_REGION, _S3_BASEPATH]
    GCS: ZEEBE_BROKER_DATA_BACKUP_STORE=GCS + _GCS_BUCKETNAME
         [, _GCS_HOST, _GCS_AUTH (bearer token), _GCS_BASEPATH]
    """
    env = env if env is not None else os.environ
    prefix = "ZEEBE_BROKER_DATA_BACKUP"
    kind = env.get(f"{prefix}_STORE", "NONE").upper()
    if kind in ("", "NONE"):
        return None
    if kind == "S3":
        client = S3Client(
            endpoint=env[f"{prefix}_S3_ENDPOINT"],
            bucket=env[f"{prefix}_S3_BUCKETNAME"],
            access_key=env[f"{prefix}_S3_ACCESSKEY"],
            secret_key=env[f"{prefix}_S3_SECRETKEY"],
            region=env.get(f"{prefix}_S3_REGION", "us-east-1"),
        )
        return S3BackupStore(client, env.get(f"{prefix}_S3_BASEPATH", "backups"))
    if kind == "GCS":
        client = GcsClient(
            bucket=env[f"{prefix}_GCS_BUCKETNAME"],
            access_token=env.get(f"{prefix}_GCS_AUTH", ""),
            endpoint=env.get(f"{prefix}_GCS_HOST",
                             "https://storage.googleapis.com"),
        )
        return GcsBackupStore(client, env.get(f"{prefix}_GCS_BASEPATH", "backups"))
    raise ValueError(f"unknown backup store kind {kind!r} (NONE/S3/GCS)")


__all__ = [
    "backup_store_from_env",
    "Backup",
    "BackupService",
    "BackupStatus",
    "CheckpointProcessor",
    "CheckpointState",
    "FileSystemBackupStore",
    "GcsBackupStore",
    "GcsClient",
    "PartitionRestoreService",
    "S3BackupStore",
    "S3Client",
]
