"""Checkpoint records: the cluster-consistent cut marker.

Reference: backup/src/main/java/io/camunda/zeebe/backup/processing/
CheckpointRecordsProcessor.java:34 — a CHECKPOINT CREATE command either
creates a checkpoint (id > last: CREATED event, listeners fire → backup
starts) or is IGNORED (id <= last, at-least-once propagation dedup).
Inter-partition commands piggyback the sender's checkpoint id; the receiver
creates the checkpoint BEFORE processing the command, which is what makes the
cut consistent across partitions without pausing processing.
"""

from __future__ import annotations

from typing import Callable

from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import ValueType
from zeebe_tpu.protocol.intent import CheckpointIntent
from zeebe_tpu.state import ZbDb
from zeebe_tpu.state.db import ColumnFamilyCode as CF


class CheckpointState:
    def __init__(self, db: ZbDb) -> None:
        self._cf = db.column_family(CF.CHECKPOINT)

    def latest_id(self) -> int:
        latest = self._cf.get(("latest",))
        return latest["checkpointId"] if latest else 0

    def latest(self) -> dict | None:
        return self._cf.get(("latest",))

    def put(self, checkpoint_id: int, position: int) -> None:
        from zeebe_tpu.utils.metrics import REGISTRY

        REGISTRY.gauge("checkpoint_id", "latest checkpoint id").set(checkpoint_id)
        REGISTRY.gauge("checkpoint_position",
                       "latest checkpoint position").set(position)
        REGISTRY.counter("checkpoint_records_total",
                         "checkpoint records applied").inc()
        self._cf.put(("latest",), {"checkpointId": checkpoint_id,
                                   "position": position})


class CheckpointProcessor:
    """Handles CHECKPOINT CREATE commands + applies CREATED events."""

    def __init__(self, state: CheckpointState) -> None:
        self.state = state
        # fired post-commit with (checkpoint_id, position) on creation —
        # the broker hangs the backup trigger here
        self.listeners: list[Callable[[int, int], None]] = []

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        checkpoint_id = cmd.record.value.get("checkpointId", -1)
        if checkpoint_id <= self.state.latest_id():
            writers.append_event(
                cmd.record.key if cmd.record.key > 0 else -1,
                ValueType.CHECKPOINT, CheckpointIntent.IGNORED,
                {"checkpointId": checkpoint_id,
                 "checkpointPosition": cmd.position},
            )
            return
        writers.append_event(
            cmd.record.key if cmd.record.key > 0 else -1,
            ValueType.CHECKPOINT, CheckpointIntent.CREATED,
            {"checkpointId": checkpoint_id, "checkpointPosition": cmd.position},
        )
        position = cmd.position
        listeners = list(self.listeners)

        def notify() -> None:
            for listener in listeners:
                listener(checkpoint_id, position)

        writers.after_commit(notify)
