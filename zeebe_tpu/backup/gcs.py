"""GCS backup store: the BackupStore interface over the GCS JSON API.

Reference: backup-stores/gcs/src/main/java/io/camunda/zeebe/backup/gcs/
GcsBackupStore.java — same object layout and manifest-last semantics as the
S3 store, addressed through Google Cloud Storage's JSON API
(``/storage/v1/b/<bucket>/o`` + ``/upload/storage/v1`` media uploads) with a
bearer token. The endpoint is configurable so fake-gcs-server-style emulators
work; auth is a static token (no metadata-server round trips in this build).
"""

from __future__ import annotations

import json
import urllib.parse

from zeebe_tpu.backup.s3 import BlobBackupStore, PersistentHttpClient


class GcsError(Exception):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"GCS request failed: HTTP {status}: {body[:500]}")
        self.status = status


class GcsClient(PersistentHttpClient):
    """Minimal GCS JSON-API client: upload/download/delete/list."""

    def __init__(self, bucket: str, access_token: str = "",
                 endpoint: str = "https://storage.googleapis.com",
                 timeout_s: float = 30.0) -> None:
        super().__init__(endpoint, timeout_s)
        self.bucket = bucket
        self.access_token = access_token

    def _request(self, method: str, target: str,
                 body: bytes = b"") -> tuple[int, bytes]:
        headers = {}
        if self.access_token:
            headers["Authorization"] = f"Bearer {self.access_token}"
        return self._send(method, target, body, headers)

    def _object_path(self, key: str) -> str:
        return (f"/storage/v1/b/{self.bucket}/o/"
                f"{urllib.parse.quote(key, safe='')}")

    def put_object(self, key: str, data: bytes) -> None:
        target = (f"/upload/storage/v1/b/{self.bucket}/o?uploadType=media"
                  f"&name={urllib.parse.quote(key, safe='')}")
        status, body = self._request("POST", target, body=data)
        if status not in (200, 201):
            raise GcsError(status, body.decode("utf-8", "replace"))

    def get_object(self, key: str) -> bytes | None:
        status, body = self._request("GET", self._object_path(key) + "?alt=media")
        if status == 404:
            return None
        if status != 200:
            raise GcsError(status, body.decode("utf-8", "replace"))
        return body

    def delete_object(self, key: str) -> None:
        status, body = self._request("DELETE", self._object_path(key))
        if status not in (200, 204, 404):
            raise GcsError(status, body.decode("utf-8", "replace"))

    def list_keys(self, prefix: str) -> list[str]:
        keys: list[str] = []
        page_token = ""
        while True:
            target = (f"/storage/v1/b/{self.bucket}/o"
                      f"?prefix={urllib.parse.quote(prefix, safe='')}")
            if page_token:
                target += f"&pageToken={urllib.parse.quote(page_token, safe='')}"
            status, body = self._request("GET", target)
            if status != 200:
                raise GcsError(status, body.decode("utf-8", "replace"))
            listing = json.loads(body)
            keys.extend(item["name"] for item in listing.get("items", []))
            page_token = listing.get("nextPageToken", "")
            if not page_token:
                return keys


class GcsBackupStore(BlobBackupStore):
    """BackupStore over a GcsClient (reference: backup-stores/gcs); all the
    layout/manifest logic lives in BlobBackupStore, which only depends on the
    shared blob-client surface."""

    def __init__(self, client: GcsClient, base_path: str = "backups") -> None:
        super().__init__(client, base_path)
