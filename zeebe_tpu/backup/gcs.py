"""GCS backup store: the BackupStore interface over the GCS JSON API.

Reference: backup-stores/gcs/src/main/java/io/camunda/zeebe/backup/gcs/
GcsBackupStore.java — same object layout and manifest-last semantics as the
S3 store, addressed through Google Cloud Storage's JSON API
(``/storage/v1/b/<bucket>/o`` + ``/upload/storage/v1`` media uploads) with a
bearer token. The endpoint is configurable so fake-gcs-server-style emulators
work; auth is a static token (no metadata-server round trips in this build).
"""

from __future__ import annotations

import http.client
import json
import urllib.parse

from zeebe_tpu.backup.s3 import BlobBackupStore


class GcsError(Exception):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"GCS request failed: HTTP {status}: {body[:500]}")
        self.status = status


class GcsClient:
    """Minimal GCS JSON-API client: upload/download/delete/list."""

    def __init__(self, bucket: str, access_token: str = "",
                 endpoint: str = "https://storage.googleapis.com",
                 timeout_s: float = 30.0) -> None:
        parsed = urllib.parse.urlparse(endpoint)
        if parsed.scheme not in ("http", "https"):
            raise ValueError(f"endpoint must be http(s)://…, got {endpoint!r}")
        self._secure = parsed.scheme == "https"
        self._host = parsed.netloc
        self.bucket = bucket
        self.access_token = access_token
        self.timeout_s = timeout_s
        self._conn: http.client.HTTPConnection | None = None

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            conn_cls = (http.client.HTTPSConnection if self._secure
                        else http.client.HTTPConnection)
            self._conn = conn_cls(self._host, timeout=self.timeout_s)
        return self._conn

    def _request(self, method: str, target: str,
                 body: bytes = b"") -> tuple[int, bytes]:
        headers = {}
        if self.access_token:
            headers["Authorization"] = f"Bearer {self.access_token}"
        # persistent connection; reconnect once on a stale keep-alive
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request(method, target, body=body, headers=headers)
                response = conn.getresponse()
                return response.status, response.read()
            except (http.client.HTTPException, OSError):
                self._conn = None
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def _object_path(self, key: str) -> str:
        return (f"/storage/v1/b/{self.bucket}/o/"
                f"{urllib.parse.quote(key, safe='')}")

    def put_object(self, key: str, data: bytes) -> None:
        target = (f"/upload/storage/v1/b/{self.bucket}/o?uploadType=media"
                  f"&name={urllib.parse.quote(key, safe='')}")
        status, body = self._request("POST", target, body=data)
        if status not in (200, 201):
            raise GcsError(status, body.decode("utf-8", "replace"))

    def get_object(self, key: str) -> bytes | None:
        status, body = self._request("GET", self._object_path(key) + "?alt=media")
        if status == 404:
            return None
        if status != 200:
            raise GcsError(status, body.decode("utf-8", "replace"))
        return body

    def delete_object(self, key: str) -> None:
        status, body = self._request("DELETE", self._object_path(key))
        if status not in (200, 204, 404):
            raise GcsError(status, body.decode("utf-8", "replace"))

    def list_keys(self, prefix: str) -> list[str]:
        keys: list[str] = []
        page_token = ""
        while True:
            target = (f"/storage/v1/b/{self.bucket}/o"
                      f"?prefix={urllib.parse.quote(prefix, safe='')}")
            if page_token:
                target += f"&pageToken={urllib.parse.quote(page_token, safe='')}"
            status, body = self._request("GET", target)
            if status != 200:
                raise GcsError(status, body.decode("utf-8", "replace"))
            listing = json.loads(body)
            keys.extend(item["name"] for item in listing.get("items", []))
            page_token = listing.get("nextPageToken", "")
            if not page_token:
                return keys


class GcsBackupStore(BlobBackupStore):
    """BackupStore over a GcsClient (reference: backup-stores/gcs); all the
    layout/manifest logic lives in BlobBackupStore, which only depends on the
    shared blob-client surface."""

    def __init__(self, client: GcsClient, base_path: str = "backups") -> None:
        super().__init__(client, base_path)
