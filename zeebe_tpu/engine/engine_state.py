"""Engine state facades over the column-family store.

Reference: engine/src/main/java/io/camunda/zeebe/engine/state/ — ProcessingDbState
aggregating ProcessState, ElementInstanceState (parent/child trees +
NUMBER_OF_TAKEN_SEQUENCE_FLOWS), JobState (activatable queues, deadlines,
backoff), VariableState (scope hierarchy), TimerInstanceState, IncidentState,
MessageState, DistributionState, BannedInstanceState.

Only event appliers (appliers.py) may call the mutating methods — the
reference enforces this with ArchUnit; here the convention is enforced by the
replay≡processing property tests.

Element-instance token accounting: each scope instance tracks
``active_children`` (element instances whose flow scope is this instance) and
``active_flows`` (tokens in transit on sequence flows of this scope). A scope
can complete when both are zero. Parallel-gateway joins count taken incoming
flows per (scope, gateway) in NUMBER_OF_TAKEN_SEQUENCE_FLOWS, exactly the
reference's join bookkeeping (docs/engine_questions.md:16-46).
"""

from __future__ import annotations

from typing import Any, Iterator

from zeebe_tpu.models.bpmn import ExecutableProcess, parse_bpmn_xml, transform
from zeebe_tpu.protocol import DEFAULT_TENANT, KeyGenerator
from zeebe_tpu.state import ColumnFamilyCode as CF
from zeebe_tpu.state import ZbDb

# element-instance lifecycle states (stored as ints)
EI_ACTIVATING = 0
EI_ACTIVATED = 1
EI_COMPLETING = 2
EI_COMPLETED = 3
EI_TERMINATING = 4
EI_TERMINATED = 5

# job states
JOB_ACTIVATABLE = 0
JOB_ACTIVATED = 1
JOB_FAILED = 2
JOB_ERROR_THROWN = 3


def _rollback_latest_version(by_id_version, version_cf, digest_cf,
                             tenant: str, resource_id: str, version: int,
                             digest_of) -> None:
    """Shared delete bookkeeping for tenant-scoped versioned resources
    (processes, forms): drop the (tenant, id, version) index entry and, if it
    was the latest, repoint latest/digest to the highest remaining version."""
    if by_id_version.exists((tenant, resource_id, version)):
        by_id_version.delete((tenant, resource_id, version))
    if version_cf.get((tenant, resource_id)) == version:
        for v in range(version - 1, 0, -1):
            prev_key = by_id_version.get((tenant, resource_id, v))
            if prev_key is not None:
                version_cf.put((tenant, resource_id), v)
                digest_cf.put((tenant, resource_id), digest_of(prev_key))
                return
        version_cf.delete((tenant, resource_id))
        if digest_cf.exists((tenant, resource_id)):
            digest_cf.delete((tenant, resource_id))


class ProcessState:
    """Deployed process definitions: by key, by (tenant, id, version), latest,
    digest. The tenant is the leading component of every id-scoped index
    (reference: DbTenantAwareKey wrapping in ProcessState /
    ZbColumnFamilies PROCESS_CACHE_BY_ID_AND_VERSION), so the same BPMN
    process id deploys and versions independently per tenant.

    Caches compiled ExecutableProcess objects outside the db (they are
    deterministic functions of the stored XML)."""

    def __init__(self, db: ZbDb) -> None:
        self._by_key = db.column_family(CF.PROCESS_CACHE)
        self._by_id_version = db.column_family(CF.PROCESS_CACHE_BY_ID_AND_VERSION)
        self._digest = db.column_family(CF.PROCESS_CACHE_DIGEST_BY_ID)
        self._version = db.column_family(CF.PROCESS_VERSION)
        self._compiled: dict[int, ExecutableProcess] = {}

    # mutators (appliers only)

    def put_process(self, key: int, bpmn_process_id: str, version: int, resource_name: str,
                    resource_xml: str, digest: str,
                    tenant: str = DEFAULT_TENANT) -> None:
        meta = {
            "bpmnProcessId": bpmn_process_id,
            "version": version,
            "processDefinitionKey": key,
            "resourceName": resource_name,
            "resource": resource_xml,
            "checksum": digest,
            "tenantId": tenant,
        }
        self._by_key.put((key,), meta)
        self._by_id_version.put((tenant, bpmn_process_id, version), key)
        self._digest.put((tenant, bpmn_process_id), digest)
        self._version.put((tenant, bpmn_process_id), version)

    # queries

    def next_version(self, bpmn_process_id: str, tenant: str = DEFAULT_TENANT) -> int:
        return (self._version.get((tenant, bpmn_process_id)) or 0) + 1

    def latest_version(self, bpmn_process_id: str,
                       tenant: str = DEFAULT_TENANT) -> int | None:
        return self._version.get((tenant, bpmn_process_id))

    def latest_digest(self, bpmn_process_id: str,
                      tenant: str = DEFAULT_TENANT) -> str | None:
        return self._digest.get((tenant, bpmn_process_id))

    def get_by_key(self, key: int) -> dict | None:
        return self._by_key.get((key,))

    def get_key_by_id_version(self, bpmn_process_id: str, version: int,
                              tenant: str = DEFAULT_TENANT) -> int | None:
        return self._by_id_version.get((tenant, bpmn_process_id, version))

    def get_latest_by_id(self, bpmn_process_id: str,
                         tenant: str = DEFAULT_TENANT) -> dict | None:
        version = self.latest_version(bpmn_process_id, tenant)
        if version is None:
            return None
        key = self.get_key_by_id_version(bpmn_process_id, version, tenant)
        return None if key is None else self.get_by_key(key)

    def delete(self, key: int) -> None:
        """Resource deletion: the definition stops being startable (removed
        from the id/version indexes; previous version repointed as latest) but
        the stored resource stays so RUNNING instances keep executing
        (reference: deleted definitions serve in-flight instances)."""
        meta = self._by_key.get((key,))
        if meta is None:
            return
        process_id = meta["bpmnProcessId"]
        version = meta["version"]
        tenant = meta.get("tenantId", DEFAULT_TENANT)
        self._by_key.put((key,), {**meta, "deleted": True})
        _rollback_latest_version(
            self._by_id_version, self._version, self._digest,
            tenant, process_id, version,
            digest_of=lambda k: self._by_key.get((k,))["checksum"],
        )

    def executable(self, key: int) -> ExecutableProcess | None:
        exe = self._compiled.get(key)
        if exe is not None:
            return exe
        meta = self.get_by_key(key)
        if meta is None:
            return None
        model = next(
            m for m in parse_bpmn_xml(meta["resource"]) if m.process_id == meta["bpmnProcessId"]
        )
        exe = transform(model)
        self._compiled[key] = exe
        return exe


class ElementInstanceState:
    """Element-instance tree + token accounting + parallel-gateway counters."""

    def __init__(self, db: ZbDb) -> None:
        self._instances = db.column_family(CF.ELEMENT_INSTANCE_KEY)
        self._parent_child = db.column_family(CF.ELEMENT_INSTANCE_PARENT_CHILD)
        self._taken_flows = db.column_family(CF.NUMBER_OF_TAKEN_SEQUENCE_FLOWS)

    # mutators

    def create(self, key: int, record_value: dict, state: int) -> None:
        instance = {
            "key": key,
            "state": state,
            "value": dict(record_value),
            "activeChildren": 0,
            "activeFlows": 0,
            "jobKey": -1,
            "interruptedByKey": -1,
        }
        self._instances.put((key,), instance)
        parent = record_value.get("flowScopeKey", -1)
        if parent >= 0:
            self._parent_child.put((parent, key), None)

    def update(self, key: int, **fields: Any) -> None:
        instance = self._instances.get((key,))
        instance.update(fields)
        self._instances.put((key,), instance)

    def set_state(self, key: int, state: int) -> None:
        self.update(key, state=state)

    def remove(self, key: int) -> None:
        instance = self._instances.get((key,))
        if instance is None:
            return
        parent = instance["value"].get("flowScopeKey", -1)
        if parent >= 0:
            self._parent_child.delete((parent, key))
        self._instances.delete((key,))

    def add_child(self, scope_key: int) -> None:
        instance = self._instances.get((scope_key,))
        instance["activeChildren"] += 1
        self._instances.put((scope_key,), instance)

    def remove_child(self, scope_key: int) -> None:
        instance = self._instances.get((scope_key,))
        if instance is None:
            return  # scope already gone (terminated concurrently)
        instance["activeChildren"] -= 1
        self._instances.put((scope_key,), instance)

    def add_active_flow(self, scope_key: int) -> None:
        instance = self._instances.get((scope_key,))
        instance["activeFlows"] += 1
        self._instances.put((scope_key,), instance)

    def consume_active_flows(self, scope_key: int, count: int) -> None:
        if count <= 0:
            return
        instance = self._instances.get((scope_key,))
        if instance is None:
            return
        instance["activeFlows"] -= count
        self._instances.put((scope_key,), instance)

    def increment_taken_flow(self, scope_key: int, gateway_idx: int, flow_idx: int) -> None:
        count = self._taken_flows.get((scope_key, gateway_idx, flow_idx)) or 0
        self._taken_flows.put((scope_key, gateway_idx, flow_idx), count + 1)

    def decrement_taken_flows_for_join(self, scope_key: int, gateway_idx: int) -> None:
        """Consume one token from every incoming flow of the gateway."""
        for enc_key, count in list(self._taken_flows.items((scope_key, gateway_idx))):
            if count > 1:
                self._taken_flows._ctx().put(enc_key, count - 1)
            else:
                self._taken_flows._ctx().delete(enc_key)

    # queries

    def get(self, key: int) -> dict | None:
        return self._instances.get((key,))

    def children_keys(self, scope_key: int) -> list[int]:
        # parent_child CF key layout: u16 cf | 0x01 i64(scope) | 0x01 i64(child)
        return [_decode_trailing_i64(enc_key) for enc_key, _ in self._parent_child.items((scope_key,))]

    def taken_flow_count(self, scope_key: int, gateway_idx: int, flow_idx: int) -> int:
        return self._taken_flows.get((scope_key, gateway_idx, flow_idx)) or 0

    def taken_flows_satisfy_join(self, scope_key: int, gateway_idx: int, incoming_flow_idxs: list[int]) -> bool:
        return all(
            self.taken_flow_count(scope_key, gateway_idx, fidx) > 0 for fidx in incoming_flow_idxs
        )


class FormState:
    """Deployed Camunda forms: by key + tenant-scoped (id, version) indexes
    (reference: engine/state/deployment/DbFormState.java, PersistedForm;
    ZbColumnFamilies FORMS / FORM_BY_ID_AND_VERSION / FORM_VERSION)."""

    def __init__(self, db: ZbDb) -> None:
        self._by_key = db.column_family(CF.FORMS)
        self._by_id_version = db.column_family(CF.FORM_BY_ID_AND_VERSION)
        self._version = db.column_family(CF.FORM_VERSION)
        self._digest = db.column_family(CF.FORM_DIGEST)

    # mutators (appliers only)

    def put(self, record_value: dict) -> None:
        tenant = record_value.get("tenantId", DEFAULT_TENANT)
        form_id = record_value["formId"]
        version = record_value["version"]
        self._by_key.put((record_value["formKey"],), dict(record_value))
        self._by_id_version.put((tenant, form_id, version), record_value["formKey"])
        self._version.put((tenant, form_id), version)
        self._digest.put((tenant, form_id), record_value.get("checksum", ""))

    def delete(self, form_key: int) -> None:
        meta = self._by_key.get((form_key,))
        if meta is None:
            return
        tenant = meta.get("tenantId", DEFAULT_TENANT)
        form_id, version = meta["formId"], meta["version"]
        self._by_key.delete((form_key,))
        _rollback_latest_version(
            self._by_id_version, self._version, self._digest,
            tenant, form_id, version,
            digest_of=lambda k: self._by_key.get((k,)).get("checksum", ""),
        )

    # queries

    def next_version(self, form_id: str, tenant: str = DEFAULT_TENANT) -> int:
        return (self._version.get((tenant, form_id)) or 0) + 1

    def latest_digest(self, form_id: str, tenant: str = DEFAULT_TENANT) -> str | None:
        return self._digest.get((tenant, form_id))

    def get_by_key(self, form_key: int) -> dict | None:
        return self._by_key.get((form_key,))

    def get_latest_by_id(self, form_id: str,
                         tenant: str = DEFAULT_TENANT) -> dict | None:
        version = self._version.get((tenant, form_id))
        if version is None:
            return None
        key = self._by_id_version.get((tenant, form_id, version))
        return None if key is None else self._by_key.get((key,))


class JobState:
    """Jobs + activatable queue by type + deadlines + retry backoff."""

    def __init__(self, db: ZbDb) -> None:
        self._db = db
        self._jobs = db.column_family(CF.JOBS)
        self._states = db.column_family(CF.JOB_STATES)
        self._activatable = db.column_family(CF.JOB_ACTIVATABLE)
        self._deadlines = db.column_family(CF.JOB_DEADLINES)
        self._backoff = db.column_family(CF.JOB_BACKOFF)

    # mutators

    @staticmethod
    def _act_key(job: dict, key: int) -> tuple:
        # tenant inside the index key: tenant-filtered activation peeks are
        # prefix lookups, not scans (reference: tenant-aware JobState CFs)
        return (job["type"], job.get("tenantId", DEFAULT_TENANT), key)

    def create(self, key: int, record_value: dict) -> None:
        self._jobs.put((key,), dict(record_value))
        self._states.put((key,), JOB_ACTIVATABLE)
        self._activatable.put(self._act_key(record_value, key), None)
        # physical park seam: an instance waiting on a job is a tiering
        # candidate (state/tiering.py); no-op when tiering is off
        self._db.note_parked(record_value.get("processInstanceKey", -1))

    def activate(self, key: int, worker: str, deadline: int) -> None:
        job = self._jobs.get((key,))
        job["worker"] = worker
        job["deadline"] = deadline
        self._jobs.put((key,), job)
        self._states.put((key,), JOB_ACTIVATED)
        self._activatable.delete(self._act_key(job, key))
        self._deadlines.put((deadline, key), None)
        self._db.note_due(deadline)

    def complete(self, key: int) -> None:
        self._remove(key)

    def update_value(self, key: int, record_value: dict) -> None:
        """Retarget job metadata without touching lifecycle indexes
        (migration applier)."""
        if self._jobs.exists((key,)):
            self._jobs.put((key,), dict(record_value))

    def cancel(self, key: int) -> None:
        self._remove(key)

    def _remove(self, key: int) -> None:
        job = self._jobs.get((key,))
        if job is None:
            return
        state = self._states.get((key,))
        if state == JOB_ACTIVATABLE:
            self._activatable.delete(self._act_key(job, key))
        if state == JOB_ACTIVATED and job.get("deadline", -1) >= 0:
            self._deadlines.delete((job["deadline"], key))
        backoff_until = job.get("backoffUntil", -1)
        if backoff_until > 0 and self._backoff.exists((backoff_until, key)):
            self._backoff.delete((backoff_until, key))
        self._jobs.delete((key,))
        self._states.delete((key,))

    def fail(self, key: int, retries: int, backoff_until: int = -1) -> None:
        job = self._jobs.get((key,))
        state = self._states.get((key,))
        if state == JOB_ACTIVATED and job.get("deadline", -1) >= 0:
            self._deadlines.delete((job["deadline"], key))
        job["retries"] = retries
        job["deadline"] = -1
        if backoff_until > 0:
            job["backoffUntil"] = backoff_until
        self._jobs.put((key,), job)
        if retries > 0:
            if backoff_until > 0:
                self._states.put((key,), JOB_FAILED)
                self._backoff.put((backoff_until, key), None)
                self._db.note_due(backoff_until)
            else:
                self._states.put((key,), JOB_ACTIVATABLE)
                self._activatable.put(self._act_key(job, key), None)
        else:
            self._states.put((key,), JOB_FAILED)

    def recur_after_backoff(self, key: int, backoff_until: int) -> None:
        job = self._jobs.get((key,))
        stored_until = job.pop("backoffUntil", -1)
        for until in (backoff_until, stored_until):
            if until > 0 and self._backoff.exists((until, key)):
                self._backoff.delete((until, key))
        self._jobs.put((key,), job)
        self._states.put((key,), JOB_ACTIVATABLE)
        self._activatable.put(self._act_key(job, key), None)

    def timeout(self, key: int) -> None:
        """Deadline passed: activated → activatable again."""
        job = self._jobs.get((key,))
        if job.get("deadline", -1) >= 0:
            self._deadlines.delete((job["deadline"], key))
        job["deadline"] = -1
        job["worker"] = ""
        self._jobs.put((key,), job)
        self._states.put((key,), JOB_ACTIVATABLE)
        self._activatable.put(self._act_key(job, key), None)

    def update_retries(self, key: int, retries: int) -> None:
        job = self._jobs.get((key,))
        job["retries"] = retries
        self._jobs.put((key,), job)

    def update_deadline(self, key: int, deadline: int) -> None:
        """UpdateJobTimeout: move the activated job's deadline (reference:
        JobUpdateTimeoutProcessor / JobTimeoutUpdatedApplier)."""
        job = self._jobs.get((key,))
        old = job.get("deadline", -1)
        if old >= 0 and self._deadlines.exists((old, key)):
            self._deadlines.delete((old, key))
        job["deadline"] = deadline
        self._jobs.put((key,), job)
        if self._states.get((key,)) == JOB_ACTIVATED:
            self._deadlines.put((deadline, key), None)
            self._db.note_due(deadline)

    def error_thrown(self, key: int) -> None:
        """The job is consumed by a thrown BPMN error (reference:
        JobErrorThrownApplier removes it from activatable/deadline sets)."""
        self._remove(key)

    def make_activatable(self, key: int) -> None:
        """After retries updated on a no-retries-failed job + incident resolve."""
        job = self._jobs.get((key,))
        self._states.put((key,), JOB_ACTIVATABLE)
        self._activatable.put(self._act_key(job, key), None)

    # queries

    def get(self, key: int) -> dict | None:
        return self._jobs.get((key,))

    def state_of(self, key: int) -> int | None:
        return self._states.get((key,))

    @staticmethod
    def any_activatable_committed(db: ZbDb, job_type: str,
                                  tenant_ids: list[str] | None = None) -> bool:
        """Lock-free long-poll peek at the COMMITTED activatable index —
        the cross-thread twin of :meth:`activatable_keys`. Gateway threads
        must never open the processing-owned transaction slot
        (committed-read discipline, enforced by zlint's
        committed-read-discipline rule); the key-index read costs one
        bisect and no value materialization."""
        if tenant_ids is None:
            return bool(db.committed_keys_of(CF.JOB_ACTIVATABLE, (job_type,)))
        return any(
            db.committed_keys_of(CF.JOB_ACTIVATABLE, (job_type, tenant))
            for tenant in tenant_ids)

    def activatable_keys(self, job_type: str, limit: int,
                         tenant_ids: list[str] | None = None) -> list[int]:
        """Activatable job keys of a type, optionally restricted to the
        caller's authorized tenants; each tenant is a prefix range
        (reference: JobBatchCollector + tenant-aware JobState CFs)."""
        out: list[int] = []
        if tenant_ids is None:
            for enc_key, _ in self._activatable.items((job_type,)):
                out.append(_decode_trailing_i64(enc_key))
                if len(out) >= limit:
                    break
            return out
        for tenant in tenant_ids:
            for enc_key, _ in self._activatable.items((job_type, tenant)):
                out.append(_decode_trailing_i64(enc_key))
                if len(out) >= limit:
                    return out
        return out

    # due-date-prefixed sorted keys + range-bounded scans: each sweep touches
    # exactly the due entries — O(due), never O(parked) — where the previous
    # break-on-first-future loop still MATERIALIZED the whole index first

    def expired_deadlines(self, now_millis: int) -> list[int]:
        return [
            _decode_two_i64(enc_key)[1]
            for enc_key, _ in self._deadlines.items_below((now_millis + 1,))
        ]

    def backoff_due(self, now_millis: int) -> list[tuple[int, int]]:
        return [
            _decode_two_i64(enc_key)
            for enc_key, _ in self._backoff.items_below((now_millis + 1,))
        ]

    def next_deadline(self) -> int | None:
        item = self._deadlines.first_item()
        return None if item is None else _decode_two_i64(item[0])[0]

    def next_backoff(self) -> int | None:
        item = self._backoff.first_item()
        return None if item is None else _decode_two_i64(item[0])[0]


def _decode_trailing_i64(enc_key: bytes) -> int:
    import struct as _struct

    (flipped,) = _struct.unpack(">Q", enc_key[-8:])
    value = flipped ^ 0x8000000000000000
    return value - (1 << 64) if value >= (1 << 63) else value


def _decode_two_i64(enc_key: bytes) -> tuple[int, int]:
    import struct as _struct

    (f1,) = _struct.unpack(">Q", enc_key[3:11])
    (f2,) = _struct.unpack(">Q", enc_key[12:20])
    v1 = f1 ^ 0x8000000000000000
    v2 = f2 ^ 0x8000000000000000
    v1 = v1 - (1 << 64) if v1 >= (1 << 63) else v1
    v2 = v2 - (1 << 64) if v2 >= (1 << 63) else v2
    return v1, v2


class VariableState:
    """Scoped variables: (scopeKey, name) → value; lookup walks the scope chain."""

    def __init__(self, db: ZbDb, element_instances: ElementInstanceState) -> None:
        self._vars = db.column_family(CF.VARIABLES)
        self._instances = element_instances

    # mutators

    def set_variable(self, scope_key: int, name: str, value: Any) -> None:
        self._vars.put((scope_key, name), value)

    def remove_scope(self, scope_key: int) -> None:
        for enc_key, _ in list(self._vars.items((scope_key,))):
            self._vars._ctx().delete(enc_key)

    # queries

    def get_local(self, scope_key: int, name: str) -> Any:
        return self._vars.get((scope_key, name))

    def has_local(self, scope_key: int, name: str) -> bool:
        return self._vars.exists((scope_key, name))

    def locals_of(self, scope_key: int) -> dict[str, Any]:
        out = {}
        for enc_key, value in self._vars.items((scope_key,)):
            name = enc_key[2 + 9 + 1 : -1].decode("utf-8")
            out[name] = value
        return out

    def find_scope_with(self, scope_key: int, name: str) -> int | None:
        """Nearest enclosing scope defining ``name`` (for variable updates)."""
        cur = scope_key
        while cur >= 0:
            if self.has_local(cur, name):
                return cur
            instance = self._instances.get(cur)
            if instance is None:
                return None
            cur = instance["value"].get("flowScopeKey", -1)
        return None

    def collect(self, scope_key: int) -> dict[str, Any]:
        """Effective variables visible from a scope (inner shadows outer) —
        the evaluation context for conditions and mappings."""
        chain = []
        cur = scope_key
        while cur >= 0:
            chain.append(cur)
            instance = self._instances.get(cur)
            if instance is None:
                break
            cur = instance["value"].get("flowScopeKey", -1)
        out: dict[str, Any] = {}
        for scope in reversed(chain):
            out.update(self.locals_of(scope))
        return out


class TimerState:
    """Timer instances + due-date index + per-element index (reference:
    TimerInstanceState keys timers by (elementInstanceKey, timerKey))."""

    def __init__(self, db: ZbDb) -> None:
        self._db = db
        self._timers = db.column_family(CF.TIMERS)
        self._due = db.column_family(CF.TIMER_DUE_DATES)
        self._by_element = db.column_family(CF.TIMER_BY_ELEMENT)

    def create(self, key: int, record_value: dict) -> None:
        self._timers.put((key,), dict(record_value))
        self._due.put((record_value["dueDate"], key), None)
        element_key = record_value.get("elementInstanceKey", -1)
        if element_key >= 0:
            self._by_element.put((element_key, key), None)
        self._db.note_due(record_value["dueDate"])
        self._db.note_parked(record_value.get("processInstanceKey", -1))

    def remove(self, key: int) -> None:
        timer = self._timers.get((key,))
        if timer is None:
            return
        self._due.delete((timer["dueDate"], key))
        element_key = timer.get("elementInstanceKey", -1)
        if element_key >= 0 and self._by_element.exists((element_key, key)):
            self._by_element.delete((element_key, key))
        self._timers.delete((key,))

    def get(self, key: int) -> dict | None:
        return self._timers.get((key,))

    def due_timers(self, now_millis: int) -> list[tuple[int, dict]]:
        # range-bounded: O(due) even with a million parked timers behind now
        out = []
        for enc_key, _ in self._due.items_below((now_millis + 1,)):
            key = _decode_two_i64(enc_key)[1]
            out.append((key, self._timers.get((key,))))
        return out

    def next_due(self) -> int | None:
        item = self._due.first_item()
        return None if item is None else _decode_two_i64(item[0])[0]

    def timers_for_element_instance(self, element_instance_key: int) -> list[tuple[int, dict]]:
        out = []
        for enc_key, _ in self._by_element.items((element_instance_key,)):
            key = _decode_trailing_i64(enc_key)
            out.append((key, self._timers.get((key,))))
        return out

    def start_timers_for_process(self, process_definition_key: int) -> list[tuple[int, dict]]:
        """Timer-start-event timers of a definition (deploy-time scan is fine)."""
        out = []
        for enc_key, timer in self._timers.items():
            if (
                timer.get("elementInstanceKey", -1) < 0
                and timer.get("processDefinitionKey") == process_definition_key
            ):
                out.append((_decode_trailing_i64(enc_key), timer))
        return out


class MessageState:
    """Published message buffer + TTL deadlines + id dedup (reference:
    MessageState: MESSAGES, MESSAGE_DEADLINES, MESSAGE_IDS CFs)."""

    def __init__(self, db: ZbDb) -> None:
        self._db = db
        self._messages = db.column_family(CF.MESSAGES)
        self._by_name_key = db.column_family(CF.MESSAGE_PROCESSES)  # (name, corrKey, msgKey)
        self._deadlines = db.column_family(CF.MESSAGE_DEADLINES)
        self._ids = db.column_family(CF.MESSAGE_IDS)
        self._correlated = db.column_family(CF.MESSAGE_CORRELATED)

    def put(self, key: int, record_value: dict, deadline: int) -> None:
        self._messages.put((key,), dict(record_value))
        self._by_name_key.put((record_value["name"], record_value["correlationKey"], key), None)
        if deadline > 0:
            self._deadlines.put((deadline, key), None)
            self._db.note_due(deadline)
        message_id = record_value.get("messageId") or ""
        if message_id:
            # tenant is part of the dedup key: id reuse across tenants must
            # not clobber another tenant's entry (reference: tenant-aware
            # MESSAGE_IDS column family)
            tenant = record_value.get("tenantId", DEFAULT_TENANT)
            self._ids.put(
                (record_value["name"], record_value["correlationKey"],
                 message_id, tenant), key)

    def remove(self, key: int, deadline: int) -> None:
        msg = self._messages.get((key,))
        if msg is None:
            return
        self._by_name_key.delete((msg["name"], msg["correlationKey"], key))
        if deadline > 0 and self._deadlines.exists((deadline, key)):
            self._deadlines.delete((deadline, key))
        message_id = msg.get("messageId") or ""
        if message_id:
            id_key = (msg["name"], msg["correlationKey"], message_id,
                      msg.get("tenantId", DEFAULT_TENANT))
            if self._ids.exists(id_key):
                self._ids.delete(id_key)
        for enc_key, _ in list(self._correlated.items((key,))):
            self._correlated._ctx().delete(enc_key)
        self._messages.delete((key,))

    def get(self, key: int) -> dict | None:
        return self._messages.get((key,))

    def is_id_taken(self, name: str, correlation_key: str, message_id: str,
                    tenant: str = DEFAULT_TENANT) -> bool:
        return self._ids.exists((name, correlation_key, message_id, tenant))

    def buffered_for(self, name: str, correlation_key: str) -> list[int]:
        out = []
        for enc_key, _ in self._by_name_key.items((name, correlation_key)):
            out.append(_decode_trailing_i64(enc_key))
        return out

    def mark_correlated(self, message_key: int, process_instance_key: int) -> None:
        self._correlated.put((message_key, process_instance_key), None)

    def was_correlated_to(self, message_key: int, process_instance_key: int) -> bool:
        return self._correlated.exists((message_key, process_instance_key))

    def expired(self, now_millis: int) -> list[tuple[int, int]]:
        # range-bounded: O(due) regardless of the parked TTL backlog
        return [
            _decode_two_i64(enc_key)
            for enc_key, _ in self._deadlines.items_below((now_millis + 1,))
        ]

    def next_deadline(self) -> int | None:
        item = self._deadlines.first_item()
        return None if item is None else _decode_two_i64(item[0])[0]


class MessageSubscriptionState:
    """Message-partition side of correlation: subscriptions by (name, corrKey)
    (reference: MessageSubscriptionState)."""

    def __init__(self, db: ZbDb) -> None:
        self._by_key = db.column_family(CF.MESSAGE_SUBSCRIPTION_BY_KEY)
        self._by_name = db.column_family(CF.MESSAGE_SUBSCRIPTION_BY_NAME_AND_CORRELATION_KEY)

    def put(self, key: int, record_value: dict) -> None:
        v = dict(record_value)
        self._by_key.put((key,), v)
        self._by_name.put((v["messageName"], v["correlationKey"], key), None)

    def remove(self, key: int) -> None:
        sub = self._by_key.get((key,))
        if sub is None:
            return
        self._by_name.delete((sub["messageName"], sub["correlationKey"], key))
        self._by_key.delete((key,))

    def get(self, key: int) -> dict | None:
        return self._by_key.get((key,))

    def find(self, name: str, correlation_key: str) -> list[tuple[int, dict]]:
        out = []
        for enc_key, _ in self._by_name.items((name, correlation_key)):
            key = _decode_trailing_i64(enc_key)
            out.append((key, self._by_key.get((key,))))
        return out


class ProcessMessageSubscriptionState:
    """Process-partition side: subscriptions by element instance (reference:
    ProcessMessageSubscriptionState)."""

    def __init__(self, db: ZbDb) -> None:
        self._db = db
        self._by_key = db.column_family(CF.PROCESS_SUBSCRIPTION_BY_KEY)

    def put(self, element_instance_key: int, message_name: str, record_value: dict) -> None:
        self._by_key.put((element_instance_key, message_name), dict(record_value))
        # an instance waiting on a message is a tiering candidate
        self._db.note_parked(record_value.get("processInstanceKey", -1))

    def update(self, element_instance_key: int, message_name: str, **fields) -> None:
        sub = self._by_key.get((element_instance_key, message_name))
        sub.update(fields)
        self._by_key.put((element_instance_key, message_name), sub)

    def remove(self, element_instance_key: int, message_name: str) -> None:
        if self._by_key.exists((element_instance_key, message_name)):
            self._by_key.delete((element_instance_key, message_name))

    def get(self, element_instance_key: int, message_name: str) -> dict | None:
        return self._by_key.get((element_instance_key, message_name))

    def subscriptions_of(self, element_instance_key: int) -> list[dict]:
        return list(self._by_key.values((element_instance_key,)))


class MessageStartEventSubscriptionState:
    def __init__(self, db: ZbDb) -> None:
        self._by_name = db.column_family(CF.MESSAGE_START_EVENT_SUBSCRIPTION_BY_NAME_AND_KEY)

    def put(self, message_name: str, process_definition_key: int, record_value: dict) -> None:
        self._by_name.put((message_name, process_definition_key), dict(record_value))

    def remove_for_process(self, process_definition_key: int) -> None:
        for enc_key, v in list(self._by_name.items()):
            if v.get("processDefinitionKey") == process_definition_key:
                self._by_name._ctx().delete(enc_key)

    def find(self, message_name: str) -> list[dict]:
        return list(self._by_name.values((message_name,)))


class SignalSubscriptionState:
    """Signal subscriptions (reference: state/signal/DbSignalSubscriptionState):
    keyed (signalName, subscriptionKey) where the subscription key is the
    process definition key for start-event subscriptions and the element
    instance key for catch-event/boundary/event-sub-process subscriptions."""

    def __init__(self, db: ZbDb) -> None:
        self._by_name = db.column_family(CF.SIGNAL_SUBSCRIPTION_BY_NAME_AND_KEY)
        self._by_key = db.column_family(CF.SIGNAL_SUBSCRIPTION_BY_KEY_AND_NAME)

    def put(self, signal_name: str, subscription_key: int, record_value: dict) -> None:
        self._by_name.put((signal_name, subscription_key), dict(record_value))
        self._by_key.put((subscription_key, signal_name), None)

    def remove(self, signal_name: str, subscription_key: int) -> None:
        if self._by_name.exists((signal_name, subscription_key)):
            self._by_name.delete((signal_name, subscription_key))
        if self._by_key.exists((subscription_key, signal_name)):
            self._by_key.delete((subscription_key, signal_name))

    def find(self, signal_name: str) -> list[dict]:
        return list(self._by_name.values((signal_name,)))

    def names_of(self, subscription_key: int) -> list[str]:
        out = []
        for enc_key, _ in self._by_key.items((subscription_key,)):
            # key layout: u16 cf | 0x01 i64(key) | 0x01 utf8(name) | 0x00
            out.append(enc_key[2 + 9 + 1 : -1].decode("utf-8"))
        return out

    def subscriptions_of(self, subscription_key: int) -> list[dict]:
        return [
            sub
            for name in self.names_of(subscription_key)
            if (sub := self._by_name.get((name, subscription_key))) is not None
        ]


class IncidentState:
    def __init__(self, db: ZbDb) -> None:
        self._incidents = db.column_family(CF.INCIDENTS)
        self._by_element = db.column_family(CF.INCIDENT_PROCESS_INSTANCES)
        self._by_job = db.column_family(CF.INCIDENT_JOBS)

    def create(self, key: int, record_value: dict) -> None:
        self._incidents.put((key,), dict(record_value))
        element_key = record_value.get("elementInstanceKey", -1)
        if element_key >= 0:
            self._by_element.put((element_key, key), None)
        job_key = record_value.get("jobKey", -1)
        if job_key >= 0:
            self._by_job.put((job_key,), key)

    def resolve(self, key: int) -> None:
        incident = self._incidents.get((key,))
        if incident is None:
            return
        element_key = incident.get("elementInstanceKey", -1)
        if element_key >= 0:
            self._by_element.delete((element_key, key))
        job_key = incident.get("jobKey", -1)
        if job_key >= 0:
            self._by_job.delete((job_key,))
        self._incidents.delete((key,))

    def get(self, key: int) -> dict | None:
        return self._incidents.get((key,))

    def incident_key_for_job(self, job_key: int) -> int | None:
        return self._by_job.get((job_key,))


class BannedInstanceState:
    """Poison process instances quarantined instead of wedging the partition
    (reference: state/instance/BannedInstanceState, Engine.java:126)."""

    def __init__(self, db: ZbDb) -> None:
        self._banned = db.column_family(CF.BANNED_INSTANCE)
        from zeebe_tpu.utils.metrics import REGISTRY

        # registered at state construction, not first ban (reference:
        # BannedInstanceMetrics is a static collector)
        self._banned_counter = REGISTRY.counter(
            "banned_instances_total",
            "process instances quarantined after processing errors",
            ("partition",))

    def ban(self, process_instance_key: int) -> None:
        self._banned.put((process_instance_key,), True)
        from zeebe_tpu.protocol.keys import decode_partition_id

        self._banned_counter.labels(
            str(decode_partition_id(process_instance_key))).inc()

    def is_banned(self, process_instance_key: int) -> bool:
        return process_instance_key >= 0 and self._banned.exists((process_instance_key,))


class DistributionState:
    """Pending command distributions (reference: state/distribution/
    DbDistributionState — COMMAND_DISTRIBUTION_RECORD stores the distributed
    command, PENDING_DISTRIBUTION marks (distributionKey, partition) pairs still
    awaiting an ACKNOWLEDGE; receiver side dedups retried sends)."""

    # receiver dedup markers are retained long enough to absorb origin retries,
    # then purged (deterministically, from the applier) so state and snapshots
    # don't grow without bound
    RECEIVED_RETENTION_MS = 24 * 3_600_000

    def __init__(self, db: ZbDb) -> None:
        self._records = db.column_family(CF.COMMAND_DISTRIBUTION_RECORD)
        self._pending = db.column_family(CF.PENDING_DISTRIBUTION)
        self._received = db.column_family(CF.DISTRIBUTION)
        self._received_by_time = db.column_family(CF.RECEIVED_DISTRIBUTION_BY_TIME)

    def start(self, distribution_key: int, stored: dict) -> None:
        self._records.put((distribution_key,), dict(stored))

    def get(self, distribution_key: int) -> dict | None:
        return self._records.get((distribution_key,))

    def add_pending(self, distribution_key: int, partition: int) -> None:
        self._pending.put((distribution_key, partition), None)

    def remove_pending(self, distribution_key: int, partition: int) -> None:
        if self._pending.exists((distribution_key, partition)):
            self._pending.delete((distribution_key, partition))

    def pending_partitions(self, distribution_key: int) -> list[int]:
        return [
            _decode_trailing_i64(enc) for enc, _ in self._pending.items((distribution_key,))
        ]

    def is_pending(self, distribution_key: int, partition: int) -> bool:
        return self._pending.exists((distribution_key, partition))

    def none_pending(self, distribution_key: int) -> bool:
        return self._pending.is_empty((distribution_key,))

    def has_any_pending(self) -> bool:
        return not self._pending.is_empty()

    def all_pending(self) -> list[tuple[int, int]]:
        return [_decode_two_i64(enc) for enc, _ in self._pending.items()]

    def finish(self, distribution_key: int) -> None:
        if self._records.exists((distribution_key,)):
            self._records.delete((distribution_key,))

    def mark_received(self, distribution_key: int, received_at: int) -> None:
        if self._received.exists((distribution_key,)):
            return
        self._received.put((distribution_key,), received_at)
        self._received_by_time.put((received_at, distribution_key), None)
        # Purge markers older than the retention window, keyed by the event's
        # own clock value so replay purges identically. A retry arriving after
        # its marker was purged re-executes the command (at-least-once);
        # receiver processors stay idempotent at the domain level (e.g. the
        # deployment digest check) to keep that harmless.
        cutoff = received_at - self.RECEIVED_RETENTION_MS
        expired: list[tuple[int, int]] = []
        for enc, _ in self._received_by_time.items():
            at, key = _decode_two_i64(enc)
            if at >= cutoff:
                break
            expired.append((at, key))
        for at, key in expired:
            self._received_by_time.delete((at, key))
            if self._received.exists((key,)):
                self._received.delete((key,))

    def was_received(self, distribution_key: int) -> bool:
        return self._received.exists((distribution_key,))


class DecisionState:
    """Deployed DMN decision requirement graphs + decisions (reference:
    state/deployment/DbDecisionState — decisions by key, latest by id, DRGs by
    key with the raw resource for re-parse on recovery)."""

    def __init__(self, db: ZbDb) -> None:
        self._decisions = db.column_family(CF.DMN_DECISIONS)
        self._drgs = db.column_family(CF.DMN_DECISION_REQUIREMENTS)
        self._latest_decision = db.column_family(CF.DMN_LATEST_DECISION_BY_ID)
        self._latest_drg = db.column_family(CF.DMN_LATEST_DRG_BY_ID)
        self._by_drg = db.column_family(CF.DMN_DECISIONS_BY_DRG)
        self._parsed: dict[int, object] = {}  # drg_key → ParsedDrg (cache)

    @staticmethod
    def _tenant_of(meta: dict) -> str:
        return meta.get("tenantId", DEFAULT_TENANT)

    def put_drg(self, drg_key: int, meta: dict) -> None:
        self._drgs.put((drg_key,), dict(meta))
        id_key = (self._tenant_of(meta), meta["decisionRequirementsId"])
        latest = self._latest_drg.get(id_key)
        if latest is None or meta["version"] >= latest.get("version", 0):
            self._latest_drg.put(id_key,
                                 {"version": meta["version"], "key": drg_key})

    def put_decision(self, decision_key: int, meta: dict) -> None:
        self._decisions.put((decision_key,), dict(meta))
        self._by_drg.put((meta["decisionRequirementsKey"], decision_key), None)
        id_key = (self._tenant_of(meta), meta["decisionId"])
        latest_key = self._latest_decision.get(id_key)
        latest = self._decisions.get((latest_key,)) if latest_key else None
        if latest is None or meta["version"] >= latest.get("version", 0):
            self._latest_decision.put(id_key, decision_key)

    def decision_by_key(self, decision_key: int) -> dict | None:
        return self._decisions.get((decision_key,))

    def latest_decision_by_id(self, decision_id: str,
                              tenant: str = DEFAULT_TENANT) -> dict | None:
        key = self._latest_decision.get((tenant, decision_id))
        return None if key is None else self._decisions.get((key,))

    def drg_by_key(self, drg_key: int) -> dict | None:
        return self._drgs.get((drg_key,))

    def latest_drg_meta(self, drg_id: str,
                        tenant: str = DEFAULT_TENANT) -> dict | None:
        latest = self._latest_drg.get((tenant, drg_id))
        return None if latest is None else self._drgs.get((latest["key"],))

    def decisions_of_drg(self, drg_key: int) -> list[dict]:
        return [
            self._decisions.get((_decode_trailing_i64(enc),))
            for enc, _ in self._by_drg.items((drg_key,))
        ]

    def latest_drg_digest(self, drg_id: str,
                          tenant: str = DEFAULT_TENANT) -> str | None:
        latest = self._latest_drg.get((tenant, drg_id))
        if latest is None:
            return None
        drg = self._drgs.get((latest["key"],))
        return None if drg is None else drg.get("checksum")

    def latest_drg_version(self, drg_id: str,
                           tenant: str = DEFAULT_TENANT) -> int:
        latest = self._latest_drg.get((tenant, drg_id))
        return 0 if latest is None else latest["version"]

    def delete_drg(self, drg_key: int) -> None:
        """Resource deletion: drop the DRG and all its decisions."""
        drg = self._drgs.get((drg_key,))
        if drg is None:
            return
        tenant = self._tenant_of(drg)
        for meta in self.decisions_of_drg(drg_key):
            if meta is None:
                continue
            decision_key = meta["decisionKey"]
            self._decisions.delete((decision_key,))
            self._by_drg.delete((drg_key, decision_key))
            dec_key = (tenant, meta["decisionId"])
            if self._latest_decision.get(dec_key) == decision_key:
                self._latest_decision.delete(dec_key)
        self._drgs.delete((drg_key,))
        self._parsed.pop(drg_key, None)
        drg_id = drg["decisionRequirementsId"]
        latest = self._latest_drg.get((tenant, drg_id))
        if latest is not None and latest.get("key") == drg_key:
            self._latest_drg.delete((tenant, drg_id))
            # repoint latest to the highest remaining version of the same DRG
            best = None
            for remaining in self._drgs.values():
                if remaining.get("decisionRequirementsId") != drg_id:
                    continue
                if self._tenant_of(remaining) != tenant:
                    continue
                if best is None or remaining["version"] > best["version"]:
                    best = remaining
            if best is not None:
                best_key = best["decisionRequirementsKey"]
                self._latest_drg.put((tenant, drg_id),
                                     {"version": best["version"], "key": best_key})
                for meta in self.decisions_of_drg(best_key):
                    if meta is not None:
                        self._latest_decision.put((tenant, meta["decisionId"]),
                                                  meta["decisionKey"])

    def parsed_drg(self, drg_key: int):
        """Parse-once cache over the stored DMN resource."""
        cached = self._parsed.get(drg_key)
        if cached is not None:
            return cached
        drg_meta = self._drgs.get((drg_key,))
        if drg_meta is None:
            return None
        from zeebe_tpu.dmn import parse_dmn_xml

        parsed = parse_dmn_xml(drg_meta["resource"])
        self._parsed[drg_key] = parsed
        return parsed


class UserTaskState:
    """Native user tasks (reference: state/usertask/DbUserTaskState)."""

    def __init__(self, db: ZbDb) -> None:
        self._tasks = db.column_family(CF.USER_TASKS)
        self._by_element = db.column_family(CF.USER_TASK_STATES)

    def create(self, key: int, record_value: dict) -> None:
        self._tasks.put((key,), dict(record_value))
        self._by_element.put((record_value["elementInstanceKey"],), key)

    def update(self, key: int, record_value: dict) -> None:
        if self._tasks.exists((key,)):
            self._tasks.put((key,), dict(record_value))

    def remove(self, key: int) -> None:
        task = self._tasks.get((key,))
        if task is None:
            return
        element_key = task.get("elementInstanceKey", -1)
        if self._by_element.exists((element_key,)):
            self._by_element.delete((element_key,))
        self._tasks.delete((key,))

    def get(self, key: int) -> dict | None:
        return self._tasks.get((key,))

    def key_for_element(self, element_instance_key: int) -> int | None:
        return self._by_element.get((element_instance_key,))


class EngineState:
    """Aggregates all engine sub-states over one partition's db + key generator
    (reference: ProcessingDbState)."""

    def __init__(self, db: ZbDb, partition_id: int) -> None:
        self.db = db
        self.partition_id = partition_id
        self.processes = ProcessState(db)
        self.forms = FormState(db)
        self.element_instances = ElementInstanceState(db)
        self.jobs = JobState(db)
        self.variables = VariableState(db, self.element_instances)
        self.incidents = IncidentState(db)
        self.banned = BannedInstanceState(db)
        self.timers = TimerState(db)
        self.messages = MessageState(db)
        self.message_subscriptions = MessageSubscriptionState(db)
        self.process_message_subscriptions = ProcessMessageSubscriptionState(db)
        self.message_start_subscriptions = MessageStartEventSubscriptionState(db)
        self.signal_subscriptions = SignalSubscriptionState(db)
        self.distribution = DistributionState(db)
        self.decisions = DecisionState(db)
        from zeebe_tpu.backup.checkpoint import CheckpointState

        self.checkpoints = CheckpointState(db)
        self.user_tasks = UserTaskState(db)
        self._key_cf = db.column_family(CF.KEY)
        self.key_generator = KeyGenerator(partition_id)
        self._key_loaded = False

    def load_key_generator(self) -> None:
        with self.db.transaction():
            current = self._key_cf.get(("next",))
        if current is not None:
            self.key_generator = KeyGenerator(self.partition_id, start=current)
        self._key_loaded = True

    def next_key(self) -> int:
        key = self.key_generator.next_key()
        self._key_cf.put(("next",), self.key_generator.current)
        return key

    def bulk_mint(self, count: int) -> list[int]:
        """Mint ``count`` keys with a single generator-state write (the burst
        template fast path: same final generator state as ``count`` next_key
        calls, one CF put instead of ``count``). Keys are computed as one
        range over the partition-encoded base — identical to ``count``
        next_key calls (encode_partition_id is base + local counter)."""
        if not count:
            return []
        gen = self.key_generator
        first = gen.next_key()
        mints = list(range(first, first + count))
        gen.set_current(gen.current + count - 1)
        self._key_cf.put(("next",), gen.current)
        return mints

    def observe_key(self, key: int) -> None:
        """Replay path: fast-forward the generator past keys seen in events."""
        self.key_generator.set_key_if_higher(key)
        self._key_cf.put(("next",), self.key_generator.current)
