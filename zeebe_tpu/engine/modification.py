"""Process-instance modification, migration, and resource deletion.

Reference: engine/…/processing/processinstance/
ProcessInstanceModificationProcessor.java (activate/terminate arbitrary
elements with variable instructions), ProcessInstanceMigration processors
(8.4: map active element instances onto a target definition via mapping
instructions), and resource/ResourceDeletionDeleteProcessor (delete a
deployed process definition or DRG, closing its start subscriptions).
"""

from __future__ import annotations

from zeebe_tpu.engine.engine_state import (
    EI_ACTIVATED,
    EI_ACTIVATING,
    EngineState,
)
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import RejectionType, ValueType
from zeebe_tpu.protocol.enums import BpmnElementType
from zeebe_tpu.protocol.intent import (
    ProcessInstanceIntent,
    ProcessInstanceMigrationIntent,
    ProcessInstanceModificationIntent,
    ResourceDeletionIntent,
    VariableIntent,
)


def _descendants(state: EngineState, scope_key: int) -> list[int]:
    """All transitive element-instance children of a scope."""
    out = []
    stack = [scope_key]
    while stack:
        key = stack.pop()
        children = state.element_instances.children_keys(key)
        out.extend(children)
        stack.extend(children)
    return out


class ProcessInstanceModificationProcessor:
    """PROCESS_INSTANCE_MODIFICATION MODIFY (key = process instance key)."""

    def __init__(self, state: EngineState, bpmn) -> None:
        self.state = state
        self.bpmn = bpmn

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        pi_key = cmd.record.key
        value = cmd.record.value
        instance = self.state.element_instances.get(pi_key)
        if instance is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to modify process instance {pi_key}, but none found",
            )
            return
        pi_value = instance["value"]
        exe = self.state.processes.executable(pi_value["processDefinitionKey"])
        activate = value.get("activateInstructions", [])
        terminate = value.get("terminateInstructions", [])

        # validate everything before writing anything (all-or-nothing command)
        plans = []
        for instruction in activate:
            element_id = instruction.get("elementId", "")
            if element_id not in exe.by_id:
                writers.respond_rejection(
                    cmd, RejectionType.INVALID_ARGUMENT,
                    f"Expected to activate element '{element_id}', but no such "
                    "element in the process definition",
                )
                return
            element = exe.elements[exe.by_id[element_id]]
            scope_key = self._resolve_scope(
                pi_key, exe, element,
                instruction.get("ancestorElementInstanceKey", -1),
            )
            if scope_key is None:
                writers.respond_rejection(
                    cmd, RejectionType.INVALID_STATE,
                    f"Expected to activate element '{element_id}', but its flow "
                    "scope is not active exactly once; pass "
                    "ancestorElementInstanceKey to disambiguate",
                )
                return
            plans.append((element, scope_key, instruction))
        for instruction in terminate:
            target = instruction.get("elementInstanceKey", -1)
            target_instance = self.state.element_instances.get(target)
            if target_instance is None or \
                    target_instance["value"].get("processInstanceKey") != pi_key:
                writers.respond_rejection(
                    cmd, RejectionType.NOT_FOUND,
                    f"Expected to terminate element instance {target}, but it "
                    "is not an active element of this process instance",
                )
                return

        modified = writers.append_event(
            pi_key, ValueType.PROCESS_INSTANCE_MODIFICATION,
            ProcessInstanceModificationIntent.MODIFIED, dict(value),
        )
        writers.respond(cmd, modified)
        # activations BEFORE terminations: terminating the last active child
        # first would complete the whole scope before the new tokens exist
        # (reference: ProcessInstanceModificationProcessor ordering)
        for element, scope_key, instruction in plans:
            # variable instructions seed the target scope (or the scope named
            # by scopeId) before activation
            for var_inst in instruction.get("variableInstructions", []):
                target_scope = self._variable_scope(
                    pi_key, scope_key, var_inst.get("scopeId", "")
                )
                for name, val in (var_inst.get("variables") or {}).items():
                    writers.append_event(
                        self.state.next_key(), ValueType.VARIABLE,
                        VariableIntent.CREATED,
                        {"name": name, "value": val, "scopeKey": target_scope,
                         "processInstanceKey": pi_key,
                         "processDefinitionKey": pi_value["processDefinitionKey"],
                         "bpmnProcessId": pi_value["bpmnProcessId"]},
                    )
            # no sequence-flow token is in transit for a modification-activated
            # element; the marker keeps the applier's token accounting honest
            self.bpmn._write_activate(writers, exe, element, scope_key, pi_value,
                                      extra={"directActivation": True})
        for instruction in terminate:
            writers.append_command(
                instruction["elementInstanceKey"], ValueType.PROCESS_INSTANCE,
                ProcessInstanceIntent.TERMINATE_ELEMENT, {},
            )

    def _variable_scope(self, pi_key: int, default_scope: int,
                        scope_id: str) -> int:
        """scopeId names an element whose unique active instance receives the
        variables; default is the activated element's flow scope."""
        if not scope_id:
            return default_scope
        root = self.state.element_instances.get(pi_key)
        if root is not None and root["value"].get("bpmnProcessId") == scope_id:
            return pi_key
        candidates = [
            key for key in _descendants(self.state, pi_key)
            if (inst := self.state.element_instances.get(key)) is not None
            and inst["value"].get("elementId") == scope_id
        ]
        return candidates[0] if len(candidates) == 1 else default_scope

    def _resolve_scope(self, pi_key: int, exe, element,
                       ancestor_key: int) -> int | None:
        """The element's flow scope instance: the process root, an explicit
        ancestor, or the unique active instance of the parent scope element."""
        if ancestor_key > 0:
            ancestor = self.state.element_instances.get(ancestor_key)
            if ancestor is None or ancestor["value"].get(
                    "processInstanceKey", ancestor_key) != pi_key:
                return None  # foreign or dead ancestor: reject
            return ancestor_key
        parent_idx = element.parent_idx
        if parent_idx == 0:
            return pi_key
        parent_id = exe.elements[parent_idx].id
        candidates = [
            key for key in _descendants(self.state, pi_key)
            if (inst := self.state.element_instances.get(key)) is not None
            and inst["value"].get("elementId") == parent_id
            and inst["state"] in (EI_ACTIVATED, EI_ACTIVATING)
        ]
        return candidates[0] if len(candidates) == 1 else None



class ProcessInstanceMigrationProcessor:
    """PROCESS_INSTANCE_MIGRATION MIGRATE (key = process instance key)."""

    def __init__(self, state: EngineState) -> None:
        self.state = state

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        pi_key = cmd.record.key
        value = cmd.record.value
        plan = value.get("migrationPlan", {})
        target_key = plan.get("targetProcessDefinitionKey", -1)
        mappings = {
            m["sourceElementId"]: m["targetElementId"]
            for m in plan.get("mappingInstructions", [])
        }
        instance = self.state.element_instances.get(pi_key)
        if instance is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to migrate process instance {pi_key}, but none found",
            )
            return
        target_meta = self.state.processes.get_by_key(target_key)
        target_exe = (self.state.processes.executable(target_key)
                      if target_meta else None)
        if target_exe is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to migrate to process definition {target_key}, "
                "but no such definition deployed",
            )
            return
        # every active element must map onto an element of the target
        # definition (same id by default, or via a mapping instruction)
        tree = [pi_key] + _descendants(self.state, pi_key)
        element_updates: list[tuple[int, str]] = []
        for key in tree:
            inst = self.state.element_instances.get(key)
            if inst is None:
                continue
            source_id = inst["value"].get("elementId", "")
            if key == pi_key:
                element_updates.append((key, target_exe.elements[0].id))
                continue
            target_id = mappings.get(source_id, source_id)
            if target_id not in target_exe.by_id:
                writers.respond_rejection(
                    cmd, RejectionType.INVALID_STATE,
                    f"Expected to migrate element '{source_id}', but the target "
                    f"process has no element '{target_id}' and no mapping",
                )
                return
            if self.state.incidents.incident_key_for_job(
                    inst.get("jobKey", -1)) is not None:
                writers.respond_rejection(
                    cmd, RejectionType.INVALID_STATE,
                    f"Expected to migrate element '{source_id}', but it has an "
                    "unresolved incident",
                )
                return
            element_updates.append((key, target_id))

        migrated = writers.append_event(
            pi_key, ValueType.PROCESS_INSTANCE_MIGRATION,
            ProcessInstanceMigrationIntent.MIGRATED,
            {**value,
             "bpmnProcessId": target_meta["bpmnProcessId"],
             "version": target_meta["version"],
             "elementUpdates": [
                 {"elementInstanceKey": k, "targetElementId": tid}
                 for k, tid in element_updates
             ]},
        )
        writers.respond(cmd, migrated)



def apply_migrated(state: EngineState, record) -> None:
    """Event applier: retarget the instance tree (and its jobs) onto the new
    definition — the only state mutation of a migration."""
    value = record.value
    plan = value.get("migrationPlan", {})
    target_key = plan.get("targetProcessDefinitionKey", -1)
    bpmn_process_id = value.get("bpmnProcessId", "")
    version = value.get("version", -1)
    for update in value.get("elementUpdates", []):
        key = update["elementInstanceKey"]
        inst = state.element_instances.get(key)
        if inst is None:
            continue
        iv = dict(inst["value"])
        iv["processDefinitionKey"] = target_key
        iv["bpmnProcessId"] = bpmn_process_id
        iv["version"] = version
        iv["elementId"] = update["targetElementId"]
        state.element_instances.update(key, value=iv)
        job_key = inst.get("jobKey", -1)
        if job_key >= 0:
            job = state.jobs.get(job_key)
            if job is not None:
                job = dict(job)
                job["processDefinitionKey"] = target_key
                job["bpmnProcessId"] = bpmn_process_id
                job["processDefinitionVersion"] = version
                job["elementId"] = update["targetElementId"]
                state.jobs.update_value(job_key, job)


class ResourceDeletionProcessor:
    """RESOURCE_DELETION DELETE: remove a process definition or DRG by key
    (running instances keep their cached executable; new instances cannot
    start — reference: ResourceDeletionDeleteProcessor)."""

    def __init__(self, state: EngineState, distribution=None) -> None:
        self.state = state
        self.distribution = distribution

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        if self.distribution is not None and \
                self.distribution.is_distributed_command(cmd):
            self.distribution.handle_distributed(
                cmd, writers, lambda: self._delete(cmd.record.value, writers)
            )
            return
        resource_key = cmd.record.value.get("resourceKey", -1)
        process_meta = self.state.processes.get_by_key(resource_key)
        drg_meta = self.state.decisions.drg_by_key(resource_key)
        form_meta = self.state.forms.get_by_key(resource_key)
        if process_meta is None and drg_meta is None and form_meta is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to delete resource {resource_key}, but no deployed "
                "process definition, decision requirements, or form found",
            )
            return
        value = {"resourceKey": resource_key}
        deleting = writers.append_event(
            self.state.next_key(), ValueType.RESOURCE_DELETION,
            ResourceDeletionIntent.DELETING, value,
        )
        self._delete(value, writers)
        writers.respond(cmd, deleting)
        if self.distribution is not None:
            self.distribution.distribute(
                writers, deleting.key, ValueType.RESOURCE_DELETION,
                ResourceDeletionIntent.DELETE, value,
            )

    def _delete(self, value: dict, writers: Writers) -> None:
        from zeebe_tpu.protocol.intent import FormIntent

        resource_key = value["resourceKey"]
        process_meta = self.state.processes.get_by_key(resource_key)
        if process_meta is not None:
            self._close_start_subscriptions(resource_key, process_meta, writers)
        form_meta = self.state.forms.get_by_key(resource_key)
        if form_meta is not None:
            writers.append_event(resource_key, ValueType.FORM, FormIntent.DELETED,
                                 form_meta)
        writers.append_event(
            self.state.next_key(), ValueType.RESOURCE_DELETION,
            ResourceDeletionIntent.DELETED, {"resourceKey": resource_key},
        )

    def _close_start_subscriptions(self, resource_key: int, meta: dict,
                                   writers: Writers) -> None:
        from zeebe_tpu.protocol.intent import (
            MessageStartEventSubscriptionIntent,
            SignalSubscriptionIntent,
            TimerIntent,
        )

        writers.append_event(
            self.state.next_key(), ValueType.MESSAGE_START_EVENT_SUBSCRIPTION,
            MessageStartEventSubscriptionIntent.DELETED,
            {"processDefinitionKey": resource_key,
             "bpmnProcessId": meta["bpmnProcessId"]},
        )
        for timer_key, timer in self.state.timers.start_timers_for_process(resource_key):
            writers.append_event(timer_key, ValueType.TIMER, TimerIntent.CANCELED, timer)
        for sub in self.state.signal_subscriptions.subscriptions_of(resource_key):
            if sub.get("catchEventInstanceKey", -1) < 0:
                writers.append_event(
                    self.state.next_key(), ValueType.SIGNAL_SUBSCRIPTION,
                    SignalSubscriptionIntent.DELETED, sub,
                )
