"""Kernel-path eligibility: ONE reason catalog, the static classifier, and
the consolidated path accounting (ISSUE 13).

ROADMAP item 3 ("make host-side execution the exception") is graded on a
number nothing measured before this module existed: *which records ran on
the kernel path vs host, and why*. Three seams used to answer fragments of
that question with private state — ``check_element_eligibility`` (a bool),
``note_sequential_head`` (a bench-only Counter), and the in-dispatch
``fallback_reasons`` increments — each minting its own reason strings. This
module is their single home:

- **The reason catalog**: every reason a record can take the host path,
  typed and enumerated. Static reasons are predictable from the definition
  alone; runtime-only reasons (geometry bounds, non-quiescence, pool
  overflow, mesh errors) are not — the split is what makes the
  static-vs-observed parity gate sound. ``canonical_reason`` maps any noted
  string (including the dynamic ``head-*:<VT>.<INTENT>`` families) onto the
  catalog; an unregistered string lands on the ``unregistered`` label and
  fails a test instead of silently minting a new metric child.
- **``element_host_reason``**: the reason-returning form of the kernel
  backend's element eligibility check (the backend's boolean is derived
  from it — one logic, two views).
- **``classify_definition``**: the static eligibility report. It runs the
  REAL ``KernelRegistry`` lookup (inlining, solo compile, typed decline
  reasons) so the prediction can never drift from what admission will do,
  then explains every host-forced row through the catalog.
- **``PathAccounting``**: the runtime counter home — per-partition
  ``zeebe_kernel_records_total{path,reason}``, a per-definition
  ``zeebe_kernel_coverage_ratio`` gauge, and the per-definition reason
  split the parity gate compares against the classifier's prediction.

Honest caveats (also in docs/eligibility.md): classification is solo —
joint deployments can downgrade further via SlotMap kind clashes across
definitions; offline classification cannot resolve call activities without
the deployed process state; in-batch follow-up commands ride their head's
path and are not separately counted; coverage is per partition, not global.
"""

from __future__ import annotations

from collections import Counter
from typing import Any

from zeebe_tpu.models.bpmn.executable import ExecutableElement, ExecutableProcess
from zeebe_tpu.ops.tables import _KERNEL_OP, _MI_BODY_TYPES, K_TASK
from zeebe_tpu.protocol.enums import BpmnElementType, BpmnEventType

# ---------------------------------------------------------------------------
# the reason catalog — ONE home for every path-routing reason string

#: statically predictable, per-element: the element itself forces the host
#: path (it lowers to K_HOST, or disqualifies the whole definition)
STATIC_ELEMENT_REASONS = frozenset({
    "multi-instance",
    "io-mapping-nontask",
    "unsafe-expression",
    "output-writes-condition-var",
    "user-task",
    "called-decision",
    "script-task-shape",
    "timer-cycle-date",
    "escalation-boundary",
    "boundary-unsupported",
    "boundary-on-nontask",
    "subprocess-no-none-start",
    "subprocess-event-subprocess",
    "call-activity-unresolved",
    "event-gateway-target",
    "link-unresolved",
    "catch-unsupported",
    "unsupported-element",
    "event-type-unsupported",
    "job-type-dynamic",
    # report-only: the element is individually eligible but sits inside an
    # event sub-process whose tokens only ever enter through a host-routed
    # start event — the ROADMAP item 3 "event-sub-process children" shape
    "event-subprocess-body",
    # the solo/shared lowering downgraded the element to a host escape
    # (condition outside the device subset, SlotMap kind clash)
    "condition-not-compilable",
})

#: statically predictable, definition-level: the definition cannot ride the
#: kernel at all (KernelRegistry lookup declines with one of these;
#: table-set-full is deployment-SET-dependent — the registry's
#: max_definitions capacity, predictable only when classifying the whole
#: set against one shared registry)
DEFINITION_REASONS = frozenset({
    "no-none-start",
    "esp-start-unsupported",
    "condition-not-compilable",
    "table-set-full",
})

#: NOT statically predictable — the dispatch itself declined; the parity
#: gate must never hold these against the classifier
RUNTIME_REASONS = frozenset({
    "geometry-bounds",
    "no-quiesce",
    "token-overflow",
    "mesh-dispatch-error",
    "mesh-no-quiesce",
    "mesh-token-overflow",
    "group-error",
    # device-fault defense (ISSUE 15): containment + quarantine routing
    "device-dispatch-error",
    "device-wedged",
    "device-quarantined",
})

#: dynamic families noted as ``<family>:<VALUE_TYPE>.<INTENT>`` —
#: head-sequential is ordinary non-candidate traffic at the group boundary;
#: head-not-admittable is a candidate command that failed admission (a
#: regression signal when the definition is predicted eligible)
HEAD_FAMILIES = frozenset({"head-sequential", "head-not-admittable"})

#: the full catalog of canonical reason labels (metric label universe)
ALL_REASONS = (STATIC_ELEMENT_REASONS | DEFINITION_REASONS | RUNTIME_REASONS
               | HEAD_FAMILIES)


def canonical_reason(reason: str) -> str | None:
    """Map a noted reason string onto its catalog label: exact codes pass
    through, ``head-*:<kind>`` collapses to its family (bounded metric
    cardinality), anything else is unregistered (None)."""
    family = reason.split(":", 1)[0]
    if family in HEAD_FAMILIES:
        return family
    if reason in ALL_REASONS:
        return reason
    return None


# ---------------------------------------------------------------------------
# element-level classification (the kernel backend's eligibility logic,
# reason-returning; KernelBackend's boolean check derives from this)


def element_host_reason(exe: ExecutableProcess,
                        el: ExecutableElement) -> str | None:
    """None when the sequential engine's behavior for this element is exactly
    the kernel's opcode behavior (engine/…/processing/bpmn element processors
    vs ops/automaton masks); otherwise the catalog reason it must host-route.
    """
    from zeebe_tpu.engine.kernel_backend import (
        _condition_var_names,
        _safe_mapping_expr,
    )

    if el.multi_instance is not None:
        # only synthetic K_MI bodies (_inline_mi_bodies sets child_start on a
        # task-type element) ride the device; real loop elements host-escape
        if el.child_start_idx >= 0 and el.element_type in _MI_BODY_TYPES:
            return None
        return "multi-instance"
    if el.inputs or el.outputs:
        # io-mappings ride the kernel on job-worker tasks only, and only
        # when they cannot fail mid-burst (safe expressions) and their
        # outputs cannot invalidate prefetched device condition slots
        if _KERNEL_OP.get(el.element_type) != K_TASK:
            return "io-mapping-nontask"
        if not all(_safe_mapping_expr(e) for e, _t in el.inputs):
            return "unsafe-expression"
        if el.outputs:
            if not all(_safe_mapping_expr(e) for e, _t in el.outputs):
                return "unsafe-expression"
            if {t for _e, t in el.outputs} & _condition_var_names(exe):
                return "output-writes-condition-var"
    if el.native_user_task:
        return "user-task"
    if el.called_decision_id:
        return "called-decision"
    if el.script_expression is not None:
        # expression-flavor script tasks ride as K_PASS with the evaluation
        # and result write emitted between ACTIVATED and COMPLETING: the
        # expression must be a never-raises safe expression, and the result
        # variable must not invalidate prefetched device condition slots
        # (same discipline as io-mapping outputs)
        if (el.element_type != BpmnElementType.SCRIPT_TASK
                or el.job_type is not None
                or el.inputs or el.outputs or el.boundary_idxs):
            return "script-task-shape"
        if not _safe_mapping_expr(el.script_expression):
            return "unsafe-expression"
        if (el.script_result_variable is not None
                and el.script_result_variable in _condition_var_names(exe)):
            return "output-writes-condition-var"
        return None
    if el.element_type == BpmnElementType.BOUNDARY_EVENT:
        # triggers route sequentially (route_trigger); the kernel only needs
        # the attached wait state to be reconstructable, so the boundary's
        # subscription kind must be one _reconstruct knows how to collect
        if el.event_type == BpmnEventType.TIMER:
            if el.timer_duration is not None and el.timer_date is None:
                return None
            return "timer-cycle-date"
        if el.event_type == BpmnEventType.MESSAGE:
            return None if el.message_name is not None else "boundary-unsupported"
        if el.event_type == BpmnEventType.SIGNAL:
            # signal subscriptions count in the reconstruction integrity
            # check like timers/messages (boundary_waits third slot)
            return None if el.signal_name is not None else "boundary-unsupported"
        # error boundaries carry no wait state at all (the job THROW_ERROR
        # command routes through _find_catcher on the host). Escalation
        # boundaries only fire from a CHILD SCOPE — and scope hosts fail
        # the K_TASK host check anyway
        if el.event_type == BpmnEventType.ERROR:
            return None
        if el.event_type == BpmnEventType.ESCALATION:
            return "escalation-boundary"
        return "boundary-unsupported"
    if el.boundary_idxs:
        # boundary wait-state reconstruction is implemented for parked
        # job-worker tasks only, and every attached boundary must itself be
        # collectable (an escaped signal boundary would open a subscription
        # the reconstruction doesn't count — so the host task escapes too)
        if _KERNEL_OP.get(el.element_type) != K_TASK:
            return "boundary-on-nontask"
        if not all(element_host_reason(exe, exe.elements[b]) is None
                   for b in el.boundary_idxs):
            return "boundary-unsupported"
    if el.element_type == BpmnElementType.SUB_PROCESS:
        # embedded sub-process with a none start rides the kernel (K_SCOPE);
        # attached event sub-processes would need host-side trigger state
        # the scope reconstruction does not collect yet
        if el.child_start_idx < 0:
            return "subprocess-no-none-start"
        if exe.event_sub_processes_of(el.idx):
            return "subprocess-event-subprocess"
        return None
    if el.element_type in (BpmnElementType.CALL_ACTIVITY,
                           BpmnElementType.PROCESS):
        # only synthetic inlined rows carry a child_start here (the call
        # activity scope and its child-root placeholder); a plain call
        # activity host-escapes (_inline_call_activities decides which)
        return None if el.child_start_idx >= 0 else "call-activity-unresolved"
    if el.element_type == BpmnElementType.EVENT_BASED_GATEWAY:
        # parks on device like a catch; every succeeding catch must hold a
        # wait state the reconstruction counts — fixed-duration timers,
        # message subscriptions, and signal subscriptions all count in
        # _collect_wait_states; cycle/date timers stay host-side
        for fidx in el.outgoing:
            target = exe.elements[exe.flows[fidx].target_idx]
            if target.timer_duration is not None:
                if target.timer_cycle or target.timer_date is not None:
                    return "timer-cycle-date"
            elif target.message_name is None and target.signal_name is None:
                return "event-gateway-target"
        return None if el.outgoing else "event-gateway-target"
    if (el.element_type == BpmnElementType.INTERMEDIATE_THROW_EVENT
            and el.event_type == BpmnEventType.LINK):
        # link throw rides the kernel as a K_PASS with a synthetic edge to
        # the resolved same-scope catch (tables.compile_tables link branch)
        return None if el.link_target_idx >= 0 else "link-unresolved"
    if el.element_type in (BpmnElementType.INTERMEDIATE_CATCH_EVENT,
                           BpmnElementType.RECEIVE_TASK):
        if el.event_type == BpmnEventType.LINK:
            # catch link: plain pass-through, no wait state to reconstruct
            return None
        # timer (fixed duration), message, and signal catches park on device
        # (K_CATCH); the host resumes them via TRIGGER / CORRELATE /
        # COMPLETE_ELEMENT commands
        if el.timer_duration is not None:
            if el.timer_cycle or el.timer_date is not None:
                return "timer-cycle-date"
            if el.message_name is not None or el.signal_name is not None:
                return "catch-unsupported"
            return None
        if el.timer_cycle is not None or el.timer_date is not None:
            # cycle/date-only timer catch: no reconstructable wait state
            return "timer-cycle-date"
        if el.message_name is not None or el.signal_name is not None:
            return None
        return "catch-unsupported"
    op = _KERNEL_OP.get(el.element_type)
    if op is None:
        return "unsupported-element"
    if el.event_type not in (BpmnEventType.NONE, BpmnEventType.UNSPECIFIED):
        return "event-type-unsupported"
    if (
        el.timer_duration is not None
        or el.timer_cycle is not None
        or el.timer_date is not None
        or el.message_name is not None
        or el.signal_name is not None
    ):
        return "event-type-unsupported"
    if op == K_TASK:
        # job-worker semantics only, with deploy-time-constant type/retries
        if el.job_type is None or not el.job_type.is_static:
            return "job-type-dynamic"
        if el.job_retries is not None and not el.job_retries.is_static:
            return "job-type-dynamic"
    return None


def esp_start_host_reason(start: ExecutableElement) -> str | None:
    """Definition-level gate on a ROOT event sub-process start event: only
    subscription shapes the kernel's root-wait-state reconstruction can
    count are admissible (the BODY still host-escapes either way). Shared
    by ``KernelRegistry._build_info`` and the classifier so the two can
    never disagree."""
    if (
        start.event_type in (BpmnEventType.ERROR, BpmnEventType.ESCALATION)
        or (start.event_type == BpmnEventType.TIMER
            and start.timer_duration is not None
            and start.timer_cycle is None
            and start.timer_date is None)
        or (start.event_type == BpmnEventType.MESSAGE and start.message_name)
        or (start.event_type == BpmnEventType.SIGNAL and start.signal_name)
    ):
        return None
    # cycle/date timer starts and every other shape: sequential end to end
    return "esp-start-unsupported"


# ---------------------------------------------------------------------------
# runtime path accounting — the one counter home


class PathAccounting:
    """Per-partition kernel-vs-host record accounting. Every fallback note
    and every kernel-routed command flows through here: the legacy
    ``fallback_reasons`` Counter (full strings, BENCH back-compat), the
    ``zeebe_kernel_records_total{path,reason}`` registry counter (bounded
    canonical labels), and the per-definition split behind the
    ``zeebe_kernel_coverage_ratio{definition}`` gauge and the parity gate.

    Recording is hot-path-adjacent (one note per routed head command, one
    per kernel group member): children are resolved lazily and cached, and
    per-definition tracking is bounded (overflow folds into ``other``)."""

    MAX_DEFINITIONS = 128

    def __init__(self, partition_id: int | str = 0) -> None:
        from zeebe_tpu.utils.metrics import REGISTRY

        self.partition = str(partition_id)
        #: reason string (full, incl. ``head-*:<kind>`` suffixes) → count;
        #: cleared by bench between measurement windows
        self.reasons: Counter = Counter()
        #: reason strings that failed catalog validation (a test asserts
        #: this stays empty — new reasons register in the catalog first)
        self.unregistered: Counter = Counter()
        self.kernel_records = 0
        self.host_records = 0
        # definition (bpmnProcessId) → [kernel, host, Counter(reasons)]
        self.per_definition: dict[str, list] = {}
        self._records_total = REGISTRY.counter(
            "kernel_records_total",
            "commands routed by the stream processor, by path and "
            "(host-path) catalog reason",
            ("partition", "path", "reason"))
        self._coverage = REGISTRY.gauge(
            "kernel_coverage_ratio",
            "records on the kernel path / total routed records, per "
            "definition (cumulative over the partition's life)",
            ("partition", "definition"))
        self._children: dict = {}
        self._kernel_child = self._records_total.labels(
            self.partition, "kernel", "-")

    def _def_slot(self, definition: str) -> list:
        slot = self.per_definition.get(definition)
        if slot is None:
            if len(self.per_definition) >= self.MAX_DEFINITIONS:
                definition = "other"
                slot = self.per_definition.get(definition)
                if slot is not None:
                    return slot
            # [kernel, host, host-reason Counter, cached gauge child] —
            # the child is resolved once per definition, not per note
            slot = self.per_definition[definition] = [
                0, 0, Counter(),
                self._coverage.labels(self.partition, definition)]
        return slot

    @staticmethod
    def _set_coverage(slot: list) -> None:
        total = slot[0] + slot[1]
        if total:
            slot[3].set(slot[0] / total)

    def note_kernel(self, definition: str, n: int = 1) -> None:
        """``n`` commands of ``definition`` rode the kernel path."""
        self.kernel_records += n
        self._kernel_child.inc(n)
        slot = self._def_slot(definition)
        slot[0] += n
        self._set_coverage(slot)

    def note_host(self, reason: str, definition: str = "-") -> None:
        """One head command took the host path for ``reason`` (a catalog
        code or a ``head-*:<kind>`` family member)."""
        self.reasons[reason] += 1
        label = canonical_reason(reason)
        if label is None:
            self.unregistered[reason] += 1
            label = "unregistered"
        self.host_records += 1
        child = self._children.get(label)
        if child is None:
            child = self._children[label] = self._records_total.labels(
                self.partition, "host", label)
        child.inc()
        slot = self._def_slot(definition)
        slot[1] += 1
        slot[2][reason] += 1
        self._set_coverage(slot)

    def coverage_ratio(self) -> float:
        total = self.kernel_records + self.host_records
        return self.kernel_records / total if total else 1.0

    def mark(self) -> dict:
        """Snapshot for windowed measurement (bench scenarios measure
        coverage over the driven window, not the warmup)."""
        return {
            "kernel": self.kernel_records,
            "host": self.host_records,
            "reasons": dict(self.reasons),
            "per_definition": {
                d: (s[0], s[1], dict(s[2]))
                for d, s in self.per_definition.items()
            },
        }

    def delta_since(self, mark: dict) -> dict:
        """Counts accumulated since ``mark`` — the shape
        ``parity_violations`` consumes (perDefinition rows)."""
        reasons = {
            r: c - mark["reasons"].get(r, 0)
            for r, c in self.reasons.items()
            if c > mark["reasons"].get(r, 0)
        }
        per_def: dict[str, dict] = {}
        for d, s in self.per_definition.items():
            mk, mh, mr = mark["per_definition"].get(d, (0, 0, {}))
            kernel, host = s[0] - mk, s[1] - mh
            if kernel or host:
                per_def[d] = {
                    "kernel": kernel, "host": host,
                    "hostReasons": {
                        r: c - mr.get(r, 0)
                        for r, c in s[2].items() if c > mr.get(r, 0)
                    },
                }
        return {
            "kernel": self.kernel_records - mark["kernel"],
            "host": self.host_records - mark["host"],
            "reasons": reasons,
            "perDefinition": per_def,
        }

    def snapshot(self) -> dict:
        """The ``kernelCoverage`` block served on partition ``/health`` and
        ``/cluster/status`` rows (and folded into BENCH extra)."""
        top = self.reasons.most_common(8)
        return {
            "kernelRecords": self.kernel_records,
            "hostRecords": self.host_records,
            "coverageRatio": round(self.coverage_ratio(), 4),
            "perDefinition": {
                d: {"kernel": s[0], "host": s[1],
                    "coverageRatio": round(
                        s[0] / (s[0] + s[1]), 4) if (s[0] + s[1]) else 1.0,
                    "hostReasons": dict(s[2])}
                for d, s in sorted(self.per_definition.items())
            },
            "topFallbackReasons": [
                {"reason": r, "count": c} for r, c in top],
        }


# ---------------------------------------------------------------------------
# the static eligibility report


def classify_definition(exe: ExecutableProcess, processes=None,
                        definition_key: int = -1, registry=None) -> dict:
    """Static eligibility report for one definition: runs the REAL
    ``KernelRegistry`` lookup (same inlining, same solo compile, same typed
    decline reasons admission will hit), then explains every host-forced
    row through the reason catalog. ``processes`` (the deployed
    ProcessState) is needed to resolve call activities; without it they
    honestly classify ``call-activity-unresolved``.

    Pass ONE ``registry`` (with unique ``definition_key``s) to classify a
    whole deployment set jointly — the prediction then sees exactly what
    runtime admission will: cross-definition SlotMap clashes in the shared
    compile and the registry's ``max_definitions`` capacity
    (``table-set-full``). Solo classification cannot predict either (the
    honest caveat in docs/eligibility.md)."""
    from zeebe_tpu.engine.kernel_backend import KernelRegistry

    reg = registry if registry is not None else KernelRegistry()
    key = definition_key if definition_key > 0 else 1
    info = reg.lookup(key, exe, processes=processes)
    report: dict[str, Any] = {
        "bpmnProcessId": exe.process_id,
        "definitionKey": definition_key,
        "runtimeOnlyReasons": sorted(RUNTIME_REASONS),
    }
    if info is None:
        # lookup returning None WITHOUT recording a decline reason is the
        # capacity path (len(_infos) >= max_definitions)
        reason = reg.decline_reason(key) or "table-set-full"
        report["eligible"] = False
        report["definitionReasons"] = [reason]
        report["elements"] = [
            {"id": el.id, "type": el.element_type.name, "path": "host",
             **({"reason": r} if (r := element_host_reason(exe, el)) else {})}
            for el in exe.elements[1:]
        ]
        report["counts"] = {"kernel": 0, "host": len(exe.elements) - 1}
        return report

    sx = info.exe  # synthetic (call activities + MI bodies inlined)
    esp_rows = _esp_subtree_rows(sx)
    elements = []
    kernel = host = 0
    for el in sx.elements[1:]:
        if el.idx in info.host_idxs:
            # own reason first; a reason-less host row inside an event
            # sub-process is a body element (the lowering escapes the whole
            # subtree); anything else was a compile downgrade
            reason = (element_host_reason(sx, el)
                      or ("event-subprocess-body" if el.idx in esp_rows
                          else "condition-not-compilable"))
            path = "host"
        elif el.idx in esp_rows:
            # individually eligible, but tokens only enter through the
            # host-routed event-sub-process start — effectively host
            reason = "event-subprocess-body"
            path = "host"
        else:
            reason = None
            path = "kernel"
        row = {"id": el.id, "type": el.element_type.name, "path": path}
        if reason:
            row["reason"] = reason
        elements.append(row)
        kernel += path == "kernel"
        host += path == "host"
    report["eligible"] = True
    report["definitionReasons"] = []
    report["elements"] = elements
    report["counts"] = {"kernel": kernel, "host": host}
    return report


def _esp_subtree_rows(exe: ExecutableProcess) -> set[int]:
    """Rows inside any event sub-process (the container, its start, its
    body): device-unreachable even when individually eligible."""
    containers = {el.idx for el in exe.elements
                  if el.element_type == BpmnElementType.EVENT_SUB_PROCESS}
    if not containers:
        return set()
    rows: set[int] = set()
    for el in exe.elements:
        idx, seen = el.idx, []
        while idx >= 0 and idx not in rows:
            if idx in containers:
                rows.update(seen, {idx})
                break
            seen.append(idx)
            idx = exe.elements[idx].parent_idx
        else:
            if idx >= 0:  # walked into an already-classified subtree
                rows.update(seen)
    return rows


# ---------------------------------------------------------------------------
# the static-vs-observed parity gate


def parity_violations(predictions: dict[str, bool],
                      observed: dict[str, dict]) -> list[str]:
    """Compare the classifier's per-definition prediction against observed
    routing (a ``PathAccounting.snapshot()['perDefinition']`` block). A
    definition the report calls kernel-eligible whose records routed
    host-side for a NON-runtime reason — or an ineligible one that rode
    the kernel — is a violation. Runtime-only reasons and ordinary
    ``head-sequential`` traffic (non-candidate command kinds) never count
    against the prediction."""
    violations: list[str] = []
    for definition, obs in sorted(observed.items()):
        predicted = predictions.get(definition)
        if predicted is None:
            continue  # unattributed ("-"/"other") or undeclared definition
        kernel, host = obs.get("kernel", 0), obs.get("host", 0)
        reasons = obs.get("hostReasons", {})
        static_host = {
            r: c for r, c in reasons.items()
            if (canonical_reason(r) or "unregistered") not in RUNTIME_REASONS
            and not r.startswith("head-sequential")
        }
        if predicted:
            if static_host:
                violations.append(
                    f"{definition}: predicted kernel-eligible but "
                    f"{sum(static_host.values())} record(s) host-routed for "
                    f"non-runtime reason(s) {static_host} "
                    f"(kernel={kernel}, host={host})")
        elif kernel > 0:
            violations.append(
                f"{definition}: predicted host-forced but {kernel} "
                f"record(s) rode the kernel path")
    return violations
