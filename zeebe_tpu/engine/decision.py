"""BPMN↔DMN integration: called decisions + standalone evaluation.

Reference: engine/…/processing/bpmn/behavior/BpmnDecisionBehavior.java
(business rule task with zeebe:calledDecision — evaluate at activation, write
the audit DECISION_EVALUATION event, set the result variable, raise
CALLED_DECISION_ERROR / DECISION_EVALUATION_ERROR incidents) and
engine/…/processing/dmn/DecisionEvaluationEvaluteProcessor (the gateway's
EvaluateDecision rpc)."""

from __future__ import annotations

from zeebe_tpu.dmn import DecisionEngine, DecisionEvaluationResult
from zeebe_tpu.engine.engine_state import EngineState
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import RejectionType, ValueType
from zeebe_tpu.protocol.enums import ErrorType
from zeebe_tpu.protocol.intent import DecisionEvaluationIntent

_ENGINE = DecisionEngine()


def evaluation_record_value(decision_meta: dict,
                            result: DecisionEvaluationResult) -> dict:
    """The DECISION_EVALUATION record shape (reference: protocol-impl
    DecisionEvaluationRecord — full audit trail)."""
    return {
        "decisionKey": decision_meta["decisionKey"],
        "decisionId": decision_meta["decisionId"],
        "decisionName": decision_meta["decisionName"],
        "decisionVersion": decision_meta["version"],
        "decisionRequirementsKey": decision_meta["decisionRequirementsKey"],
        "decisionRequirementsId": decision_meta["decisionRequirementsId"],
        "decisionOutput": result.output,
        "failedDecisionId": result.failed_decision_id,
        "evaluationFailureMessage": result.failure_message,
        "evaluatedDecisions": [
            {
                "decisionId": d.decision_id,
                "decisionName": d.decision_name,
                "decisionType": d.decision_type,
                "decisionOutput": d.output,
                "evaluatedInputs": [
                    {"inputId": i.input_id, "inputName": i.input_name,
                     "inputValue": i.input_value}
                    for i in d.evaluated_inputs
                ],
                "matchedRules": [
                    {"ruleId": r.rule_id, "ruleIndex": r.rule_index,
                     "evaluatedOutputs": [
                         {"outputId": o.output_id, "outputName": o.output_name,
                          "outputValue": o.output_value}
                         for o in r.evaluated_outputs
                     ]}
                    for r in d.matched_rules
                ],
            }
            for d in result.evaluated_decisions
        ],
    }


def _dmn_counter():
    """Registered at import (reference: ProcessEngineMetrics registers its
    collectors statically, not on first evaluation)."""
    from zeebe_tpu.utils.metrics import REGISTRY

    return REGISTRY.counter(
        "evaluated_dmn_elements_total", "DMN decisions evaluated by outcome",
        ("action",))


_DMN_COUNTER = _dmn_counter()


def evaluate_decision(state: EngineState, decision_meta: dict,
                      context: dict) -> DecisionEvaluationResult:
    counter = _DMN_COUNTER
    drg = state.decisions.parsed_drg(decision_meta["decisionRequirementsKey"])
    if drg is None:
        counter.labels("failed").inc()
        result = DecisionEvaluationResult()
        result.failed = True
        result.failed_decision_id = decision_meta["decisionId"]
        result.failure_message = (
            f"decision requirements {decision_meta['decisionRequirementsKey']} "
            "not found in state"
        )
        return result
    result = _ENGINE.evaluate(drg, decision_meta["decisionId"], context)
    counter.labels("failed" if result.failed else "evaluated").inc()
    return result


class BpmnDecisionBehavior:
    """Business rule task with zeebe:calledDecision."""

    def __init__(self, state: EngineState, raise_incident, write_variable) -> None:
        self.state = state
        self._raise_incident = raise_incident
        self._write_variable = write_variable

    def evaluate_called_decision(self, key: int, value: dict, element,
                                 writers: Writers) -> bool:
        """Returns True when evaluation succeeded and the result variable was
        written; False when an incident was raised (element stays ACTIVATING)."""
        from zeebe_tpu.protocol import DEFAULT_TENANT

        decision_meta = self.state.decisions.latest_decision_by_id(
            element.called_decision_id, value.get("tenantId", DEFAULT_TENANT)
        )
        if decision_meta is None:
            self._raise_incident(
                writers, key, value, ErrorType.CALLED_DECISION_ERROR,
                f"Expected to evaluate decision '{element.called_decision_id}', "
                "but no decision found for id",
            )
            return False
        context = self.state.variables.collect(key)
        result = evaluate_decision(self.state, decision_meta, context)
        eval_key = self.state.next_key()
        record_value = evaluation_record_value(decision_meta, result)
        record_value.update({
            "processInstanceKey": value.get("processInstanceKey", -1),
            "elementInstanceKey": key,
            "elementId": value.get("elementId", ""),
            "bpmnProcessId": value.get("bpmnProcessId", ""),
            "processDefinitionKey": value.get("processDefinitionKey", -1),
        })
        writers.append_event(
            eval_key, ValueType.DECISION_EVALUATION,
            DecisionEvaluationIntent.FAILED if result.failed
            else DecisionEvaluationIntent.EVALUATED,
            record_value,
        )
        if result.failed:
            self._raise_incident(
                writers, key, value, ErrorType.DECISION_EVALUATION_ERROR,
                result.failure_message,
            )
            return False
        if element.decision_result_variable:
            # result variable is local to the task scope; output mappings (or
            # the default merge) carry it outward (reference behavior)
            self._write_variable(
                writers, key, value, element.decision_result_variable,
                result.output,
            )
        return True


class DecisionEvaluationProcessor:
    """DECISION_EVALUATION EVALUATE command (gateway EvaluateDecision rpc)."""

    def __init__(self, state: EngineState) -> None:
        self.state = state

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        from zeebe_tpu.engine.processors import check_tenant_authorized
        from zeebe_tpu.protocol import DEFAULT_TENANT

        value = cmd.record.value
        decision_id = value.get("decisionId", "")
        decision_key = value.get("decisionKey", -1)
        tenant = value.get("tenantId") or DEFAULT_TENANT
        if not check_tenant_authorized(cmd, tenant, writers):
            return
        if decision_key > 0:
            decision_meta = self.state.decisions.decision_by_key(decision_key)
            if decision_meta is not None and \
                    decision_meta.get("tenantId", DEFAULT_TENANT) != tenant:
                decision_meta = None
        else:
            decision_meta = self.state.decisions.latest_decision_by_id(
                decision_id, tenant)
        if decision_meta is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to evaluate decision '{decision_id or decision_key}', "
                "but no decision found",
            )
            return
        result = evaluate_decision(
            self.state, decision_meta, dict(value.get("variables", {}))
        )
        eval_key = self.state.next_key()
        record = writers.append_event(
            eval_key, ValueType.DECISION_EVALUATION,
            DecisionEvaluationIntent.FAILED if result.failed
            else DecisionEvaluationIntent.EVALUATED,
            evaluation_record_value(decision_meta, result),
        )
        writers.respond(cmd, record)
