"""Workflow engine: BPMN semantics over the stream platform (SURVEY.md §2.8)."""

from zeebe_tpu.engine.engine import Engine
from zeebe_tpu.engine.engine_state import EngineState

__all__ = ["Engine", "EngineState"]
