"""Burst templates: run-time codegen of a command's full record burst.

The sequential materializer (kernel_backend's cascade + the head processors)
is a deterministic function of a small input vector: the keys it mints, the
command's correlation fields, the clock, and the instance-scoped state it
reads. For a given *route* through a definition (the device-step trace) and a
given byte-image of those state reads (the context fingerprint), its output —
the serialized log batch, the state write-set, the client responses — is
IDENTICAL up to substituting that input vector.

So we capture it once per (definition, kind, trace, fingerprint): run the slow
path with the inputs tagged (RoleInt) or registered by value (keys are unique
ints ≥ 2^51, so value-equality identifies them unambiguously — equal ints are
the same quantity), record where each input lands in the payload bytes / db
keys / value objects, and replay every later identical-shaped command by
patching a byte template — no Writers, no per-event appliers, no Record
objects. This is the same trick the reference plays with SBE codegen
(protocol/src/main/resources/protocol.xml): fixed layouts patched at
runtime; here the layouts are derived from the engine itself at first use.

Safety model:
- the cache key pins the route (trace) AND every instance-scoped document the
  slow path reads (fingerprint) — a command whose inputs differ in any
  non-role byte can never hit a template built for another;
- capture validates by re-instantiating with the capture inputs and requiring
  byte-equality with the slow path's own serialization;
- EngineHarness runs kernel backends in audit mode by default: every template
  hit ALSO runs the slow path and asserts payload/state/response equality, so
  the whole test suite (incl. the 120-process randomized parity suite)
  continuously cross-checks the codegen against the interpreter.

Reference seams: ProcessingStateMachine's writeRecords batch
(stream-platform/…/ProcessingStateMachine.java:495), SBE codegen
(protocol.xml), StateWriter lock-step apply (StateWriter.java:11).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Callable

from zeebe_tpu.protocol import msgpack
from zeebe_tpu.state.db import ColumnFamilyCode, _DELETED as _DB_DELETED
from zeebe_tpu.stream.api import activatable_job_types as _activatable_job_types

# record header layout (protocol/record.py _HEADER = "<BBBBqqqiqqH")
_REC_KEY_OFF = 4
_REC_SOURCE_OFF = 12
_REC_TS_OFF = 20
_REC_STREAM_OFF = 28
_REC_REQ_OFF = 32
_REC_OPREF_OFF = 40
_REC_REASON_LEN_OFF = 48
_REC_HEADER_SIZE = 50
_BATCH_HEADER = struct.Struct("<IqQ")
_ENTRY_HEADER = struct.Struct("<BqI")

_PACK_LE_Q = struct.Struct("<q")
_PACK_LE_I = struct.Struct("<i")
_PACK_BE_Q = struct.Struct(">Q")

_ROLE_VALUE_MIN = 1 << 32  # below this, only explicit RoleInt tagging counts


class RoleInt(int):
    """An int carrying its provenance ('which template input am I').

    (int subclasses cannot use nonempty __slots__, so instances carry a dict —
    they only exist transiently during capture/audit runs.)"""

    def __new__(cls, value: int, role: tuple):
        obj = super().__new__(cls, value)
        obj.role = role
        return obj


class _RoleSlot:
    """Sentinel standing in for a role inside a template value object."""

    __slots__ = ("role",)

    def __init__(self, role: tuple) -> None:
        self.role = role

    def __repr__(self) -> str:  # debugging clarity only
        return f"<role {self.role}>"


class NotTemplatable(Exception):
    pass


# ---------------------------------------------------------------------------
# role resolution


class Roles:
    """Capture-time role context: which template input does an int stand for.

    - ``role_map``: exact value → role. Keys (pi/tok/cmd/mint/wait), request
      ids, fingerprint-extracted document fields (("fp", i) — dueDate /
      deadline values read from admission docs, normalized out of the cache
      fingerprint and re-extracted per command at the same canonical
      position), and clock-note values (("clock", delta) — due dates the
      engine computed as clock + clock-free-duration during this capture,
      recorded by the ``clock_note`` hooks below).
    - ``allowed``: large ints the fingerprint pins byte-for-byte — they may
      appear as constants (the slow path copies them verbatim).

    There is deliberately NO range-based clock detection: an unexplained
    value near the clock could be an engine-computed quantity that is NOT
    clock + fixed-delta (e.g. a now()-entangled FEEL result), and patching
    it as one would silently corrupt later instantiations. Clock roles come
    only from provenance (the notes), everything else unexplained rejects.
    """

    __slots__ = ("role_map", "allowed")

    def __init__(self, role_map: dict[int, tuple],
                 allowed: frozenset[int] | set[int] = frozenset()) -> None:
        self.role_map = role_map
        self.allowed = allowed

    def of(self, v: Any) -> tuple | None:
        if isinstance(v, RoleInt):
            return v.role
        if not isinstance(v, int) or isinstance(v, bool) or v < _ROLE_VALUE_MIN:
            return None
        return self.role_map.get(int(v))


# ---------------------------------------------------------------------------
# clock-value provenance notes
#
# The engine's timer machinery computes clock-derived values (dueDate =
# clock + duration). During a template capture/audit run the kernel backend
# activates this collector; the computing site reports each value together
# with its clock-free delta — or poisons the run when the delta itself reads
# the clock (a now()-referencing duration expression), because such a value
# cannot be expressed as clock + constant. Inactive outside capture runs
# (plain attribute check), so the hot sequential path pays ~nothing.

import threading as _threading

_clock_notes = _threading.local()


def clock_note_begin() -> None:
    _clock_notes.items = []
    _clock_notes.poison = False


def clock_note_end() -> tuple[list[tuple[int, int]], bool]:
    items = getattr(_clock_notes, "items", None) or []
    poison = getattr(_clock_notes, "poison", False)
    _clock_notes.items = None
    _clock_notes.poison = False
    return items, poison


def note_clock_value(value: int, delta: int) -> None:
    """Report ``value = clock + delta`` with ``delta`` a pure function of
    the (fingerprint-pinned) variable context."""
    items = getattr(_clock_notes, "items", None)
    if items is not None:
        items.append((int(value), int(delta)))


def note_clock_poison() -> None:
    """Report a clock-derived value whose delta is NOT clock-free — the
    enclosing burst must not be templated."""
    if getattr(_clock_notes, "items", None) is not None:
        _clock_notes.poison = True


# ---------------------------------------------------------------------------
# msgpack serialization with role-offset tracking (mirrors msgpack._pack; the
# parity invariant is enforced by the capture-time byte-equality check against
# the slow path's own codec output)

_pack_f64 = struct.Struct(">d").pack
_pack_u16 = struct.Struct(">H").pack
_pack_u32 = struct.Struct(">I").pack
_pack_u64 = struct.Struct(">Q").pack
_pack_i8 = struct.Struct(">b").pack
_pack_i16 = struct.Struct(">h").pack
_pack_i32 = struct.Struct(">i").pack
_pack_i64 = struct.Struct(">q").pack


def _pack_with_roles(obj: Any, buf: bytearray, patches: list, roles: Roles,
                     unknown: list | None = None) -> None:
    role = roles.of(obj)
    if role is not None:
        v = int(obj)
        if not (0 <= v < 1 << 64) or v < _ROLE_VALUE_MIN:
            raise NotTemplatable(f"role int out of patchable range: {v}")
        buf.append(0xCF)
        patches.append((len(buf), "be_q", role))
        buf += _pack_u64(v)
        return
    if (unknown is not None and isinstance(obj, int) and not isinstance(obj, bool)
            and abs(obj) >= _ROLE_VALUE_MIN):
        unknown.append(int(obj))
    if obj is None:
        buf.append(0xC0)
    elif obj is True:
        buf.append(0xC3)
    elif obj is False:
        buf.append(0xC2)
    elif isinstance(obj, int):
        _pack_int_plain(obj, buf)
    elif isinstance(obj, float):
        buf.append(0xCB)
        buf += _pack_f64(obj)
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        n = len(raw)
        if n < 32:
            buf.append(0xA0 | n)
        elif n < 0x100:
            buf.append(0xD9)
            buf.append(n)
        elif n < 0x10000:
            buf.append(0xDA)
            buf += _pack_u16(n)
        else:
            buf.append(0xDB)
            buf += _pack_u32(n)
        buf += raw
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        n = len(raw)
        if n < 0x100:
            buf.append(0xC4)
            buf.append(n)
        elif n < 0x10000:
            buf.append(0xC5)
            buf += _pack_u16(n)
        else:
            buf.append(0xC6)
            buf += _pack_u32(n)
        buf += raw
    elif isinstance(obj, (list, tuple)):
        n = len(obj)
        if n < 16:
            buf.append(0x90 | n)
        elif n < 0x10000:
            buf.append(0xDC)
            buf += _pack_u16(n)
        else:
            buf.append(0xDD)
            buf += _pack_u32(n)
        for item in obj:
            _pack_with_roles(item, buf, patches, roles, unknown)
    elif isinstance(obj, dict):
        n = len(obj)
        if n < 16:
            buf.append(0x80 | n)
        elif n < 0x10000:
            buf.append(0xDE)
            buf += _pack_u16(n)
        else:
            buf.append(0xDF)
            buf += _pack_u32(n)
        for k, v in obj.items():
            _pack_with_roles(k, buf, patches, roles, unknown)
            _pack_with_roles(v, buf, patches, roles, unknown)
    else:
        raise NotTemplatable(f"cannot template msgpack type {type(obj).__name__}")


def _pack_int_plain(v: int, buf: bytearray) -> None:
    if v >= 0:
        if v < 0x80:
            buf.append(v)
        elif v < 0x100:
            buf.append(0xCC)
            buf.append(v)
        elif v < 0x10000:
            buf.append(0xCD)
            buf += _pack_u16(v)
        elif v < 0x100000000:
            buf.append(0xCE)
            buf += _pack_u32(v)
        else:
            buf.append(0xCF)
            buf += _pack_u64(v)
    else:
        if v >= -32:
            buf.append(v & 0xFF)
        elif v >= -0x80:
            buf.append(0xD0)
            buf += _pack_i8(v)
        elif v >= -0x8000:
            buf.append(0xD1)
            buf += _pack_i16(v)
        elif v >= -0x80000000:
            buf.append(0xD2)
            buf += _pack_i32(v)
        else:
            buf.append(0xD3)
            buf += _pack_i64(v)


# ---------------------------------------------------------------------------
# value-object templating (state writes, response record values)


def _templatize_value(obj: Any, roles: Roles, unknown: list | None = None):
    """Replace role ints with _RoleSlot sentinels; returns (template, n_roles)."""
    role = roles.of(obj)
    if role is not None:
        return _RoleSlot(role), 1
    if (unknown is not None and isinstance(obj, int) and not isinstance(obj, bool)
            and abs(obj) >= _ROLE_VALUE_MIN):
        unknown.append(int(obj))
    if isinstance(obj, dict):
        n = 0
        out = {}
        for k, v in obj.items():
            kt, nk = _templatize_value(k, roles, unknown)
            vt, nv = _templatize_value(v, roles, unknown)
            out[k if nk == 0 else kt] = vt
            n += nk + nv
        return out, n
    if isinstance(obj, (list, tuple)):
        items = []
        n = 0
        for v in obj:
            vt, nv = _templatize_value(v, roles, unknown)
            items.append(vt)
            n += nv
        return (items if isinstance(obj, list) else tuple(items)), n
    if isinstance(obj, RoleInt):  # small tagged int (request ids)
        return _RoleSlot(obj.role), 1
    return obj, 0


def _build_value(template: Any, resolve: Callable[[tuple], int]):
    """Instantiate a templatized value object."""
    if isinstance(template, _RoleSlot):
        return resolve(template.role)
    if isinstance(template, dict):
        return {
            (_build_value(k, resolve) if isinstance(k, _RoleSlot) else k): _build_value(v, resolve)
            for k, v in template.items()
        }
    if isinstance(template, list):
        return [_build_value(v, resolve) for v in template]
    if isinstance(template, tuple):
        return tuple(_build_value(v, resolve) for v in template)
    return template


# ---------------------------------------------------------------------------
# encoded-db-key templating (keys are self-describing: type-tagged parts)


def _templatize_db_key(enc: bytes, roles: Roles,
                       unknown: list | None = None) -> tuple[bytes, list]:
    """Parse an encoded state key; return (bytes, [(offset, role)]) patching
    int parts whose value is a role. Layout per state/db._encode_part:
    u16 cf | parts, each 0x01+BE-u64(sign-flipped) | 0x02+utf8+NUL |
    0x03+BE-u64-len+bytes."""
    patches = []
    off = 2
    n = len(enc)
    while off < n:
        tag = enc[off]
        off += 1
        if tag == 0x01:
            raw = _PACK_BE_Q.unpack_from(enc, off)[0]
            v = raw ^ 0x8000000000000000
            if v >= 1 << 63:
                v -= 1 << 64
            role = roles.of(v)
            if role is not None:
                patches.append((off, role))
            elif unknown is not None and abs(v) >= _ROLE_VALUE_MIN:
                unknown.append(v)
            off += 8
        elif tag == 0x02:
            end = enc.index(b"\x00", off)
            off = end + 1
        elif tag == 0x03:
            length = _PACK_BE_Q.unpack_from(enc, off)[0]
            off += 8 + length
        else:
            raise NotTemplatable(f"unknown key part tag 0x{tag:02x}")
    return enc, patches


# ---------------------------------------------------------------------------
# the template


@dataclass
class StateOp:
    op: str  # "put" | "del"
    key: bytes
    key_patches: list  # [(offset, role)]
    value_template: Any = None
    # fast value rebuild: when the value round-trips the codec exactly, it is
    # stored as msgpack bytes + patch offsets and rebuilt with one C unpack —
    # also guaranteeing a FRESH object per instantiation (the engine mutates
    # state values in place, so sharing a template object would corrupt
    # every instance that hit the template)
    value_bytes: bytes | None = None
    value_byte_patches: list = field(default_factory=list)

    def build_value(self, resolve: Callable[[tuple], int]):
        if self.value_bytes is not None:
            if self.value_byte_patches:
                buf = bytearray(self.value_bytes)
                for off, _fmt, role in self.value_byte_patches:
                    _PACK_BE_Q.pack_into(buf, off, resolve(role) & 0xFFFFFFFFFFFFFFFF)
                return msgpack.unpackb(bytes(buf))
            return msgpack.unpackb(self.value_bytes)
        return _build_value(self.value_template, resolve)


@dataclass
class ResponseTemplate:
    extra: bool  # False → with_response, True → add_response (await-result)
    header: dict  # field → constant or _RoleSlot
    value_template: Any = None
    stream_role: Any = None  # constant int or _RoleSlot
    req_role: Any = None


@dataclass
class PreparedBurst:
    """An instantiated template, ready for the writer: the payload needs only
    position/timestamp patching inside the append lock."""

    buf: bytearray
    pos_offsets: list[int]
    ts_offsets: list[int]
    count: int
    responses: list  # [(extra, Record, request_stream_id, request_id)]
    has_pending_commands: bool = False
    job_types: frozenset = frozenset()  # job types made activatable by the burst


_FMT_CODES = {"le_q": 0, "le_i": 1, "be_q": 2}
_PLAN_ENTRY = struct.Struct("<IBB")


from zeebe_tpu.native import codec_fn as _codec_fn

_apply_patches = _codec_fn("apply_patches")
_apply_state_plan = _codec_fn("apply_state_plan")
_STATE_PATCH = struct.Struct("<IB")


@dataclass
class BurstTemplate:
    """Everything needed to replay one command's burst by patching."""

    payload: bytes
    count: int  # records in the batch
    pos_offsets: list[int]  # entry-header position fields (first_position + i)
    ts_offsets: list[int]  # batch header + per-record timestamp fields
    role_patches: list  # [(offset, fmt, role)] fmt ∈ {"be_q","le_q","le_i"}
    mint_count: int
    state_ops: list[StateOp] = field(default_factory=list)
    responses: list[ResponseTemplate] = field(default_factory=list)
    has_pending_commands: bool = False
    job_types: frozenset = frozenset()
    # compiled payload patch plan (native apply_patches): entry bytes +
    # distinct role list; False = not compilable (fallback loop)
    _plan: Any = field(default=None, repr=False, compare=False)
    # compiled state-op plan (native apply_state_plan): per-op tuples +
    # distinct role list; False = not compilable (fallback loop)
    _state_plan: Any = field(default=None, repr=False, compare=False)
    # cached puts into the due-date index CFs (timer-wheel note_due replay)
    _due_ops: Any = field(default=None, repr=False, compare=False)
    # cached puts into the wait-state CFs (tiering note_parked replay)
    _park_ops: Any = field(default=None, repr=False, compare=False)

    def _compiled_plan(self):
        """(plan bytes, distinct roles) for the native patcher, or None.
        Each distinct role resolves ONCE per instantiation; the C pass
        applies every offset."""
        plan = self._plan
        if plan is None:
            role_idx: dict[tuple, int] = {}
            entries = bytearray()
            for off, fmt, role in self.role_patches:
                idx = role_idx.setdefault(role, len(role_idx))
                if idx > 0xFF or off > 0xFFFFFFFF:
                    self._plan = plan = False
                    break
                entries += _PLAN_ENTRY.pack(off, _FMT_CODES[fmt], idx)
            else:
                self._plan = plan = (bytes(entries), list(role_idx))
        return None if plan is False else plan

    def instantiate_payload(self, resolve: Callable[[tuple], int]) -> bytearray:
        buf = bytearray(self.payload)
        if _apply_patches is not None:
            plan = self._compiled_plan()
            if plan is not None:
                entries, roles = plan
                _apply_patches(buf, entries, [resolve(r) for r in roles])
                return buf
        for off, fmt, role in self.role_patches:
            v = resolve(role)
            if fmt == "be_q":
                _PACK_BE_Q.pack_into(buf, off, v & 0xFFFFFFFFFFFFFFFF)
            elif fmt == "le_q":
                _PACK_LE_Q.pack_into(buf, off, v)
            else:
                _PACK_LE_I.pack_into(buf, off, v)
        return buf

    def _compiled_state_plan(self):
        """(per-op tuples, distinct roles) for the native state applier, or
        None. Compilable iff every put carries codec-stable value bytes and
        role/offset widths fit the packed patch format. Each distinct role
        resolves ONCE per instantiation."""
        plan = self._state_plan
        if plan is None:
            role_idx: dict[tuple, int] = {}
            ops: list[tuple] = []

            def pack_patches(patches) -> bytes | None:
                out = bytearray()
                for entry in patches:
                    off, role = entry[0], entry[-1]
                    idx = role_idx.setdefault(role, len(role_idx))
                    if idx > 0xFF or off > 0xFFFFFFFF:
                        return None
                    out += _STATE_PATCH.pack(off, idx)
                return bytes(out)

            for op in self.state_ops:
                kp = pack_patches(op.key_patches)
                if kp is None:
                    ops = None
                    break
                if op.op != "put":
                    ops.append((0, op.key, kp, None, b""))
                    continue
                if op.value_bytes is None:
                    ops = None  # template-object value: python fallback
                    break
                vp = pack_patches(op.value_byte_patches)
                if vp is None:
                    ops = None
                    break
                ops.append((1, op.key, kp, op.value_bytes, vp))
            self._state_plan = plan = (
                False if ops is None else (ops, list(role_idx)))
        return None if plan is False else plan

    def _due_index_ops(self) -> list:
        """Puts into the due-date index CFs (timer due dates, message TTLs,
        job deadlines/backoff): the template applies raw encoded keys below
        the state facades, so the hierarchical timer wheel's ``note_due``
        seam must be replayed from the key bytes (ISSUE 8) — a missed due
        insert would be a timer that never fires."""
        ops = self._due_ops
        if ops is None:
            from zeebe_tpu.state import ColumnFamilyCode as _CF

            prefixes = {struct.pack(">H", int(cf)) for cf in (
                _CF.TIMER_DUE_DATES, _CF.MESSAGE_DEADLINES,
                _CF.JOB_DEADLINES, _CF.JOB_BACKOFF)}
            ops = [op for op in self.state_ops
                   if op.op == "put" and op.key[:2] in prefixes]
            self._due_ops = ops
        return ops

    def _park_index_ops(self) -> list:
        """Puts into the wait-state CFs (timers, jobs, message
        subscriptions): the tiering manager's ``note_parked`` seam must be
        replayed too, or template-cacheable park workloads (constant
        variables → near-1.0 template hit rates) would never produce spill
        candidates and RSS would grow unbounded with the parked backlog."""
        ops = self._park_ops
        if ops is None:
            from zeebe_tpu.state import ColumnFamilyCode as _CF

            prefixes = {struct.pack(">H", int(cf)) for cf in (
                _CF.TIMERS, _CF.JOBS, _CF.PROCESS_SUBSCRIPTION_BY_KEY)}
            ops = [op for op in self.state_ops
                   if op.op == "put" and op.key[:2] in prefixes]
            self._park_ops = ops
        return ops

    def _note_parks(self, txn, resolve: Callable[[tuple], int]) -> None:
        db = getattr(txn, "_db", None)
        if db is None or db.park_listener is None:
            return  # tiering off: zero cost beyond this check
        for op in self._park_index_ops():
            # the instance key lives in the record document; one small
            # unpack per park-op per instantiation, paid only with a
            # tiering manager wired
            val = op.build_value(resolve)
            if type(val) is dict:
                db.note_parked(val.get("processInstanceKey", -1))

    def _note_dues(self, txn, resolve: Callable[[tuple], int]) -> None:
        db = getattr(txn, "_db", None)
        if db is None or db.due_listener is None:
            return
        for op in self._due_index_ops():
            # first key part = the due millis: tag byte at offset 2, flipped
            # big-endian i64 at 3..11 — patched when role-derived
            due = None
            for off, role in op.key_patches:
                if off == 3:
                    due = resolve(role)
                    break
            if due is None:
                flipped = _PACK_BE_Q.unpack_from(op.key, 3)[0]
                raw = flipped ^ 0x8000000000000000
                due = raw - (1 << 64) if raw >= (1 << 63) else raw
            db.note_due(due)

    def apply_state(self, txn, resolve: Callable[[tuple], int]) -> None:
        if (_apply_state_plan is not None and getattr(txn, "capture", True) is None
                and getattr(txn, "_writes", None) is not None):
            plan = self._compiled_state_plan()
            if plan is not None:
                ops, roles = plan
                _apply_state_plan(ops, [resolve(r) for r in roles],
                                  txn._writes, txn._sorted_writes, _DB_DELETED)
                self._note_dues(txn, resolve)
                self._note_parks(txn, resolve)
                return
        for op in self.state_ops:
            if op.key_patches:
                key = bytearray(op.key)
                for off, role in op.key_patches:
                    _PACK_BE_Q.pack_into(
                        key, off, (resolve(role) & 0xFFFFFFFFFFFFFFFF) ^ 0x8000000000000000
                    )
                key = bytes(key)
            else:
                key = op.key
            if op.op == "put":
                txn.put(key, op.build_value(resolve))
            else:
                txn.delete(key)
        self._note_dues(txn, resolve)
        self._note_parks(txn, resolve)

    def build_responses(self, resolve: Callable[[tuple], int]):
        from zeebe_tpu.protocol.record import Record

        out = []
        for rt in self.responses:
            fields = {
                k: (resolve(v.role) if isinstance(v, _RoleSlot) else v)
                for k, v in rt.header.items()
            }
            fields["value"] = _build_value(rt.value_template, resolve)
            rec = Record(**fields)
            stream = resolve(rt.stream_role.role) if isinstance(rt.stream_role, _RoleSlot) else rt.stream_role
            req = resolve(rt.req_role.role) if isinstance(rt.req_role, _RoleSlot) else rt.req_role
            out.append((rt.extra, rec, stream, req))
        return out


# ---------------------------------------------------------------------------
# capture


def build_template(
    builder,
    state_log: list,
    roles: Roles,
    mint_count: int,
    partition_id: int,
) -> BurstTemplate:
    """Build a BurstTemplate from one slow-path materialization: the result
    builder (records + responses) and the transaction's write capture log.
    Raises NotTemplatable when anything resists the role model.

    ``roles`` carries the full role context: exact value→role map (keys,
    mints, fingerprint-extracted fields), the fingerprint-pinned constants
    (``roles.allowed`` — large ints that may legitimately be baked in because
    the cache key's fingerprint pins them), and the capture clock base for
    clock-derived detection. Any other large non-role int is evidence of
    hidden variance the role model cannot express — baking it in would
    silently corrupt later instantiations, so the burst is rejected."""
    if builder.post_commit_tasks:
        raise NotTemplatable("post-commit tasks cannot be templated")
    unknown: list[int] = []

    # ---- payload: batch header + per-entry header + record frames ----------
    payload = bytearray(_BATCH_HEADER.pack(len(builder.follow_ups), -1, 0))
    pos_offsets: list[int] = []
    ts_offsets: list[int] = [12]  # batch header timestamp
    role_patches: list = [(4, "le_q", ("source_position",))]
    for fu in builder.follow_ups:
        rec = fu.record
        if rec.rejection_reason and len(rec.rejection_reason.encode("utf-8")) > 0xFFFF:
            raise NotTemplatable("oversized rejection reason")
        body = bytearray()
        body_patches: list = []
        _pack_with_roles(dict(rec.value), body, body_patches, roles, unknown)
        reason = rec.rejection_reason.encode("utf-8")
        entry_off = len(payload)
        rec_off = entry_off + _ENTRY_HEADER.size
        rec_len = _REC_HEADER_SIZE + len(reason) + 4 + len(body)
        payload += _ENTRY_HEADER.pack(1 if fu.processed else 0, 0, rec_len)
        pos_offsets.append(entry_off + 1)
        header = struct.pack(
            "<BBBBqqqiqqH",
            int(rec.record_type),
            int(rec.value_type),
            int(rec.intent),
            int(rec.rejection_type),
            int(rec.key),
            int(rec.source_record_position),
            0,  # timestamp patched at append
            int(rec.request_stream_id),
            int(rec.request_id),
            int(rec.operation_reference),
            len(reason),
        )
        payload += header
        # header field roles
        for value, off, fmt in (
            (rec.key, _REC_KEY_OFF, "le_q"),
            (rec.source_record_position, _REC_SOURCE_OFF, "le_q"),
            (rec.request_stream_id, _REC_STREAM_OFF, "le_i"),
            (rec.request_id, _REC_REQ_OFF, "le_q"),
            (rec.operation_reference, _REC_OPREF_OFF, "le_q"),
        ):
            role = roles.of(value)
            if role is not None:
                role_patches.append((rec_off + off, fmt, role))
            elif abs(int(value)) >= _ROLE_VALUE_MIN:
                unknown.append(int(value))
        ts_offsets.append(rec_off + _REC_TS_OFF)
        payload += reason
        payload += struct.pack("<I", len(body))
        body_base = len(payload)
        for boff, fmt, role in body_patches:
            role_patches.append((body_base + boff, fmt, role))
        payload += body

    # ---- state ops ---------------------------------------------------------
    # collapse to the final op per key: instantiation replays ops blindly
    # (no reads in between), so only the last write to each key matters —
    # slow-path bursts touch the same element-instance row once per lifecycle
    # event, and replaying every intermediate version would dominate the fast
    # path
    final_ops: dict[bytes, tuple] = {}
    for op, enc_key, value in state_log:
        cf = struct.unpack_from(">H", enc_key, 0)[0]
        if cf == int(ColumnFamilyCode.KEY):
            continue  # replaced by the single bulk-mint write at instantiation
        if enc_key in final_ops:
            del final_ops[enc_key]  # re-insert to keep last-write order
        final_ops[enc_key] = (op, value)
    state_ops: list[StateOp] = []
    for enc_key, (op, value) in final_ops.items():
        key_bytes, key_patches = _templatize_db_key(enc_key, roles, unknown)
        if op != "put":
            state_ops.append(StateOp("del", key_bytes, key_patches))
            continue
        entry = StateOp("put", key_bytes, key_patches)
        # prefer the bytes rebuild when the value survives the codec exactly
        try:
            vbuf = bytearray()
            vpatches: list = []
            _pack_with_roles(value, vbuf, vpatches, roles, unknown)
            if msgpack.unpackb(bytes(vbuf)) == value:
                entry.value_bytes = bytes(vbuf)
                entry.value_byte_patches = vpatches
            else:
                raise NotTemplatable("value not codec-stable")
        except (NotTemplatable, msgpack.MsgPackError):
            vt, _n = _templatize_value(value, roles, unknown)
            entry.value_template = vt
        state_ops.append(entry)

    # ---- responses ---------------------------------------------------------
    responses: list[ResponseTemplate] = []
    all_responses = ([] if builder.response is None else [(False, builder.response)]) + [
        (True, r) for r in builder.extra_responses
    ]
    # replicated-dedupe parity guard (ISSUE 9): the live burst path notes
    # dedupe entries from `responses` while replay notes them from the
    # logged frames — a request-carrying follow-up frame that is NOT a
    # registered response would make the two diverge. Such steps (none in
    # the engine today) fall back to the slow path instead.
    response_records = {id(r.record) for _extra, r in all_responses}
    for fu in builder.follow_ups:
        rec = fu.record
        if (rec.request_id >= 0 and not rec.is_command
                and id(rec) not in response_records):
            raise NotTemplatable(
                "request-carrying follow-up is not a registered response")
    for extra, resp in all_responses:
        rec = resp.record
        header: dict[str, Any] = {}
        for name in (
            "record_type", "value_type", "intent", "key", "position",
            "source_record_position", "timestamp", "partition_id",
            "rejection_type", "rejection_reason", "request_stream_id",
            "request_id", "operation_reference",
        ):
            v = getattr(rec, name)
            role = roles.of(v)
            header[name] = _RoleSlot(role) if role is not None else v
        vt, _ = _templatize_value(dict(rec.value), roles, unknown)
        stream_role = roles.of(resp.request_stream_id)
        req_role = roles.of(resp.request_id)
        responses.append(
            ResponseTemplate(
                extra=extra,
                header=header,
                value_template=vt,
                stream_role=(
                    _RoleSlot(stream_role) if stream_role is not None else int(resp.request_stream_id)
                ),
                req_role=_RoleSlot(req_role) if req_role is not None else int(resp.request_id),
            )
        )

    stray = [v for v in unknown if v not in roles.allowed]
    if stray:
        raise NotTemplatable(
            f"unexplained large ints (not roles, not fingerprint-pinned): {stray[:4]}"
        )

    return BurstTemplate(
        payload=bytes(payload),
        count=len(builder.follow_ups),
        pos_offsets=pos_offsets,
        ts_offsets=ts_offsets,
        role_patches=role_patches,
        mint_count=mint_count,
        state_ops=state_ops,
        responses=responses,
        has_pending_commands=any(
            f.record.is_command and not f.processed for f in builder.follow_ups
        ),
        job_types=frozenset(_activatable_job_types(builder.follow_ups)),
    )


def serialize_reference(builder, first_position: int, source_position: int, timestamp: int) -> bytes:
    """The slow path's own serialization of the builder (for capture-time
    byte-equality validation of a freshly built template)."""
    from zeebe_tpu.logstreams.log_stream import LogAppendEntry, _serialize_batch

    entries = [LogAppendEntry(f.record, f.processed) for f in builder.follow_ups]
    return _serialize_batch(entries, first_position, source_position, timestamp)


def validate_template(template: BurstTemplate, builder, resolve: Callable[[tuple], int]) -> None:
    """Instantiate with the capture inputs and require byte-equality with the
    slow path's serializer output for synthetic position/timestamp."""
    synth_pos, synth_src, synth_ts = 977_717, 977_713, 1_234_567_890_123

    def resolve_with_synth(role: tuple) -> int:
        if role == ("source_position",):
            return synth_src
        return resolve(role)

    buf = template.instantiate_payload(resolve_with_synth)
    for i, off in enumerate(template.pos_offsets):
        _PACK_LE_Q.pack_into(buf, off, synth_pos + i)
    for off in template.ts_offsets:
        _PACK_LE_Q.pack_into(buf, off, synth_ts)
    expected = serialize_reference(builder, synth_pos, synth_src, synth_ts)
    if bytes(buf) != expected:
        raise NotTemplatable("template instantiation does not reproduce the slow path bytes")
