"""Engine: the RecordProcessor implementation for one partition.

Reference: engine/src/main/java/io/camunda/zeebe/engine/Engine.java:40
(implements RecordProcessor; process :100 looks up a TypedRecordProcessor in
RecordProcessorMap by (RecordType, ValueType, Intent); replay :94 delegates to
EventApplier; banned-instance guard :126) and
processing/EngineProcessors.createEngineProcessors (EngineProcessors.java:61).
"""

from __future__ import annotations

from typing import Callable

from zeebe_tpu.engine.appliers import EventAppliers
from zeebe_tpu.engine.bpmn import BpmnProcessor
from zeebe_tpu.engine.engine_state import EngineState
from zeebe_tpu.engine.processors import (
    DeploymentProcessor,
    IncidentResolveProcessor,
    JobBatchProcessor,
    JobProcessors,
    ProcessInstanceCancelProcessor,
    ProcessInstanceCreationProcessor,
    VariableDocumentProcessor,
)
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import RejectionType, ValueType
from zeebe_tpu.protocol.intent import (
    CheckpointIntent,
    CommandDistributionIntent,
    ProcessInstanceMigrationIntent,
    ProcessInstanceModificationIntent,
    ResourceDeletionIntent,
    DecisionEvaluationIntent,
    DeploymentIntent,
    IncidentIntent,
    JobBatchIntent,
    JobIntent,
    MessageBatchIntent,
    MessageIntent,
    MessageSubscriptionIntent,
    ProcessInstanceBatchIntent,
    ProcessInstanceCreationIntent,
    ProcessInstanceIntent,
    ProcessMessageSubscriptionIntent,
    SignalIntent,
    TimerIntent,
    UserTaskIntent,
    VariableDocumentIntent,
)
from zeebe_tpu.state import ZbDb
from zeebe_tpu.stream import ProcessingResultBuilder, RecordProcessor


class _SenderProxy:
    """Late-bound InterPartitionCommandSender (wired once the log exists)."""

    def __init__(self) -> None:
        self.delegate = None

    def send_command(self, receiver_partition_id: int, record) -> None:
        if self.delegate is None:
            raise RuntimeError("inter-partition sender not wired")
        self.delegate.send_command(receiver_partition_id, record)


class Engine(RecordProcessor):
    def __init__(self, db: ZbDb, partition_id: int = 1,
                 clock_millis: Callable[[], int] | None = None,
                 partition_count: int = 1) -> None:
        self.state = EngineState(db, partition_id)
        self.appliers = EventAppliers(self.state)
        clock = clock_millis or (lambda: 0)
        self.clock_millis = clock
        self.partition_count = partition_count
        self.sender = _SenderProxy()

        from zeebe_tpu.engine.message_timer import (
            MessageProcessors,
            MessageSubscriptionProcessors,
            ProcessMessageSubscriptionProcessors,
            TimerProcessors,
        )

        from zeebe_tpu.engine.signal import SignalProcessors
        from zeebe_tpu.engine.distribution import (
            CommandDistributionAcknowledgeProcessor,
            CommandDistributionBehavior,
        )

        bpmn = BpmnProcessor(self.state, clock, sender=self.sender,
                             partition_count=partition_count)
        self.distribution_behavior = CommandDistributionBehavior(
            self.state, partition_count, self.sender, clock_millis=clock
        )
        distribution = self.distribution_behavior if partition_count > 1 else None
        deployment = DeploymentProcessor(self.state, clock, distribution=distribution)
        # transient await-result requests (CreateProcessInstanceWithResult):
        # in-memory by design — they die with the node, the client retries
        self.await_results: dict[int, tuple[int, int, list]] = {}
        creation = ProcessInstanceCreationProcessor(self.state, bpmn,
                                                    await_results=self.await_results)
        bpmn.on_root_completed = self._on_root_completed
        bpmn.on_root_terminated = self._on_root_terminated
        cancel = ProcessInstanceCancelProcessor(self.state)
        jobs = JobProcessors(self.state, clock, bpmn)
        job_batch = JobBatchProcessor(self.state, clock)
        incidents = IncidentResolveProcessor(self.state, bpmn)
        variables = VariableDocumentProcessor(self.state)
        timers = TimerProcessors(self.state, clock, bpmn)
        messages = MessageProcessors(self.state, clock, partition_count, self.sender)
        msg_subs = MessageSubscriptionProcessors(self.state, self.sender)
        pms = ProcessMessageSubscriptionProcessors(self.state, self.sender, partition_count,
                                                   bpmn=bpmn)
        signals = SignalProcessors(self.state, bpmn, distribution=distribution)
        dist_ack = CommandDistributionAcknowledgeProcessor(self.state)
        self.distribution_ack = dist_ack
        from zeebe_tpu.engine.decision import DecisionEvaluationProcessor

        decision_eval = DecisionEvaluationProcessor(self.state)
        from zeebe_tpu.engine.modification import (
            ProcessInstanceMigrationProcessor,
            ProcessInstanceModificationProcessor,
            ResourceDeletionProcessor,
        )

        from zeebe_tpu.engine.processors import ProcessInstanceBatchProcessor
        from zeebe_tpu.engine.user_task import UserTaskProcessors

        pi_batch = ProcessInstanceBatchProcessor(self.state, bpmn)
        user_tasks = UserTaskProcessors(self.state)
        modification = ProcessInstanceModificationProcessor(self.state, bpmn)
        migration = ProcessInstanceMigrationProcessor(self.state)
        resource_deletion = ResourceDeletionProcessor(self.state, distribution)
        from zeebe_tpu.backup.checkpoint import CheckpointProcessor

        self.checkpoint_state = self.state.checkpoints
        self.checkpoint = CheckpointProcessor(self.checkpoint_state)

        def _deployment_fully_distributed(wr, distribution_key, stored):
            wr.append_event(
                distribution_key, ValueType.DEPLOYMENT, DeploymentIntent.FULLY_DISTRIBUTED,
                stored.get("commandValue", {}),
            )

        dist_ack.on_finished(ValueType.DEPLOYMENT, _deployment_fully_distributed)
        self.bpmn = bpmn

        # the RecordProcessorMap: (ValueType, command intent) → handler
        self._processors: dict[tuple[ValueType, int], Callable[[LoggedRecord, Writers], None]] = {
            (ValueType.DEPLOYMENT, int(DeploymentIntent.CREATE)): deployment.process,
            (ValueType.PROCESS_INSTANCE_CREATION, int(ProcessInstanceCreationIntent.CREATE)): creation.process,
            (ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.ACTIVATE_ELEMENT)): bpmn.process,
            (ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.COMPLETE_ELEMENT)): bpmn.process,
            (ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.TERMINATE_ELEMENT)): bpmn.process,
            (ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.CANCEL)): cancel.process,
            (ValueType.JOB, int(JobIntent.COMPLETE)): jobs.complete,
            (ValueType.JOB, int(JobIntent.FAIL)): jobs.fail,
            (ValueType.JOB, int(JobIntent.UPDATE_RETRIES)): jobs.update_retries,
            (ValueType.JOB, int(JobIntent.TIME_OUT)): jobs.time_out,
            (ValueType.JOB, int(JobIntent.THROW_ERROR)): jobs.throw_error,
            (ValueType.JOB_BATCH, int(JobBatchIntent.ACTIVATE)): job_batch.process,
            (ValueType.INCIDENT, int(IncidentIntent.RESOLVE)): incidents.process,
            (ValueType.VARIABLE_DOCUMENT, int(VariableDocumentIntent.UPDATE)): variables.process,
            (ValueType.JOB, int(JobIntent.RECUR_AFTER_BACKOFF)): jobs.recur_after_backoff,
            (ValueType.JOB, int(JobIntent.YIELD)): jobs.yield_job,
            (ValueType.JOB, int(JobIntent.UPDATE_TIMEOUT)): jobs.update_timeout,
            (ValueType.TIMER, int(TimerIntent.TRIGGER)): timers.trigger,
            (ValueType.MESSAGE, int(MessageIntent.PUBLISH)): messages.publish,
            (ValueType.MESSAGE, int(MessageIntent.EXPIRE)): messages.expire,
            (ValueType.MESSAGE_BATCH, int(MessageBatchIntent.EXPIRE)): messages.expire_batch,
            (ValueType.MESSAGE_SUBSCRIPTION, int(MessageSubscriptionIntent.CREATE)): msg_subs.create,
            (ValueType.MESSAGE_SUBSCRIPTION, int(MessageSubscriptionIntent.CORRELATE)): msg_subs.correlate_ack,
            (ValueType.MESSAGE_SUBSCRIPTION, int(MessageSubscriptionIntent.DELETE)): msg_subs.delete,
            (ValueType.PROCESS_MESSAGE_SUBSCRIPTION, int(ProcessMessageSubscriptionIntent.CORRELATE)): pms.correlate,
            (ValueType.SIGNAL, int(SignalIntent.BROADCAST)): signals.broadcast,
            (ValueType.COMMAND_DISTRIBUTION, int(CommandDistributionIntent.ACKNOWLEDGE)): dist_ack.process,
            (ValueType.DECISION_EVALUATION, int(DecisionEvaluationIntent.EVALUATE)): decision_eval.process,
            (ValueType.CHECKPOINT, int(CheckpointIntent.CREATE)): self.checkpoint.process,
            (ValueType.PROCESS_INSTANCE_MODIFICATION, int(ProcessInstanceModificationIntent.MODIFY)): modification.process,
            (ValueType.PROCESS_INSTANCE_MIGRATION, int(ProcessInstanceMigrationIntent.MIGRATE)): migration.process,
            (ValueType.RESOURCE_DELETION, int(ResourceDeletionIntent.DELETE)): resource_deletion.process,
            (ValueType.PROCESS_INSTANCE_BATCH, int(ProcessInstanceBatchIntent.ACTIVATE)): pi_batch.activate,
            (ValueType.PROCESS_INSTANCE_BATCH, int(ProcessInstanceBatchIntent.TERMINATE)): pi_batch.terminate,
            (ValueType.USER_TASK, int(UserTaskIntent.COMPLETE)): user_tasks.complete,
            (ValueType.USER_TASK, int(UserTaskIntent.ASSIGN)): user_tasks.assign,
            (ValueType.USER_TASK, int(UserTaskIntent.CLAIM)): user_tasks.claim,
            (ValueType.USER_TASK, int(UserTaskIntent.UPDATE)): user_tasks.update,
        }
        self.state.load_key_generator()

    def _on_root_completed(self, key: int, value: dict, child_locals: dict,
                           writers) -> None:
        """Answer a parked CreateProcessInstanceWithResult request with the
        root scope's final variables (reference: ProcessProcessor →
        BpmnProcessResultSenderBehavior, ProcessInstanceResultIntent)."""
        parked = self.await_results.pop(key, None)
        if parked is None:
            return
        request_id, stream_id, fetch = parked
        variables = dict(child_locals)
        if fetch:
            variables = {k: v for k, v in variables.items() if k in fetch}
        from zeebe_tpu.protocol.intent import ProcessInstanceResultIntent

        result = writers.append_event(
            key, ValueType.PROCESS_INSTANCE_RESULT,
            ProcessInstanceResultIntent.COMPLETED,
            {**{k: value.get(k) for k in (
                "bpmnProcessId", "version", "processDefinitionKey",
                "processInstanceKey")},
             "variables": variables},
        )
        writers.respond_to(result, stream_id, request_id)

    def _on_root_terminated(self, key: int, value: dict, writers) -> None:
        """A canceled instance fails its parked await-result request fast
        instead of leaking it until the request times out."""
        parked = self.await_results.pop(key, None)
        if parked is None:
            return
        request_id, stream_id, _ = parked
        from zeebe_tpu.protocol import rejection
        from zeebe_tpu.protocol import command as _command
        from zeebe_tpu.protocol.intent import ProcessInstanceCreationIntent as _PIC

        rej = rejection(
            _command(ValueType.PROCESS_INSTANCE_CREATION, _PIC.CREATE,
                     {"processInstanceKey": key}),
            RejectionType.NOT_FOUND,
            f"process instance {key} was terminated before completing",
        )
        writers.respond_to(rej, stream_id, request_id)

    def wire_sender(self, sender) -> None:
        """Install the inter-partition command sender (loopback or cluster)."""
        self.sender.delegate = sender

    # -- RecordProcessor SPI -------------------------------------------------

    def accepts(self, value_type: ValueType) -> bool:
        return any(vt == value_type for vt, _ in self._processors)

    def process(self, record: LoggedRecord, result: ProcessingResultBuilder) -> None:
        writers = Writers(result, self.appliers)
        pi_key = record.record.value.get("processInstanceKey", -1) if record.record.value else -1
        if self.state.banned.is_banned(pi_key):
            return  # quarantined instance: drop silently (reference Engine:126)
        handler = self._processors.get((record.record.value_type, int(record.record.intent)))
        if handler is None:
            writers.respond_rejection(
                record, RejectionType.INVALID_ARGUMENT,
                f"no processor for {record.record.value_type.name} {record.record.intent.name}",
            )
            return
        handler(record, writers)

    def replay(self, record: LoggedRecord) -> None:
        self.appliers.apply(record.record)
