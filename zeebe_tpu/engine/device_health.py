"""Per-broker device health ladder: HEALTHY → SUSPECT → QUARANTINED →
(canary) → HEALTHY (ISSUE 15).

"Gray Failure" (Huang et al., HotOS'17) argues the dangerous accelerator
failure mode is *degraded-not-dead*: a device that still answers most
dispatches but wedges, errors, or silently corrupts some of them. The
kernel backend's containment (host re-execution of a failed group) and
detection (sampled shadow verification) layers report every observed
device fault here, and this ladder turns the fault stream into an audited
routing posture:

- **HEALTHY** — full kernel dispatch; shadow verification at the
  configured sample rate.
- **SUSPECT** — latched by the first fault (a dispatch exception, a
  watchdog-expired stall, or a shadow mismatch). Shadow sampling is
  boosted (``suspect_shadow_boost``), the kernel-routing controller reads
  the ``zeebe_device_health_state`` gauge and biases groups host-ward
  through its existing ``route_threshold_s`` actuator, and a quiet window
  (``suspect_clear_ms`` without a fault) steps back down to HEALTHY.
  ``quarantine_faults`` faults inside ``fault_window_ms`` escalate.
- **QUARANTINED** — no ordinary group rides the device: the backend
  host-routes every group (typed ``device-quarantined`` accounting).
  Every ``canary_interval_ms`` ONE canary group is dispatched under
  FORCED shadow verification — a known-answer probe whose answer is the
  host oracle's own result, so a wrong canary can never commit wrong
  bytes. ``canary_successes`` consecutive verified canaries re-prove the
  device and return to HEALTHY; any canary fault or mismatch resets the
  streak.

Every transition is a ``control_adjust``-style audited event
(controller ``device-health``, knob ``device.healthState``) plus a typed
``device_health`` flight event, a ``zeebe_device_*`` metric move, and —
under the device-chaos harness — a line in a JSONL evidence file the
offline gate joins against the injected-fault ledger.

Scope caveats (also in docs/device-faults.md): the ladder is per-BROKER
(one state for every partition in the process, matching the shared
router), per-process not per-chip, and it watches the *direct* dispatch
path — mesh dispatch has its own killable probe (PR 7).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass

from zeebe_tpu.utils.metrics import REGISTRY as _REG

logger = logging.getLogger("zeebe_tpu.device_health")

HEALTHY = "HEALTHY"
SUSPECT = "SUSPECT"
QUARANTINED = "QUARANTINED"

_STATE_VALUE = {HEALTHY: 0, SUSPECT: 1, QUARANTINED: 2}

# registered at import so the metrics-doc scenario and the sampler see the
# families before the first fault (the control-plane pattern)
_M_STATE = _REG.gauge(
    "device_health_state",
    "device health ladder state of this broker's kernel dispatch path "
    "(0=HEALTHY, 1=SUSPECT, 2=QUARANTINED)", ())
_M_FAULTS = _REG.counter(
    "device_faults_total",
    "device faults observed at the kernel dispatch seam, by kind "
    "(dispatch-error, wedge, shadow-mismatch, canary classes)", ("kind",))
_M_TRANSITIONS = _REG.counter(
    "device_health_transitions_total",
    "device health ladder transitions, by target state", ("to",))
_M_CANARY = _REG.counter(
    "device_canary_total",
    "quarantine canary dispatches, by outcome (verified / failed)",
    ("outcome",))
_M_SHADOW_CHECKS = _REG.counter(
    "device_shadow_checks_total",
    "kernel groups re-executed on the host oracle and compared "
    "byte-for-byte before commit", ())
_M_SHADOW_MISMATCH = _REG.counter(
    "device_shadow_mismatches_total",
    "shadow verifications whose device result diverged from the host "
    "oracle — the result was quarantined (host result committed)", ())
_M_HOST_REROUTES = _REG.counter(
    "device_host_reroutes_total",
    "pump passes whose group was host-routed because the device is "
    "QUARANTINED", ())

_M_STATE.set(0.0)


@dataclass
class DeviceDefenseCfg:
    """The device-defense knob surface, bound from ``ZEEBE_BROKER_DEVICE_*``
    (read once per process at ladder construction — the knobs shape a
    process-wide posture, not per-partition behavior)."""

    #: watchdog deadline per device dispatch/fetch; 0 disables. Only armed
    #: on real accelerators (pipelined chunks) or under the chaos plane —
    #: the plain host XLA path pays nothing.
    dispatch_timeout_ms: int = 45_000
    #: fraction of kernel groups shadow-verified on the host oracle
    shadow_sample_rate: float = 0.02
    #: shadow-rate multiplier while SUSPECT
    suspect_shadow_boost: float = 8.0
    #: faults inside fault_window_ms that escalate SUSPECT → QUARANTINED
    quarantine_faults: int = 3
    fault_window_ms: int = 60_000
    #: fault-free window that clears SUSPECT back to HEALTHY
    suspect_clear_ms: int = 30_000
    #: cadence of canary dispatches while QUARANTINED
    canary_interval_ms: int = 5_000
    #: consecutive verified canaries that re-prove the device
    canary_successes: int = 2
    #: deterministic shadow-sampling stream seed
    shadow_seed: int = 0


def defense_cfg_from_env(env=None) -> DeviceDefenseCfg:
    env = os.environ if env is None else env
    cfg = DeviceDefenseCfg()

    def _get(var, convert, current):
        raw = env.get(var)
        if not raw:
            return current
        try:
            return convert(raw)
        except ValueError:
            logger.error("ignoring malformed %s=%r", var, raw)
            return current

    cfg.dispatch_timeout_ms = _get(
        "ZEEBE_BROKER_DEVICE_DISPATCHTIMEOUTMS", int, cfg.dispatch_timeout_ms)
    cfg.shadow_sample_rate = _get(
        "ZEEBE_BROKER_DEVICE_SHADOWSAMPLERATE", float, cfg.shadow_sample_rate)
    cfg.suspect_shadow_boost = _get(
        "ZEEBE_BROKER_DEVICE_SUSPECTSHADOWBOOST", float,
        cfg.suspect_shadow_boost)
    cfg.quarantine_faults = _get(
        "ZEEBE_BROKER_DEVICE_QUARANTINEFAULTS", int, cfg.quarantine_faults)
    cfg.fault_window_ms = _get(
        "ZEEBE_BROKER_DEVICE_FAULTWINDOWMS", int, cfg.fault_window_ms)
    cfg.suspect_clear_ms = _get(
        "ZEEBE_BROKER_DEVICE_SUSPECTCLEARMS", int, cfg.suspect_clear_ms)
    cfg.canary_interval_ms = _get(
        "ZEEBE_BROKER_DEVICE_CANARYINTERVALMS", int, cfg.canary_interval_ms)
    cfg.canary_successes = _get(
        "ZEEBE_BROKER_DEVICE_CANARYSUCCESSES", int, cfg.canary_successes)
    cfg.shadow_seed = _get(
        "ZEEBE_BROKER_DEVICE_SHADOWSEED", int, cfg.shadow_seed)
    return cfg


class DeviceHealth:
    """The ladder. Thread-safe: kernel backends of several partitions (and
    their watchdog threads) report faults concurrently; transitions are
    serialized under one lock and audited outside it."""

    def __init__(self, cfg: DeviceDefenseCfg | None = None,
                 clock=time.time) -> None:
        self.cfg = cfg if cfg is not None else defense_cfg_from_env()
        self._clock = clock
        self._lock = threading.Lock()
        self.state = HEALTHY
        self.faults: dict[str, int] = {}
        self._fault_times: list[float] = []  # ms, bounded by window pruning
        self._last_fault_ms = 0.0
        self._canary_streak = 0
        self._last_canary_ms = 0.0
        self.shadow_checks = 0
        self.shadow_mismatches = 0
        self.host_reroutes = 0
        self.canary_attempts = 0
        self.canary_verified = 0
        #: bounded transition history (status surfaces render the tail)
        self.transitions: list[dict] = []
        #: (flight_recorder, partition_id) sink for audited events — wired
        #: by the broker partition that owns the flight recorder; process-
        #: wide ladder ⇒ one sink, last wiring wins (same-broker recorders
        #: share the ring anyway)
        self.flight_sink = None
        # JSONL evidence ledger (device-chaos harness only) — the shared
        # line-flushed discipline, one home with the chaos planes'
        from zeebe_tpu.testing.chaos_common import JsonlLedger

        self._evidence = JsonlLedger()

    @property
    def evidence_file(self) -> str | None:
        return self._evidence.path

    @evidence_file.setter
    def evidence_file(self, value: str | None) -> None:
        self._evidence.path = value

    # -- fault/clean stream (called by the kernel backend) -------------------

    def now_ms(self) -> float:
        return self._clock() * 1000.0

    def note_fault(self, kind: str, detail: str = "") -> None:
        """One observed device fault (containment or shadow mismatch).
        HEALTHY latches SUSPECT; enough faults in the window escalate to
        QUARANTINED."""
        now = self.now_ms()
        _M_FAULTS.labels(kind).inc()
        with self._lock:
            self.faults[kind] = self.faults.get(kind, 0) + 1
            self._last_fault_ms = now
            horizon = now - self.cfg.fault_window_ms
            self._fault_times = [t for t in self._fault_times if t >= horizon]
            self._fault_times.append(now)
            recent = len(self._fault_times)
            if self.state == HEALTHY:
                transition = (SUSPECT, f"device fault `{kind}`: {detail}"
                              if detail else f"device fault `{kind}`")
            elif (self.state == SUSPECT
                  and recent >= self.cfg.quarantine_faults):
                transition = (
                    QUARANTINED,
                    f"{recent} device faults inside "
                    f"{self.cfg.fault_window_ms}ms (latest `{kind}`): all "
                    f"groups host-side, canary re-proving begins")
            else:
                transition = None
            if transition is not None:
                event = self._transition_locked(*transition, now)
            else:
                event = None
        if self.flight_sink is not None:
            # typed per-fault flight evidence (rare by construction: the
            # ladder quarantines a noisy device after quarantine_faults)
            flight, partition_id = self.flight_sink
            flight.record(partition_id, "device_fault", faultKind=kind,
                          detail=detail, state=self.state)
        if event is not None:
            self._audit(event)

    def note_group_ok(self) -> None:
        """A kernel group committed cleanly. While SUSPECT, a fault-free
        ``suspect_clear_ms`` window steps back down to HEALTHY."""
        event = None
        with self._lock:
            if self.state != SUSPECT:
                return
            now = self.now_ms()
            if now - self._last_fault_ms >= self.cfg.suspect_clear_ms:
                event = self._transition_locked(
                    HEALTHY,
                    f"{self.cfg.suspect_clear_ms}ms fault-free under "
                    f"boosted shadow sampling", now)
        if event is not None:
            self._audit(event)

    # -- shadow accounting ---------------------------------------------------

    def note_shadow_check(self) -> None:
        _M_SHADOW_CHECKS.inc()
        with self._lock:
            self.shadow_checks += 1

    def note_shadow_mismatch(self, detail: str = "") -> None:
        _M_SHADOW_MISMATCH.inc()
        with self._lock:
            self.shadow_mismatches += 1
        self.note_fault("shadow-mismatch", detail)

    def note_host_reroute(self) -> None:
        _M_HOST_REROUTES.inc()
        with self._lock:
            self.host_reroutes += 1

    # -- quarantine canary ---------------------------------------------------

    def is_quarantined(self) -> bool:
        return self.state == QUARANTINED

    def canary_due(self) -> bool:
        """While QUARANTINED: claim the next canary slot (at most one per
        interval across every partition sharing the ladder)."""
        with self._lock:
            if self.state != QUARANTINED:
                return False
            now = self.now_ms()
            if now - self._last_canary_ms < self.cfg.canary_interval_ms:
                return False
            self._last_canary_ms = now
            return True

    def release_canary(self) -> None:
        """Un-claim a canary slot that never dispatched (the group declined
        admission — a non-admittable head or an empty candidate iterator):
        the next quarantined pass may probe immediately instead of waiting
        out a canary interval the device never saw."""
        with self._lock:
            self._last_canary_ms = 0.0

    def note_canary(self, verified: bool, detail: str = "") -> None:
        """Outcome of one canary dispatch (verified = dispatched clean AND
        shadow-matched the host oracle)."""
        _M_CANARY.labels("verified" if verified else "failed").inc()
        event = None
        with self._lock:
            self.canary_attempts += 1
            if not verified:
                self._canary_streak = 0
                return
            self.canary_verified += 1
            self._canary_streak += 1
            if (self.state == QUARANTINED
                    and self._canary_streak >= self.cfg.canary_successes):
                event = self._transition_locked(
                    HEALTHY,
                    f"{self._canary_streak} consecutive canary dispatches "
                    f"verified against the host oracle", self.now_ms())
                self._canary_streak = 0
                self._fault_times.clear()
        if event is not None:
            self._audit(event)

    # -- transitions + audit -------------------------------------------------

    def _transition_locked(self, to: str, reason: str, now_ms: float) -> dict:
        before = self.state
        self.state = to
        _M_STATE.set(float(_STATE_VALUE[to]))
        _M_TRANSITIONS.labels(to).inc()
        event = {"atMs": now_ms, "from": before, "to": to, "reason": reason,
                 "pid": os.getpid()}
        self.transitions.append(event)
        del self.transitions[:-32]
        logger.warning("device health %s -> %s: %s", before, to, reason)
        return event

    def _audit(self, event: dict) -> None:
        """The control_adjust-style audit record + evidence line for one
        transition — outside the ladder lock (the flight recorder takes its
        own lock; evidence IO must never serialize fault noting)."""
        from zeebe_tpu.control.audit import record_adjust

        flight, partition_id = (self.flight_sink
                                if self.flight_sink is not None else (None, 0))
        record_adjust(
            flight, partition_id, "device-health", "device.healthState",
            event["from"], event["to"], event["reason"],
            signals={"recentFaults": len(self._fault_times),
                     "shadowMismatches": self.shadow_mismatches})
        if flight is not None:
            flight.record(partition_id, "device_health", **event)
        self._evidence.append(event)

    # -- surfaces ------------------------------------------------------------

    def status(self) -> dict:
        """The ``device`` block on ``/health`` kernelCoverage and the
        compact ``/cluster/status`` row."""
        with self._lock:
            return {
                "state": self.state,
                "faults": dict(self.faults),
                "shadowChecks": self.shadow_checks,
                "shadowMismatches": self.shadow_mismatches,
                "hostReroutes": self.host_reroutes,
                "canaries": {"attempts": self.canary_attempts,
                             "verified": self.canary_verified},
                **({"lastTransition": self.transitions[-1]}
                   if self.transitions else {}),
            }


_shared: DeviceHealth | None = None
_shared_lock = threading.Lock()


def shared_device_health() -> DeviceHealth:
    """Process-wide ladder: every partition's kernel backend shares one
    device health state (matching the shared BackendRouter — the device is
    a per-process resource)."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = DeviceHealth()
        return _shared


def reset_shared_device_health() -> None:
    """Test seam: drop the process-wide ladder so a test that provoked
    SUSPECT/QUARANTINED cannot leak its posture into later tests."""
    global _shared
    with _shared_lock:
        _shared = None
        _M_STATE.set(0.0)
