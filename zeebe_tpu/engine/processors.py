"""Command processors outside the BPMN lifecycle core.

Reference: engine/…/processing/deployment/DeploymentCreateProcessor.java,
processinstance/CreateProcessInstanceProcessor.java:46 and
CancelProcessInstanceHandler, job/{JobBatchActivateProcessor.java:33,
JobCompleteProcessor, JobFailProcessor, JobThrowErrorProcessor,
JobTimeOutProcessor, JobUpdateRetriesProcessor, JobYieldProcessor,
DefaultJobCommandPreconditionGuard}, incident/ResolveIncidentProcessor,
variable/VariableBehavior (document updates).
"""

from __future__ import annotations

import hashlib

from zeebe_tpu.engine.bpmn import BpmnProcessor
from zeebe_tpu.engine.engine_state import (
    EI_ACTIVATED,
    EngineState,
    JOB_ACTIVATABLE,
    JOB_ACTIVATED,
    JOB_FAILED,
)
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.dmn import DmnParseError, parse_dmn_xml
from zeebe_tpu.models.bpmn import BpmnModelError, parse_bpmn_xml, transform
from zeebe_tpu.protocol import DEFAULT_TENANT, RejectionType, ValueType
from zeebe_tpu.protocol.enums import BpmnElementType, ErrorType
from zeebe_tpu.protocol.intent import (
    DeploymentIntent,
    IncidentIntent,
    JobBatchIntent,
    JobIntent,
    ProcessInstanceCreationIntent,
    ProcessInstanceIntent,
    ProcessIntent,
    VariableDocumentIntent,
    VariableIntent,
)


class FormParseError(ValueError):
    pass


def _parse_form(source: str) -> dict:
    """Parse a Camunda form resource (JSON document with an ``id``).
    Reference: deployment/transform/FormResourceTransformer — the engine
    stores the raw resource; only the id is structurally required."""
    import json

    try:
        doc = json.loads(source)
    except ValueError as exc:
        raise FormParseError(f"form resource is not valid JSON: {exc}") from exc
    if not isinstance(doc, dict) or not doc.get("id"):
        raise FormParseError("form resource must be a JSON object with an 'id'")
    return doc


def check_tenant_authorized(cmd: LoggedRecord, tenant: str, writers: Writers) -> bool:
    """TenantAuthorizationChecker: the gateway stamps the caller's authorized
    tenants into the command (reference: RecordMetadata authorization claims +
    engine multitenancy/TenantAuthorizationChecker); a command addressing a
    tenant outside that list is rejected as NOT_FOUND — unauthorized tenants'
    resources are invisible, not forbidden (8.4 semantics)."""
    authorized = cmd.record.value.get("authorizedTenants")
    if authorized and tenant not in authorized:
        writers.respond_rejection(
            cmd, RejectionType.NOT_FOUND,
            f"Expected to handle command for tenant '{tenant}', but the request "
            "is not authorized for that tenant",
        )
        return False
    return True


class DeploymentProcessor:
    """DEPLOYMENT CREATE: parse + validate resources, version processes, emit
    PROCESS CREATED per definition and DEPLOYMENT CREATED/FULLY_DISTRIBUTED,
    and (re)register message/timer start-event subscriptions."""

    def __init__(self, state: EngineState, clock_millis=None, distribution=None) -> None:
        self.state = state
        self.clock_millis = clock_millis or (lambda: 0)
        self.distribution = distribution  # CommandDistributionBehavior | None

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        if self.distribution is not None and self.distribution.is_distributed_command(cmd):
            self._process_distributed(cmd, writers)
            return
        value = cmd.record.value
        resources = value.get("resources", [])
        if not resources:
            writers.respond_rejection(cmd, RejectionType.INVALID_ARGUMENT, "no resources")
            return
        tenant = value.get("tenantId") or DEFAULT_TENANT
        if not check_tenant_authorized(cmd, tenant, writers):
            return

        processes_metadata = []
        try:
            parsed = []
            dmn_parsed = []
            form_parsed = []
            for res in resources:
                xml = res["resource"]
                # checksum over the resource bytes (reference: DigestGenerator
                # hashes the deployed resource, not the compiled form)
                checksum = hashlib.sha256(xml.encode("utf-8")).hexdigest()
                if res["resourceName"].endswith(".dmn"):
                    dmn_parsed.append(
                        (res["resourceName"], xml, parse_dmn_xml(xml), checksum)
                    )
                    continue
                if res["resourceName"].endswith(".form"):
                    form_parsed.append(
                        (res["resourceName"], xml, _parse_form(xml), checksum)
                    )
                    continue
                for model in parse_bpmn_xml(xml):
                    exe = transform(model)  # also rejects bad deployments
                    parsed.append((res["resourceName"], xml, model, checksum, exe))
        except (BpmnModelError, DmnParseError, FormParseError) as exc:
            writers.respond_rejection(cmd, RejectionType.INVALID_ARGUMENT, str(exc))
            return

        deployment_key = self.state.next_key()
        for resource_name, xml, model, checksum, exe in parsed:
            previous_digest = self.state.processes.latest_digest(model.process_id, tenant)
            previous_version = self.state.processes.latest_version(model.process_id, tenant)
            previous_key = (
                self.state.processes.get_key_by_id_version(
                    model.process_id, previous_version, tenant)
                if previous_version is not None else None
            )
            duplicate = previous_digest == checksum
            if duplicate:
                version = previous_version
                process_key = previous_key
            else:
                version = self.state.processes.next_version(model.process_id, tenant)
                process_key = self.state.next_key()
            meta = {
                "bpmnProcessId": model.process_id,
                "version": version,
                "processDefinitionKey": process_key,
                "resourceName": resource_name,
                "checksum": checksum,
                "duplicate": duplicate,
                # the default tenant's records stay byte-identical to the
                # pre-tenancy shape (and to the kernel backend's output):
                # tenantId appears only when it carries information
                **({"tenantId": tenant} if tenant != DEFAULT_TENANT else {}),
            }
            processes_metadata.append(meta)
            if not duplicate:
                writers.append_event(
                    process_key, ValueType.PROCESS, ProcessIntent.CREATED,
                    {**meta, "resource": xml},
                )
                self._register_start_subscriptions(
                    writers, exe, meta, previous_key
                )

        decisions_metadata, drg_metadata = self._deploy_dmn(dmn_parsed, writers, tenant)
        form_metadata = self._deploy_forms(form_parsed, tenant, writers)

        deployment_value = {
            "resources": [
                {"resourceName": r["resourceName"], "resource": r["resource"]} for r in resources
            ],
            "processesMetadata": processes_metadata,
            "decisionsMetadata": decisions_metadata,
            "decisionRequirementsMetadata": drg_metadata,
            "formMetadata": form_metadata,
            **({"tenantId": tenant} if tenant != DEFAULT_TENANT else {}),
        }
        created = writers.append_event(
            deployment_key, ValueType.DEPLOYMENT, DeploymentIntent.CREATED, deployment_value
        )
        writers.respond(cmd, created)
        distributing = (
            self.distribution is not None
            and self.distribution.distribute(
                writers, deployment_key, ValueType.DEPLOYMENT, DeploymentIntent.CREATE,
                deployment_value,
            )
        )
        if not distributing:
            # single-partition deployments are immediately fully distributed;
            # otherwise FULLY_DISTRIBUTED is written by the completion hook once
            # every partition ACKNOWLEDGEd (docs/generalized_distribution.md)
            writers.append_event(
                deployment_key, ValueType.DEPLOYMENT, DeploymentIntent.FULLY_DISTRIBUTED,
                deployment_value,
            )

    def _deploy_dmn(self, dmn_parsed, writers: Writers, tenant: str = DEFAULT_TENANT):
        """Version DRGs + decisions per tenant and write their CREATED events
        (reference: deployment/transform DmnResourceTransformer)."""
        from zeebe_tpu.protocol.intent import (
            DecisionIntent,
            DecisionRequirementsIntent,
        )

        tenant_field = {"tenantId": tenant} if tenant != DEFAULT_TENANT else {}
        decisions_metadata: list[dict] = []
        drg_metadata: list[dict] = []
        for resource_name, xml, drg, checksum in dmn_parsed:
            duplicate = self.state.decisions.latest_drg_digest(
                drg.drg_id, tenant) == checksum
            if duplicate:
                # idempotent redeploy still reports the existing keys/versions
                # (mirrors the BPMN duplicate path's metadata contract)
                existing = dict(self.state.decisions.latest_drg_meta(drg.drg_id, tenant))
                existing.pop("resource", None)
                drg_metadata.append({**existing, "duplicate": True})
                for meta in self.state.decisions.decisions_of_drg(
                        existing["decisionRequirementsKey"]):
                    decisions_metadata.append({**meta, "duplicate": True})
                continue
            version = self.state.decisions.latest_drg_version(drg.drg_id, tenant) + 1
            drg_key = self.state.next_key()
            drg_meta = {
                "decisionRequirementsId": drg.drg_id,
                "decisionRequirementsName": drg.name,
                "version": version,
                "decisionRequirementsKey": drg_key,
                "namespace": drg.namespace,
                "resourceName": resource_name,
                "checksum": checksum,
                **tenant_field,
            }
            drg_metadata.append(drg_meta)
            writers.append_event(
                drg_key, ValueType.DECISION_REQUIREMENTS,
                DecisionRequirementsIntent.CREATED,
                {**drg_meta, "resource": xml},
            )
            for decision in drg.decisions.values():
                decision_key = self.state.next_key()
                meta = {
                    "decisionId": decision.decision_id,
                    "decisionName": decision.name,
                    "version": version,
                    "decisionKey": decision_key,
                    "decisionRequirementsKey": drg_key,
                    "decisionRequirementsId": drg.drg_id,
                    **tenant_field,
                }
                decisions_metadata.append(meta)
                writers.append_event(
                    decision_key, ValueType.DECISION, DecisionIntent.CREATED, meta
                )
        return decisions_metadata, drg_metadata

    def _deploy_forms(self, form_parsed, tenant: str, writers: Writers) -> list[dict]:
        """Version forms per (tenant, formId) with digest dedup and write FORM
        CREATED events (reference: FormResourceTransformer + FormCreatedApplier)."""
        from zeebe_tpu.protocol.intent import FormIntent

        form_metadata: list[dict] = []
        for resource_name, source, doc, checksum in form_parsed:
            form_id = doc["id"]
            duplicate = self.state.forms.latest_digest(form_id, tenant) == checksum
            if duplicate:
                existing = self.state.forms.get_latest_by_id(form_id, tenant)
                meta = {k: existing[k] for k in
                        ("formId", "version", "formKey", "resourceName", "checksum")}
                form_metadata.append({**meta, "duplicate": True,
                                      **({"tenantId": tenant}
                                         if tenant != DEFAULT_TENANT else {})})
                continue
            version = self.state.forms.next_version(form_id, tenant)
            form_key = self.state.next_key()
            meta = {
                "formId": form_id,
                "version": version,
                "formKey": form_key,
                "resourceName": resource_name,
                "checksum": checksum,
                "duplicate": False,
                **({"tenantId": tenant} if tenant != DEFAULT_TENANT else {}),
            }
            form_metadata.append(meta)
            writers.append_event(
                form_key, ValueType.FORM, FormIntent.CREATED,
                {**meta, "resource": source},
            )
        return form_metadata

    def _process_distributed(self, cmd: LoggedRecord, writers: Writers) -> None:
        """Receiver side of deployment distribution: store the definitions under
        the origin-minted keys, open message/signal start subscriptions locally
        (timer start events run only on the deployment partition), then ack."""
        self.distribution.handle_distributed(
            cmd, writers, lambda: self._apply_distributed_deployment(cmd, writers)
        )

    def _apply_distributed_deployment(self, cmd: LoggedRecord, writers: Writers) -> None:
        value = cmd.record.value
        executables: dict[str, tuple[str, "object"]] = {}

        def parsed(process_id: str) -> tuple[str, "object"] | None:
            # parse lazily, each resource at most once: a no-op redeploy
            # (all metas duplicate/digest-matched) must not pay any parse cost
            if not executables:
                for res in value.get("resources", []):
                    # mirror the origin-side filter: only .bpmn resources are
                    # process models; .dmn XML would make parse_bpmn_xml raise
                    # and wedge redistribution in a retry loop
                    if res.get("resourceName", "").endswith(".dmn"):
                        continue
                    for model in parse_bpmn_xml(res["resource"]):
                        executables[model.process_id] = (res["resource"], transform(model))
            return executables.get(process_id)

        for meta in value.get("processesMetadata", []):
            if meta.get("duplicate"):
                continue
            tenant = meta.get("tenantId", DEFAULT_TENANT)
            # domain-level idempotence: a retry whose dedup marker was already
            # purged must not re-deploy (digest check, same as the origin path)
            if self.state.processes.latest_digest(
                    meta["bpmnProcessId"], tenant) == meta["checksum"]:
                continue
            entry = parsed(meta["bpmnProcessId"])
            if entry is None:
                continue
            xml, exe = entry
            previous_version = self.state.processes.latest_version(
                meta["bpmnProcessId"], tenant)
            previous_key = (
                self.state.processes.get_key_by_id_version(
                    meta["bpmnProcessId"], previous_version, tenant
                )
                if previous_version is not None else None
            )
            writers.append_event(
                meta["processDefinitionKey"], ValueType.PROCESS, ProcessIntent.CREATED,
                {**meta, "resource": xml},
            )
            self._register_start_subscriptions(
                writers, exe, meta, previous_key, include_timers=False
            )
        # DMN resources replicate under the origin-minted keys/versions
        from zeebe_tpu.protocol.intent import (
            DecisionIntent,
            DecisionRequirementsIntent,
        )

        resource_by_name = {
            r["resourceName"]: r["resource"] for r in value.get("resources", [])
        }
        for drg_meta in value.get("decisionRequirementsMetadata", []):
            if (self.state.decisions.latest_drg_digest(
                    drg_meta["decisionRequirementsId"],
                    drg_meta.get("tenantId", DEFAULT_TENANT))
                    == drg_meta["checksum"]):
                continue
            writers.append_event(
                drg_meta["decisionRequirementsKey"], ValueType.DECISION_REQUIREMENTS,
                DecisionRequirementsIntent.CREATED,
                {**drg_meta, "resource": resource_by_name.get(drg_meta["resourceName"], "")},
            )
        for meta in value.get("decisionsMetadata", []):
            if self.state.decisions.decision_by_key(meta["decisionKey"]) is not None:
                continue
            writers.append_event(
                meta["decisionKey"], ValueType.DECISION, DecisionIntent.CREATED, meta
            )
        # forms replicate under the origin-minted keys/versions
        from zeebe_tpu.protocol.intent import FormIntent

        for meta in value.get("formMetadata", []):
            if meta.get("duplicate"):
                continue
            if self.state.forms.get_by_key(meta["formKey"]) is not None:
                continue
            writers.append_event(
                meta["formKey"], ValueType.FORM, FormIntent.CREATED,
                {**meta, "resource": resource_by_name.get(meta["resourceName"], "")},
            )
        writers.append_event(
            cmd.record.key, ValueType.DEPLOYMENT, DeploymentIntent.DISTRIBUTED, value
        )


    def _register_start_subscriptions(self, writers, exe, meta, previous_key,
                                      include_timers=True):
        register_start_subscriptions(self.state, self.clock_millis, writers,
                                     exe, meta, previous_key, include_timers)


def register_start_subscriptions(state, clock_millis, writers, exe, meta,
                                 previous_key, include_timers=True):
        """Message/timer start events of the new latest version; the previous
        version's subscriptions are closed (reference: deployment transformer
        subscription lifecycle)."""
        from zeebe_tpu.protocol.enums import BpmnEventType
        from zeebe_tpu.protocol.intent import (
            MessageStartEventSubscriptionIntent,
            TimerIntent,
        )
        from zeebe_tpu.utils import parse_cycle, parse_duration_millis

        if previous_key is not None:
            # close the *previous* version's start subscriptions: whether they
            # must go depends on what the old version had, not the new one
            old_exe = state.processes.executable(previous_key)
            old_has_msg_start = old_exe is not None and any(
                el.element_type == BpmnElementType.START_EVENT
                and el.event_type == BpmnEventType.MESSAGE
                for el in old_exe.elements[1:]
            )
            if old_has_msg_start:
                writers.append_event(
                    state.next_key(), ValueType.MESSAGE_START_EVENT_SUBSCRIPTION,
                    MessageStartEventSubscriptionIntent.DELETED,
                    {"processDefinitionKey": previous_key, "bpmnProcessId": meta["bpmnProcessId"]},
                )
            for timer_key, timer in state.timers.start_timers_for_process(previous_key):
                writers.append_event(timer_key, ValueType.TIMER, TimerIntent.CANCELED, timer)
        from zeebe_tpu.protocol.intent import SignalSubscriptionIntent

        if previous_key is not None:
            _close_signal_start_subscriptions(state, writers, previous_key, meta)
        for el in exe.elements[1:]:
            # only ROOT-scope start events start new instances; event
            # sub-process starts subscribe at scope activation instead
            if el.element_type != BpmnElementType.START_EVENT or el.parent_idx != 0:
                continue
            if el.event_type == BpmnEventType.SIGNAL and el.signal_name:
                writers.append_event(
                    state.next_key(), ValueType.SIGNAL_SUBSCRIPTION,
                    SignalSubscriptionIntent.CREATED,
                    {
                        "signalName": el.signal_name,
                        "catchEventId": el.id,
                        "catchEventInstanceKey": -1,
                        "processDefinitionKey": meta["processDefinitionKey"],
                        "bpmnProcessId": meta["bpmnProcessId"],
                        "interrupting": True,
                        **({"tenantId": meta["tenantId"]}
                           if meta.get("tenantId", DEFAULT_TENANT) != DEFAULT_TENANT else {}),
                    },
                )
            elif el.event_type == BpmnEventType.MESSAGE and el.message_name:
                writers.append_event(
                    state.next_key(), ValueType.MESSAGE_START_EVENT_SUBSCRIPTION,
                    MessageStartEventSubscriptionIntent.CREATED,
                    {
                        "processDefinitionKey": meta["processDefinitionKey"],
                        "bpmnProcessId": meta["bpmnProcessId"],
                        "startEventId": el.id,
                        "messageName": el.message_name,
                        **({"tenantId": meta["tenantId"]}
                           if meta.get("tenantId", DEFAULT_TENANT) != DEFAULT_TENANT else {}),
                    },
                )
            elif el.event_type == BpmnEventType.TIMER and include_timers and (
                el.timer_cycle is not None or el.timer_date is not None
            ):
                from zeebe_tpu.engine.burst_templates import (
                    note_clock_poison,
                    note_clock_value,
                )

                if el.timer_cycle is not None:
                    # cycle expressions evaluate against an empty context at
                    # deploy time (no instance exists yet)
                    cycle_text = el.timer_cycle.evaluate({}, clock_millis)
                    reps, interval = parse_cycle(str(cycle_text))
                    due_date = clock_millis() + interval
                    if el.timer_cycle.references_clock():
                        note_clock_poison()
                    else:
                        note_clock_value(due_date, interval)
                else:
                    from zeebe_tpu.engine.bpmn import _eval_date_millis

                    reps, interval = 1, 0
                    due_date = _eval_date_millis(el.timer_date, {}, clock_millis)
                    if el.timer_date.references_clock():
                        note_clock_poison()
                writers.append_event(
                    state.next_key(), ValueType.TIMER, TimerIntent.CREATED,
                    {
                        "elementId": el.id,
                        "targetElementId": el.id,
                        "elementInstanceKey": -1,
                        "processInstanceKey": -1,
                        "processDefinitionKey": meta["processDefinitionKey"],
                        "dueDate": due_date,
                        "repetitions": reps,
                        "interval": interval,
                    },
                )


def _close_signal_start_subscriptions(state, writers, previous_key, meta):
    from zeebe_tpu.protocol.intent import SignalSubscriptionIntent

    for sub in state.signal_subscriptions.subscriptions_of(previous_key):
        if sub.get("catchEventInstanceKey", -1) < 0:
            writers.append_event(
                state.next_key(), ValueType.SIGNAL_SUBSCRIPTION,
                SignalSubscriptionIntent.DELETED, sub,
            )


class ProcessInstanceCreationProcessor:
    """PROCESS_INSTANCE_CREATION CREATE: resolve the definition, write CREATED,
    seed variables, and kick off activation of the process element."""

    def __init__(self, state: EngineState, bpmn: BpmnProcessor,
                 await_results: dict | None = None) -> None:
        self.state = state
        self.bpmn = bpmn
        # transient request state, NOT in the replicated db: an await-result
        # request dies with the broker, exactly like the reference's
        # AwaitProcessInstanceResultMetadata (the client retries)
        self.await_results = await_results if await_results is not None else {}

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        value = cmd.record.value
        bpmn_process_id = value.get("bpmnProcessId", "")
        definition_key = value.get("processDefinitionKey", -1)
        version = value.get("version", -1)
        tenant = value.get("tenantId") or DEFAULT_TENANT
        if not check_tenant_authorized(cmd, tenant, writers):
            return

        if definition_key > 0:
            meta = self.state.processes.get_by_key(definition_key)
            # a key look-up must not cross tenants (reference:
            # TenantAuthorizationChecker on CreateProcessInstance)
            if meta is not None and meta.get("tenantId", DEFAULT_TENANT) != tenant:
                meta = None
        elif version > 0:
            key = self.state.processes.get_key_by_id_version(bpmn_process_id, version, tenant)
            meta = None if key is None else self.state.processes.get_by_key(key)
        else:
            meta = self.state.processes.get_latest_by_id(bpmn_process_id, tenant)
        if meta is None or meta.get("deleted"):
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to find process definition with process ID '{bpmn_process_id}', "
                "but none found",
            )
            return

        process_instance_key = self.state.next_key()
        created_value = {
            "bpmnProcessId": meta["bpmnProcessId"],
            "version": meta["version"],
            "processDefinitionKey": meta["processDefinitionKey"],
            "processInstanceKey": process_instance_key,
            "variables": value.get("variables", {}),
            "startInstructions": value.get("startInstructions", []),
            **({"tenantId": tenant} if tenant != DEFAULT_TENANT else {}),
        }
        created = writers.append_event(
            process_instance_key, ValueType.PROCESS_INSTANCE_CREATION,
            ProcessInstanceCreationIntent.CREATED, created_value,
        )
        if value.get("awaitResult") and cmd.record.request_id >= 0:
            # response deferred until the instance completes (CreateWithResult)
            self.await_results[process_instance_key] = (
                cmd.record.request_id, cmd.record.request_stream_id,
                list(value.get("fetchVariables", [])),
            )
        else:
            writers.respond(cmd, created)

        pi_value = {
            "bpmnProcessId": meta["bpmnProcessId"],
            "version": meta["version"],
            "processDefinitionKey": meta["processDefinitionKey"],
            "processInstanceKey": process_instance_key,
            "elementId": meta["bpmnProcessId"],
            "flowScopeKey": -1,
            "bpmnElementType": BpmnElementType.PROCESS.name,
            "bpmnEventType": "UNSPECIFIED",
            **({"tenantId": tenant} if tenant != DEFAULT_TENANT else {}),
        }
        if value.get("startElementId"):
            pi_value["startElementId"] = value["startElementId"]
        writers.append_command(
            process_instance_key, ValueType.PROCESS_INSTANCE,
            ProcessInstanceIntent.ACTIVATE_ELEMENT, pi_value,
        )
        # seed variables as events *after* CREATED — they apply to the root
        # scope which exists once ELEMENT_ACTIVATING runs; Zeebe orders the
        # variable events before activation, with the scope key pre-assigned.
        for name, val in (value.get("variables") or {}).items():
            var_key = self.state.next_key()
            writers.append_event(
                var_key, ValueType.VARIABLE, VariableIntent.CREATED,
                {
                    "name": name,
                    "value": val,
                    "scopeKey": process_instance_key,
                    "processInstanceKey": process_instance_key,
                    "processDefinitionKey": meta["processDefinitionKey"],
                    "bpmnProcessId": meta["bpmnProcessId"],
                },
            )


class ProcessInstanceCancelProcessor:
    """PROCESS_INSTANCE CANCEL (key = process instance key)."""

    def __init__(self, state: EngineState) -> None:
        self.state = state

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        key = cmd.record.key
        instance = self.state.element_instances.get(key)
        if instance is None or instance["value"].get("flowScopeKey", -1) >= 0:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to cancel existing process instance with key {key}, but none found",
            )
            return
        writers.append_command(key, ValueType.PROCESS_INSTANCE,
                               ProcessInstanceIntent.TERMINATE_ELEMENT, {})
        writers.respond(cmd, cmd.record.replace())


class JobProcessors:
    """COMPLETE / FAIL / THROW_ERROR / TIME_OUT / UPDATE_RETRIES / CANCEL."""

    def __init__(self, state: EngineState, clock_millis, bpmn=None) -> None:
        self.state = state
        self.clock_millis = clock_millis
        self.bpmn = bpmn

    def _precondition(self, cmd: LoggedRecord, writers: Writers, expect_activated: bool = True):
        """DefaultJobCommandPreconditionGuard: job exists and is in a valid state."""
        key = cmd.record.key
        job = self.state.jobs.get(key)
        if job is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to find job with key {key}, but no such job was found",
            )
            return None
        return job

    def complete(self, cmd: LoggedRecord, writers: Writers) -> None:
        job = self._precondition(cmd, writers)
        if job is None:
            return
        key = cmd.record.key
        variables = cmd.record.value.get("variables", {}) or {}
        completed_value = {**job, "variables": variables}
        completed = writers.append_event(key, ValueType.JOB, JobIntent.COMPLETED, completed_value)
        writers.respond(cmd, completed)

        element_key = job.get("elementInstanceKey", -1)
        instance = self.state.element_instances.get(element_key)
        if instance is not None:
            # completion variables merge into the process instance scope
            # (reference default propagation), EXCEPT when the element has
            # output mappings or is a multi-instance inner instance — then the
            # variables merge into the element's local scope so the mappings /
            # outputElement can read them and parallel siblings don't collide
            # (reference: VariableBehavior.mergeDocument + MI docs)
            pi_key = job.get("processInstanceKey", -1)
            merge_local = False
            exe = self.state.processes.executable(job.get("processDefinitionKey", -1))
            if exe is not None and job.get("elementId", "") in exe.by_id:
                element = exe.element(job["elementId"])
                merge_local = bool(element.outputs) or element.multi_instance is not None
            for name, val in variables.items():
                if merge_local:
                    target_scope = element_key
                else:
                    target_scope = self.state.variables.find_scope_with(element_key, name) or pi_key
                var_key = self.state.next_key()
                exists = self.state.variables.has_local(target_scope, name)
                writers.append_event(
                    var_key, ValueType.VARIABLE,
                    VariableIntent.UPDATED if exists else VariableIntent.CREATED,
                    {
                        "name": name, "value": val, "scopeKey": target_scope,
                        "processInstanceKey": pi_key,
                        "processDefinitionKey": job.get("processDefinitionKey", -1),
                        "bpmnProcessId": job.get("bpmnProcessId", ""),
                    },
                )
            writers.append_command(
                element_key, ValueType.PROCESS_INSTANCE,
                ProcessInstanceIntent.COMPLETE_ELEMENT, {},
            )

    def fail(self, cmd: LoggedRecord, writers: Writers) -> None:
        job = self._precondition(cmd, writers)
        if job is None:
            return
        key = cmd.record.key
        retries = cmd.record.value.get("retries", 0)
        backoff = cmd.record.value.get("retryBackOff", 0)
        error_message = cmd.record.value.get("errorMessage", "")
        failed_value = {**job, "retries": retries, "errorMessage": error_message}
        if backoff > 0 and retries > 0:
            failed_value["retryBackoff"] = self.clock_millis() + backoff
        failed = writers.append_event(key, ValueType.JOB, JobIntent.FAILED, failed_value)
        writers.respond(cmd, failed)
        if retries <= 0:
            incident_key = self.state.next_key()
            writers.append_event(
                incident_key, ValueType.INCIDENT, IncidentIntent.CREATED,
                {
                    "errorType": ErrorType.JOB_NO_RETRIES.name,
                    "errorMessage": error_message or "No more retries left.",
                    "bpmnProcessId": job.get("bpmnProcessId", ""),
                    "processDefinitionKey": job.get("processDefinitionKey", -1),
                    "processInstanceKey": job.get("processInstanceKey", -1),
                    "elementId": job.get("elementId", ""),
                    "elementInstanceKey": job.get("elementInstanceKey", -1),
                    "jobKey": key,
                    "variableScopeKey": job.get("elementInstanceKey", -1),
                },
            )

    def update_retries(self, cmd: LoggedRecord, writers: Writers) -> None:
        job = self._precondition(cmd, writers)
        if job is None:
            return
        retries = cmd.record.value.get("retries", 0)
        if retries < 1:
            writers.respond_rejection(
                cmd, RejectionType.INVALID_ARGUMENT, f"retries must be >0, got {retries}"
            )
            return
        updated = writers.append_event(
            cmd.record.key, ValueType.JOB, JobIntent.RETRIES_UPDATED, {**job, "retries": retries}
        )
        writers.respond(cmd, updated)

    def recur_after_backoff(self, cmd: LoggedRecord, writers: Writers) -> None:
        job = self._precondition(cmd, writers)
        if job is None:
            return
        writers.append_event(
            cmd.record.key, ValueType.JOB, JobIntent.RECURRED_AFTER_BACKOFF,
            {**job, "recurAt": cmd.record.value.get("recurAt", -1)},
        )

    def yield_job(self, cmd: LoggedRecord, writers: Writers) -> None:
        """Job YIELD: a pushed job's client stream died before delivery; hand
        the job back to the activatable queue (reference: JobYieldProcessor,
        YieldingJobStreamErrorHandler)."""
        job = self._precondition(cmd, writers)
        if job is None:
            return
        if self.state.jobs.state_of(cmd.record.key) != JOB_ACTIVATED:
            writers.respond_rejection(cmd, RejectionType.INVALID_STATE, "job is not activated")
            return
        yielded = writers.append_event(cmd.record.key, ValueType.JOB, JobIntent.YIELDED, job)
        writers.respond(cmd, yielded)

    def update_timeout(self, cmd: LoggedRecord, writers: Writers) -> None:
        """UpdateJobTimeout: move an activated job's deadline (reference:
        JobUpdateTimeoutProcessor)."""
        job = self._precondition(cmd, writers)
        if job is None:
            return
        if self.state.jobs.state_of(cmd.record.key) != JOB_ACTIVATED:
            writers.respond_rejection(cmd, RejectionType.INVALID_STATE, "job is not activated")
            return
        timeout = cmd.record.value.get("timeout", 0)
        if timeout <= 0:
            writers.respond_rejection(
                cmd, RejectionType.INVALID_ARGUMENT, f"timeout must be >0, got {timeout}"
            )
            return
        deadline = self.clock_millis() + timeout
        updated = writers.append_event(
            cmd.record.key, ValueType.JOB, JobIntent.TIMEOUT_UPDATED,
            {**job, "deadline": deadline},
        )
        writers.respond(cmd, updated)

    def time_out(self, cmd: LoggedRecord, writers: Writers) -> None:
        job = self._precondition(cmd, writers)
        if job is None:
            return
        if self.state.jobs.state_of(cmd.record.key) != JOB_ACTIVATED:
            writers.respond_rejection(cmd, RejectionType.INVALID_STATE, "job is not activated")
            return
        writers.append_event(cmd.record.key, ValueType.JOB, JobIntent.TIMED_OUT, job)

    def throw_error(self, cmd: LoggedRecord, writers: Writers) -> None:
        """Reference: processing/job/JobThrowErrorProcessor — the job is
        consumed (ERROR_THROWN), then the error routes to the closest error
        boundary/event sub-process; unhandled → UNHANDLED_ERROR_EVENT incident
        whose resolution re-attempts the throw."""
        job = self._precondition(cmd, writers)
        if job is None:
            return
        error_code = cmd.record.value.get("errorCode", "")
        thrown = writers.append_event(
            cmd.record.key, ValueType.JOB, JobIntent.ERROR_THROWN,
            {**job, "errorCode": error_code,
             "errorMessage": cmd.record.value.get("errorMessage", "")},
        )
        writers.respond(cmd, thrown)
        element_key = job.get("elementInstanceKey", -1)
        if self.bpmn is not None and self.bpmn.throw_error_from(element_key, error_code, writers):
            return
        incident_key = self.state.next_key()
        writers.append_event(
            incident_key, ValueType.INCIDENT, IncidentIntent.CREATED,
            {
                "errorType": ErrorType.UNHANDLED_ERROR_EVENT.name,
                "errorMessage": f"An error was thrown with the code '{error_code}' "
                                "but not caught.",
                "bpmnProcessId": job.get("bpmnProcessId", ""),
                "processDefinitionKey": job.get("processDefinitionKey", -1),
                "processInstanceKey": job.get("processInstanceKey", -1),
                "elementId": job.get("elementId", ""),
                "elementInstanceKey": element_key,
                "jobKey": cmd.record.key,
                "variableScopeKey": element_key,
                "errorCode": error_code,
            },
        )


class JobBatchProcessor:
    """JOB_BATCH ACTIVATE: collect activatable jobs of a type with variables
    (reference: JobBatchActivateProcessor.java:33 + JobBatchCollector)."""

    def __init__(self, state: EngineState, clock_millis) -> None:
        self.state = state
        self.clock_millis = clock_millis

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        value = cmd.record.value
        job_type = value.get("type", "")
        worker = value.get("worker", "")
        timeout = value.get("timeout", 300_000)
        max_jobs = value.get("maxJobsToActivate", 32)
        if not job_type or timeout <= 0 or max_jobs <= 0:
            writers.respond_rejection(
                cmd, RejectionType.INVALID_ARGUMENT,
                f"Expected type, positive timeout and maxJobsToActivate "
                f"(got type={job_type!r} timeout={timeout} max={max_jobs})",
            )
            return
        deadline = self.clock_millis() + timeout
        # authorized-tenant restriction: absent/empty means default tenant
        # only (reference: JobBatchActivateProcessor authorized tenants)
        tenant_ids = value.get("tenantIds") or [DEFAULT_TENANT]
        keys = self.state.jobs.activatable_keys(job_type, max_jobs, tenant_ids)
        jobs = []
        for key in keys:
            job = dict(self.state.jobs.get(key))
            element_key = job.get("elementInstanceKey", -1)
            job["variables"] = self.state.variables.collect(element_key)
            job["worker"] = worker
            job["deadline"] = deadline
            jobs.append(job)
        batch_key = self.state.next_key()
        activated_value = {
            "type": job_type,
            "worker": worker,
            "timeout": timeout,
            "maxJobsToActivate": max_jobs,
            "jobKeys": keys,
            "jobs": jobs,
            "deadline": deadline,
            "truncated": False,
        }
        activated = writers.append_event(
            batch_key, ValueType.JOB_BATCH, JobBatchIntent.ACTIVATED, activated_value
        )
        writers.respond(cmd, activated)


class ProcessInstanceBatchProcessor:
    """PROCESS_INSTANCE_BATCH ACTIVATE / TERMINATE: chunk huge fan-outs and
    fan-ins so no single processing step writes an unbounded record batch
    (reference: processinstance/ActivateProcessInstanceBatchProcessor.java,
    TerminateProcessInstanceBatchProcessor.java; SURVEY §5.7)."""

    def __init__(self, state: EngineState, bpmn: BpmnProcessor) -> None:
        self.state = state
        self.bpmn = bpmn

    def activate(self, cmd: LoggedRecord, writers: Writers) -> None:
        from zeebe_tpu.engine.bpmn import PI_BATCH_CHUNK
        from zeebe_tpu.engine.engine_state import EI_ACTIVATED, EI_ACTIVATING
        from zeebe_tpu.protocol.intent import ProcessInstanceBatchIntent

        value = cmd.record.value
        body_key = value.get("batchElementInstanceKey", -1)
        index = value.get("index", 0)
        body = self.state.element_instances.get(body_key)
        if body is None or body["state"] not in (EI_ACTIVATING, EI_ACTIVATED):
            return  # body gone (terminated meanwhile): drop the chain
        body_value = body["value"]
        exe = self.state.processes.executable(body_value["processDefinitionKey"])
        element = exe.element(body_value["elementId"])
        # the collection is re-evaluated per chunk; mutating it mid-loop is
        # documented-unsupported (same stance as sequential multi-instance).
        # The total is pinned from the FIRST chunk and the index only ever
        # advances, so a mutated collection can mis-pick items but can never
        # rewind progress or complete the body while chunks are outstanding.
        items = self.bpmn._eval_input_collection(body_key, body_value, element, writers)
        if items is None:
            return  # incident raised on the body
        total = body.get("miTotal") or len(items)
        end = max(index, min(index + PI_BATCH_CHUNK, len(items), total))
        for i in range(index, end):
            self.bpmn._write_mi_inner_activate(
                writers, body_key, body_value, element, items[i], i + 1
            )
        # a shrunken collection ends the chain here: report the REACHED count
        # as the final total so body completion is not gated on chunks that
        # will never be written (liveness over the pinned target)
        final_count = total if len(items) >= total else end
        writers.append_event(
            cmd.record.key, ValueType.PROCESS_INSTANCE_BATCH,
            ProcessInstanceBatchIntent.ACTIVATED,
            {"processInstanceKey": value.get("processInstanceKey", -1),
             "batchElementInstanceKey": body_key,
             "index": end, "count": final_count},
        )
        if end < min(total, len(items)):
            writers.append_command(
                self.state.next_key(), ValueType.PROCESS_INSTANCE_BATCH,
                ProcessInstanceBatchIntent.ACTIVATE,
                {"processInstanceKey": value.get("processInstanceKey", -1),
                 "batchElementInstanceKey": body_key, "index": end},
            )

    def terminate(self, cmd: LoggedRecord, writers: Writers) -> None:
        from zeebe_tpu.engine.bpmn import PI_BATCH_CHUNK
        from zeebe_tpu.engine.engine_state import EI_TERMINATED, EI_TERMINATING
        from zeebe_tpu.protocol.intent import ProcessInstanceBatchIntent

        value = cmd.record.value
        scope_key = value.get("batchElementInstanceKey", -1)
        scope = self.state.element_instances.get(scope_key)
        if scope is None:
            return  # scope finished terminating meanwhile
        pending = [
            k for k in self.state.element_instances.children_keys(scope_key)
            if self.state.element_instances.get(k)["state"]
            not in (EI_TERMINATING, EI_TERMINATED)
        ]
        for child_key in pending[:PI_BATCH_CHUNK]:
            writers.append_command(
                child_key, ValueType.PROCESS_INSTANCE,
                ProcessInstanceIntent.TERMINATE_ELEMENT, {},
            )
        writers.append_event(
            cmd.record.key, ValueType.PROCESS_INSTANCE_BATCH,
            ProcessInstanceBatchIntent.TERMINATED,
            {"processInstanceKey": value.get("processInstanceKey", -1),
             "batchElementInstanceKey": scope_key,
             "count": min(len(pending), PI_BATCH_CHUNK)},
        )
        if len(pending) > PI_BATCH_CHUNK:
            writers.append_command(
                self.state.next_key(), ValueType.PROCESS_INSTANCE_BATCH,
                ProcessInstanceBatchIntent.TERMINATE,
                {"processInstanceKey": value.get("processInstanceKey", -1),
                 "batchElementInstanceKey": scope_key},
            )


class IncidentResolveProcessor:
    """INCIDENT RESOLVE: drop the incident and retry the stalled work."""

    def __init__(self, state: EngineState, bpmn=None) -> None:
        self.state = state
        self.bpmn = bpmn

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        key = cmd.record.key
        incident = self.state.incidents.get(key)
        if incident is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to resolve incident with key {key}, but no such incident was found",
            )
            return
        resolved = writers.append_event(key, ValueType.INCIDENT, IncidentIntent.RESOLVED, incident)
        writers.respond(cmd, resolved)

        if (
            incident.get("errorType") == ErrorType.UNHANDLED_ERROR_EVENT.name
            and incident.get("jobKey", -1) >= 0
            and self.bpmn is not None
        ):
            # re-attempt the job's error throw (a catcher may exist now, e.g.
            # after process modification); still uncaught → fresh incident
            element_key = incident.get("elementInstanceKey", -1)
            error_code = incident.get("errorCode", "")
            if not self.bpmn.throw_error_from(element_key, error_code, writers):
                writers.append_event(
                    self.state.next_key(), ValueType.INCIDENT, IncidentIntent.CREATED,
                    {**incident,
                     "errorMessage": f"An error was thrown with the code '{error_code}' "
                                     "but not caught."},
                )
            return
        job_key = incident.get("jobKey", -1)
        if job_key >= 0:
            job = self.state.jobs.get(job_key)
            if job is not None and job.get("retries", 0) > 0:
                # worker updated retries; job becomes activatable again
                writers.append_event(
                    job_key, ValueType.JOB, JobIntent.RECURRED_AFTER_BACKOFF,
                    {**job, "recurAt": -1},
                )
            return
        element_key = incident.get("elementInstanceKey", -1)
        instance = self.state.element_instances.get(element_key)
        if instance is not None:
            # re-run the stalled transition: COMPLETING retries completion,
            # ACTIVATING retries activation
            from zeebe_tpu.engine.engine_state import EI_COMPLETING, EI_ACTIVATING

            if instance["state"] == EI_COMPLETING:
                writers.append_command(
                    element_key, ValueType.PROCESS_INSTANCE,
                    ProcessInstanceIntent.COMPLETE_ELEMENT, {},
                )
            elif instance["state"] == EI_ACTIVATING:
                writers.append_command(
                    element_key, ValueType.PROCESS_INSTANCE,
                    ProcessInstanceIntent.ACTIVATE_ELEMENT, instance["value"],
                )


class VariableDocumentProcessor:
    """VARIABLE_DOCUMENT UPDATE: merge a document into a scope (SetVariables)."""

    def __init__(self, state: EngineState) -> None:
        self.state = state

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        value = cmd.record.value
        scope_key = value.get("scopeKey", -1)
        instance = self.state.element_instances.get(scope_key)
        if instance is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to update variables for element with key {scope_key}, "
                "but no such element was found",
            )
            return
        local = value.get("local", False)
        pi_value = instance["value"]
        for name, val in (value.get("variables") or {}).items():
            if local:
                target_scope = scope_key
            else:
                target_scope = self.state.variables.find_scope_with(scope_key, name)
                if target_scope is None:
                    target_scope = pi_value.get("processInstanceKey", scope_key)
            exists = self.state.variables.has_local(target_scope, name)
            var_key = self.state.next_key()
            writers.append_event(
                var_key, ValueType.VARIABLE,
                VariableIntent.UPDATED if exists else VariableIntent.CREATED,
                {
                    "name": name, "value": val, "scopeKey": target_scope,
                    "processInstanceKey": pi_value.get("processInstanceKey", -1),
                    "processDefinitionKey": pi_value.get("processDefinitionKey", -1),
                    "bpmnProcessId": pi_value.get("bpmnProcessId", ""),
                },
            )
        doc_key = self.state.next_key()
        updated = writers.append_event(
            doc_key, ValueType.VARIABLE_DOCUMENT, VariableDocumentIntent.UPDATED, value
        )
        writers.respond(cmd, updated)
