"""Hierarchical timer wheel: O(1) due-date scheduling at a million parked
timers (ISSUE 8).

Reference shape: Varghese & Lauck hashed hierarchical timing wheels — the
structure behind Kafka's purgatory and Netty's HashedWheelTimer. The engine's
due-date machinery (timers, message TTLs, job deadlines, job retry backoff)
previously derived "when is the next sweep?" by scanning four sorted state
indexes after every processing batch — each scan materialized the WHOLE
index, so a broker parking a million timers paid O(parked) per batch for the
privilege of learning that nothing is due for an hour.

The wheel is a **physical scheduling cache**, not state:

- it lives outside the column-family store, is rebuilt from the due-date
  indexes on every partition transition (one O(parked) pass at recovery,
  where recovery is already O(state)), and is fed afterwards by the
  ``ZbDb.note_due`` seam the state facades call on every deadline insert —
  on BOTH processing and replay, so a follower's wheel is warm at takeover;
- it only **over-approximates**: entries are never removed on cancel
  (a canceled timer costs one empty sweep when its slot comes due), and a
  rolled-back transaction's insert stays as a stale entry — the sweep
  re-verifies against the sorted state indexes (now range-bounded, O(due)),
  which remain the single source of truth;
- consequently it can never fire LATE: every real deadline was inserted
  through the seam or the rebuild scan, and ``next_due`` returns a time at
  or before the earliest real deadline.

Sweep cost is therefore O(due) and the next-due probe O(levels × slots)
(constant), independent of the parked backlog — the property the scale soak
gate measures (1k vs 100k parked timers within 2× per-sweep wall time).
"""

from __future__ import annotations

import heapq
from typing import Callable

from zeebe_tpu.utils.metrics import REGISTRY as _REG

_M_SCHEDULED = _REG.counter(
    "timer_wheel_scheduled_total",
    "deadline entries inserted into the hierarchical timer wheel",
    ("partition",))
_M_ENTRIES = _REG.gauge(
    "timer_wheel_entries",
    "deadline entries currently resident in the wheel (incl. lazy-canceled)",
    ("partition",))


class HierarchicalTimerWheel:
    """Multi-level circular timing wheel over absolute millisecond deadlines.

    ``levels`` rings of ``slots`` buckets each; level ``l`` buckets are
    ``tick_ms * slots**l`` wide, so the default (64ms × 64 slots × 4 levels)
    spans ~4.1s / ~4.4min / ~4.7h / ~12.4d; deadlines beyond the top span
    wait in an overflow heap and promote into the rings as time approaches.

    Only two mutations exist: ``schedule(due_ms)`` and ``advance(now_ms)``
    (drop passed deadlines, cascade entered higher-level buckets downward).
    ``next_due(now_ms)`` is a pure query. Entries are bare timestamps — the
    wheel schedules *sweeps*, the state indexes say what is actually due.
    """

    __slots__ = ("tick_ms", "slots", "levels", "_width", "_span",
                 "_slots", "_mins", "_overflow", "_now", "_count")

    def __init__(self, now_ms: int, tick_ms: int = 64, slots: int = 64,
                 levels: int = 4) -> None:
        self.tick_ms = max(1, int(tick_ms))
        self.slots = max(2, int(slots))
        self.levels = max(1, int(levels))
        self._width = [self.tick_ms * self.slots ** l
                       for l in range(self.levels)]
        self._span = [w * self.slots for w in self._width]
        self._slots: list[list[list[int]]] = [
            [[] for _ in range(self.slots)] for _ in range(self.levels)]
        # per-slot cached minimum (None = empty): next_due never scans a
        # 100k-entry storm bucket
        self._mins: list[list[int | None]] = [
            [None] * self.slots for _ in range(self.levels)]
        self._overflow: list[int] = []  # min-heap of far-future deadlines
        self._now = int(now_ms)
        self._count = 0

    def __len__(self) -> int:
        return self._count + len(self._overflow)

    # -- mutations -------------------------------------------------------------

    def schedule(self, due_ms: int) -> None:
        due_ms = int(due_ms)
        now = self._now
        delta = due_ms - now
        if delta >= self._span[-1]:
            heapq.heappush(self._overflow, due_ms)
            return
        if delta <= 0:
            # already due: park in the CURRENT level-0 bucket so the next
            # advance reports it and next_due sees it immediately
            lvl, idx = 0, (now // self._width[0]) % self.slots
        else:
            lvl = 0
            while delta >= self._span[lvl]:
                lvl += 1
            idx = (due_ms // self._width[lvl]) % self.slots
        self._slots[lvl][idx].append(due_ms)
        cur_min = self._mins[lvl][idx]
        if cur_min is None or due_ms < cur_min:
            self._mins[lvl][idx] = due_ms
        self._count += 1

    def advance(self, now_ms: int) -> int:
        """Move wheel time forward: drop deadlines ≤ ``now_ms`` (the caller's
        sweep covers them), cascade entered higher-level buckets down into
        finer rings. Returns the number of deadlines dropped."""
        now_ms = int(now_ms)
        if now_ms < self._now:
            return 0
        prev = self._now
        self._now = now_ms
        fired = 0
        carry: list[int] = []  # deadlines to re-place at finer levels
        for lvl in range(self.levels):
            w = self._width[lvl]
            start, end = prev // w, now_ms // w
            if lvl > 0 and start == end:
                break  # this ring's cursor didn't move; neither did coarser
            # walk at most one lap — past that every bucket flushed anyway
            first = max(start, end - self.slots + 1)
            for b in range(first, end + 1):
                idx = b % self.slots
                bucket = self._slots[lvl][idx]
                if not bucket:
                    continue
                keep: list[int] = []
                for due in bucket:
                    if due <= now_ms:
                        fired += 1
                        self._count -= 1
                    elif lvl == 0 and b == end:
                        keep.append(due)  # current fine bucket, later ms
                    else:
                        # entered coarse bucket: redistribute downward
                        self._count -= 1
                        carry.append(due)
                self._slots[lvl][idx] = keep
                self._mins[lvl][idx] = min(keep) if keep else None
        for due in carry:
            self.schedule(due)
        # promote overflow deadlines that now fit the top ring
        horizon = now_ms + self._span[-1]
        overflow = self._overflow
        while overflow and overflow[0] < horizon:
            self.schedule(heapq.heappop(overflow))
        return fired

    # -- queries ---------------------------------------------------------------

    def next_due(self, now_ms: int | None = None) -> int | None:
        """Earliest resident deadline, or None. Never later than the true
        earliest (the wheel only over-approximates)."""
        best: int | None = None
        for lvl in range(self.levels):
            # min over every bucket's cached minimum — NOT first-non-empty
            # in ring order: a deadline almost a full lap ahead shares a slot
            # index with the cursor, and stopping at that slot would report
            # it over a nearer deadline in a later slot (lap aliasing)
            for m in self._mins[lvl]:
                if m is not None and (best is None or m < best):
                    best = m
        if self._overflow:
            top = self._overflow[0]
            if best is None or top < best:
                best = top
        return best


class DueDateWheel:
    """The engine-facing wheel: one ``HierarchicalTimerWheel`` covering all
    four deadline kinds (timers, message TTLs, job deadlines, job retry
    backoff), rebuilt from the sorted due-date indexes at construction and
    fed afterwards through ``ZbDb.note_due``."""

    def __init__(self, clock_millis: Callable[[], int], partition_id: int = 0,
                 tick_ms: int = 64, slots: int = 64, levels: int = 4) -> None:
        self.clock_millis = clock_millis
        self.partition_id = partition_id
        self.wheel = HierarchicalTimerWheel(
            clock_millis(), tick_ms=tick_ms, slots=slots, levels=levels)
        self._m_scheduled = _M_SCHEDULED.labels(str(partition_id))
        self._m_entries = _M_ENTRIES.labels(str(partition_id))

    # the ZbDb.note_due seam target — hot path, keep it one call deep
    def note_due(self, due_ms: int) -> None:
        self.wheel.schedule(due_ms)
        self._m_scheduled.inc()

    def rebuild(self, engine_state) -> int:
        """One pass over the four due-date indexes (committed keys only — no
        transaction, no value materialization): the recovery-time rebuild.
        O(parked) once per transition, where recovery is already O(state)."""
        from zeebe_tpu.engine.engine_state import _decode_two_i64
        from zeebe_tpu.state import ColumnFamilyCode as CF

        db = engine_state.db
        n = 0
        for cf in (CF.TIMER_DUE_DATES, CF.MESSAGE_DEADLINES,
                   CF.JOB_DEADLINES, CF.JOB_BACKOFF):
            for enc_key in db.committed_keys_of(cf):
                self.wheel.schedule(_decode_two_i64(enc_key)[0])
                n += 1
        self._m_entries.set(float(len(self.wheel)))
        return n

    def next_due(self) -> int | None:
        return self.wheel.next_due()

    def advance(self, now_ms: int) -> int:
        fired = self.wheel.advance(now_ms)
        self._m_entries.set(float(len(self.wheel)))
        return fired
