"""Signal broadcast processing.

Reference: engine/src/main/java/io/camunda/zeebe/engine/processing/signal/
SignalBroadcastProcessor.java — a broadcast triggers every matching signal
start event (new process instances) and every open signal subscription
(catch events, boundary events, event sub-process starts) on this partition.
Cross-partition distribution of broadcasts rides the command distribution
behavior (multi-partition wiring in zeebe_tpu.parallel).
"""

from __future__ import annotations

from zeebe_tpu.engine.engine_state import EngineState
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import ValueType
from zeebe_tpu.protocol.intent import (
    ProcessInstanceCreationIntent,
    SignalIntent,
    SignalSubscriptionIntent,
    VariableIntent,
)


class SignalProcessors:
    def __init__(self, state: EngineState, bpmn, distribution=None) -> None:
        self.state = state
        self.bpmn = bpmn
        self.distribution = distribution  # CommandDistributionBehavior | None

    def broadcast(self, cmd: LoggedRecord, writers: Writers) -> None:
        from zeebe_tpu.engine.processors import check_tenant_authorized
        from zeebe_tpu.protocol import DEFAULT_TENANT

        value = dict(cmd.record.value)
        value.pop("authorizedTenants", None)  # claim, not broadcast payload
        if not check_tenant_authorized(
                cmd, cmd.record.value.get("tenantId") or DEFAULT_TENANT, writers):
            return
        if self.distribution is not None and self.distribution.is_distributed_command(cmd):
            # receiver: the whole local broadcast (event + subscription
            # triggering) runs once per distribution key, then acks
            self.distribution.handle_distributed(
                cmd, writers,
                lambda: self._broadcast_locally(cmd.record.key, value, writers),
            )
            return
        key = cmd.record.key if cmd.record.key >= 0 else self.state.next_key()
        broadcasted = self._broadcast_locally(key, value, writers)
        writers.respond(cmd, broadcasted)
        if self.distribution is not None:
            self.distribution.distribute(
                writers, key, ValueType.SIGNAL, SignalIntent.BROADCAST, value
            )

    def _broadcast_locally(self, key: int, value: dict, writers: Writers):
        from zeebe_tpu.protocol import DEFAULT_TENANT

        name = value.get("signalName", "")
        variables = value.get("variables") or {}
        tenant = value.get("tenantId") or DEFAULT_TENANT
        broadcasted = writers.append_event(
            key, ValueType.SIGNAL, SignalIntent.BROADCASTED, value
        )
        for sub in list(self.state.signal_subscriptions.find(name)):
            if sub.get("tenantId", DEFAULT_TENANT) != tenant:
                continue
            host_key = sub.get("catchEventInstanceKey", -1)
            if host_key >= 0:
                instance = self.state.element_instances.get(host_key)
                if instance is None:
                    continue
                if sub.get("interrupting", True):
                    # single-use: close before routing so a second broadcast in
                    # the same batch cannot double-trigger
                    writers.append_event(
                        host_key, ValueType.SIGNAL_SUBSCRIPTION,
                        SignalSubscriptionIntent.DELETED, sub,
                    )
                self._merge_variables(instance, host_key, variables, writers)
                self.bpmn.route_trigger(host_key, sub["catchEventId"], writers)
            else:
                # start-event subscription: create a new instance at that start
                writers.append_command(
                    -1, ValueType.PROCESS_INSTANCE_CREATION,
                    ProcessInstanceCreationIntent.CREATE,
                    {
                        "bpmnProcessId": sub.get("bpmnProcessId", ""),
                        "processDefinitionKey": sub.get("processDefinitionKey", -1),
                        "variables": variables,
                        "startElementId": sub.get("catchEventId", ""),
                        **({"tenantId": sub["tenantId"]} if "tenantId" in sub else {}),
                    },
                )
        return broadcasted

    def _merge_variables(self, instance: dict, host_key: int, variables: dict,
                         writers: Writers) -> None:
        """Broadcast variables merge into the process instance like message
        correlation variables."""
        pi_value = instance["value"]
        for var_name, var_value in variables.items():
            target_scope = (
                self.state.variables.find_scope_with(host_key, var_name)
                or pi_value.get("processInstanceKey", host_key)
            )
            exists = self.state.variables.has_local(target_scope, var_name)
            writers.append_event(
                self.state.next_key(), ValueType.VARIABLE,
                VariableIntent.UPDATED if exists else VariableIntent.CREATED,
                {
                    "name": var_name,
                    "value": var_value,
                    "scopeKey": target_scope,
                    "processInstanceKey": pi_value.get("processInstanceKey", -1),
                    "processDefinitionKey": pi_value.get("processDefinitionKey", -1),
                    "bpmnProcessId": pi_value.get("bpmnProcessId", ""),
                },
            )
