"""The device-kernel execution backend: batched command processing.

This is the seam BASELINE.json names: the automaton kernel
(zeebe_tpu.ops.automaton) registered behind the stream platform's
RecordProcessor SPI as the partition's batched execution engine. The stream
processor collects a group of committed commands, this backend advances every
touched process instance lock-step on the device, and the decoded results are
materialized as the *identical* record stream the sequential engine would have
written — same events, same intermediate processed commands, same keys, same
values — through the normal Writers, so appliers, replay, exporters, and
snapshots see no difference.

Reference seams: stream-platform/src/main/java/io/camunda/zeebe/stream/api/
RecordProcessor.java (the SPI), engine/src/main/java/io/camunda/zeebe/engine/
Engine.java:40 (the sequential implementation this shadows), and the
batchProcessing loop in ProcessingStateMachine.java:328-374 whose FIFO
follow-up order the materializer reproduces exactly.

Eligibility: a process definition rides the kernel when it lowers to device
tables (flat graph of tasks / exclusive / parallel gateways / none events with
numeric FEEL conditions — zeebe_tpu.ops.tables) and none of its elements need
host-only behaviors (io mappings, boundary events, timers, messages, scripts).
Commands of other definitions — and commands whose instances are not in a
reconstructable state — fall back to the sequential engine, command by
command, preserving exact semantics.

Condition evaluation on device is BIT-EXACT against the host float64 FEEL
evaluator: slots carry IEEE-754 total-order keys as two int32 planes
(zeebe_tpu.ops.tables.f64_key_planes), comparisons are lexicographic over the
planes, and arithmetic inside conditions host-escapes at compile time — so no
float32 rounding exists anywhere on the device path.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from zeebe_tpu.engine.eligibility import PathAccounting, esp_start_host_reason
from zeebe_tpu.models.bpmn.executable import ExecutableElement, ExecutableProcess
from zeebe_tpu.feel.feel import (
    FeelEvalError,
    Lit as _FeelLit,
    Var as _FeelVar,
)
from zeebe_tpu.ops.tables import (
    _KERNEL_OP,
    _MI_BODY_TYPES,
    ConditionNotCompilable,
    K_CATCH,
    K_HOST,
    K_JOIN,
    K_MI,
    K_SCOPE,
    K_TASK,
    ProcessTables,
    compile_tables,
    f64_exact as _f64_exact,
)
from zeebe_tpu.protocol import ValueType
from zeebe_tpu.protocol.enums import BpmnElementType, BpmnEventType, ErrorType
from zeebe_tpu.protocol.intent import (
    IncidentIntent,
    JobIntent,
    ProcessInstanceCreationIntent,
    ProcessInstanceIntent as PI,
    ProcessMessageSubscriptionIntent,
    TimerIntent,
)

logger = logging.getLogger("zeebe_tpu.kernel_backend")


def _py_pack_fingerprint(docs, roles: dict[int, str],
                         fp_fields: frozenset[str]
                         ) -> tuple[bytes, list[int], set[int]]:
    """Pure-Python fingerprint walk — the specification the native
    ``pack_fingerprint`` (native/codec.c) is byte-equality-tested against.

    Pass 1 collects large ints pinned at NON-whitelisted positions — a value
    that also occurs pinned elsewhere must not be extracted (the slow path
    may copy it from the pinned position, and patching every value-equal
    occurrence would corrupt that copy). Pass 2 emits msgpack with role
    markers ["\\x00r", tag], extraction markers ["\\x00f", ordinal], and
    "\\x00s" escaping of NUL-prefixed user strings (so user data can never
    forge a marker — prefix escaping keeps the normalization injective).

    The returned pinned set is EXACTLY the ints the fingerprint pins
    byte-for-byte — the sound ``Roles.allowed`` constant set for template
    capture (an int the fingerprint normalized away varies per command and
    must never be baked into a template as a constant)."""
    from zeebe_tpu.protocol.msgpack import py_packb

    pinned: set[int] = set()

    def scan(obj, field=None):
        t = type(obj)
        if t is int:
            if obj >= _ROLE_VALUE_MIN:
                if obj not in roles and field is None:
                    pinned.add(obj)
            elif obj <= -_ROLE_VALUE_MIN:
                # large negatives are never roles and never extracted —
                # norm() emits them unchanged at every position, so they are
                # fingerprint-pinned and sound template constants
                pinned.add(obj)
        elif t is dict:
            for k, v in obj.items():
                scan(k)
                scan(v, k if type(k) is str and k in fp_fields else None)
        elif t is list or t is tuple:
            for v in obj:
                scan(v)

    scan(docs)

    fp_values: list[int] = []
    fp_ordinal: dict[int, int] = {}

    def norm(obj, field=None):
        # exact-type dispatch; bool/float/None fall through unchanged
        t = type(obj)
        if t is int:
            if obj >= _ROLE_VALUE_MIN:
                r = roles.get(obj)
                if r is not None:
                    # tuple, not list: markers must stay hashable so a
                    # role-valued int used as a dict KEY normalizes instead
                    # of crashing (packs to the same msgpack array bytes)
                    return ("\x00r", r)
                if field is not None and obj not in pinned:
                    i = fp_ordinal.get(obj)
                    if i is None:
                        i = len(fp_values)
                        fp_ordinal[obj] = i
                        fp_values.append(obj)
                    return ("\x00f", i)
            return obj
        if t is str:
            return ("\x00s" + obj) if obj.startswith("\x00") else obj
        if t is dict:
            return {
                norm(k): norm(v, k if type(k) is str and k in fp_fields else None)
                for k, v in obj.items()
            }
        if t is list or t is tuple:
            return [norm(v) for v in obj]
        return obj

    return py_packb(norm(docs)), fp_values, pinned


from zeebe_tpu.native import codec_fn as _codec_fn

_native_pack_fingerprint = _codec_fn("pack_fingerprint")

# admission cap on a device MI body's cardinality: bigger collections take
# the sequential path (also far below the PI-batch chunking threshold, so
# the chunked-activation shape never reaches the device)
_MI_MAX_CARD = 16

# token phases (mirrors zeebe_tpu.ops.automaton)
_PHASE_AT = 0
_PHASE_WAIT = 1
_PHASE_DONE = 2

# below this, ints are never treated as keys by value (burst_templates)
_ROLE_VALUE_MIN = 1 << 32
_MISSING = object()

_CANDIDATE_COMMANDS = {
    (ValueType.PROCESS_INSTANCE_CREATION, int(ProcessInstanceCreationIntent.CREATE)),
    (ValueType.JOB, int(JobIntent.COMPLETE)),
    (ValueType.TIMER, int(TimerIntent.TRIGGER)),
    (ValueType.PROCESS_MESSAGE_SUBSCRIPTION, int(ProcessMessageSubscriptionIntent.CORRELATE)),
}


def _is_numeric(v: Any) -> bool:
    return isinstance(v, (bool, int, float)) and not isinstance(v, str)




def _safe_mapping_expr(expr) -> bool:
    """True when evaluating the expression can NEVER raise: the kernel's
    trace decoder routes tokens BEFORE the materializer evaluates mappings,
    so an element may ride the device only when its mappings cannot fail
    mid-burst (an IO_MAPPING_ERROR incident after the device already took
    the outgoing flows would diverge from the sequential engine).

    The never-raises subset: static strings; variables (missing → null);
    literals; list/context literals, if-then-else, equality, and/or, and
    member access over safe operands — all null-tolerant in the evaluator
    (access in particular: the parser guarantees a string literal on the
    right, and dict.get / temporal_property / non-container all yield null
    for unknown names). Arithmetic and ordered comparisons raise on type
    mismatches; function calls raise through the builtin wrapper — both
    stay host-side."""
    from zeebe_tpu.feel.feel import Bin, ContextLit, If, Lit, ListLit, Var

    def safe(node) -> bool:
        if isinstance(node, (Lit, Var)):
            return True
        if isinstance(node, ListLit):
            return all(safe(x) for x in node.items)
        if isinstance(node, ContextLit):
            return all(safe(v) for _k, v in node.entries)
        if isinstance(node, If):
            return safe(node.cond) and safe(node.then) and safe(node.orelse)
        if isinstance(node, Bin) and node.op in ("=", "!=", "and", "or",
                                                 "access"):
            return safe(node.left) and safe(node.right)
        return False

    return expr.is_static or safe(expr.ast)


_COND_VAR_CACHE: dict[str, frozenset[str]] = {}


def _condition_var_names(exe: ExecutableProcess) -> frozenset[str]:
    """Variable names read by ANY flow condition of the definition —
    computed statically from the FEEL ASTs, once per content digest (the
    digest covers every flow's condition source). Output mappings targeting
    these must stay host-side: device condition slots are prefetched at
    admission, so a mid-burst write the device cannot see would
    mis-route."""
    import dataclasses as _dc

    from zeebe_tpu.feel.feel import Var

    cached = _COND_VAR_CACHE.get(exe.digest)
    if cached is not None:
        return cached

    names: set[str] = set()

    def walk(node):
        if isinstance(node, (list, tuple)):
            for x in node:
                walk(x)
        elif isinstance(node, Var):
            names.add(node.path[0])  # the root name owns the slot
        elif _dc.is_dataclass(node) and not isinstance(node, type):
            for f in _dc.fields(node):
                walk(getattr(node, f.name))

    for flow in exe.flows:
        if flow.condition is not None and not flow.condition.is_static:
            walk(flow.condition.ast)
    out = frozenset(names)
    if len(_COND_VAR_CACHE) > 4096:
        _COND_VAR_CACHE.clear()
    _COND_VAR_CACHE[exe.digest] = out
    return out


def check_element_eligibility(exe: ExecutableProcess, el: ExecutableElement) -> bool:
    """True when the sequential engine's behavior for this element is exactly
    the kernel's opcode behavior (engine/…/processing/bpmn element processors
    vs ops/automaton masks). Derived from the reason-returning classifier in
    engine/eligibility.py (ISSUE 13) — ONE eligibility logic feeding both the
    runtime lowering and the static eligibility report."""
    from zeebe_tpu.engine.eligibility import element_host_reason

    return element_host_reason(exe, el) is None


@dataclass(frozen=True)
class _CallSegment:
    """One inlined called process inside a synthetic definition (VERDICT r3
    item 3; reference: engine/…/processing/bpmn/container/CallActivityProcessor
    .java — here the called definition's rows are co-resident in the caller's
    table set, the call activity and a child-root placeholder both lower to
    K_SCOPE, and the whole call executes on the device)."""

    call_row: int  # synthetic row of the call activity element
    root_row: int  # synthetic row of the child-root placeholder (= offset)
    offset: int  # child element idx c → synthetic row offset + c
    flow_offset: int  # child flow idx f → synthetic flow idx flow_offset + f
    child_def_key: int  # definition bound at compile (latest at inline time)
    child_process_id: str
    child_exe: ExecutableProcess  # the REAL child executable (local idxs)


def _shifted_child_elements(child: ExecutableProcess, d_elem: int,
                            d_flow: int, call_row: int):
    """Copies of a child definition's elements/flows with indices shifted
    into the synthetic parent's row space. The child ROOT (idx 0) becomes the
    child-root placeholder at row d_elem: a non-root PROCESS element whose
    parent is the call activity row — it parks as a K_SCOPE token standing
    for the child process instance, so activation/completion decode can
    delegate to the sequential PROCESS element handlers verbatim."""
    import dataclasses as _dc

    elements = []
    for el in child.elements:
        elements.append(_dc.replace(
            el,
            idx=el.idx + d_elem,
            parent_idx=(call_row if el.idx == 0
                        else el.parent_idx + d_elem if el.parent_idx >= 0
                        else -1),
            outgoing=([] if el.idx == 0 else [f + d_flow for f in el.outgoing]),
            default_flow_idx=(el.default_flow_idx + d_flow
                              if el.default_flow_idx >= 0 else -1),
            attached_to_idx=(el.attached_to_idx + d_elem
                             if el.attached_to_idx >= 0 else -1),
            boundary_idxs=[b + d_elem for b in el.boundary_idxs],
            child_start_idx=(el.child_start_idx + d_elem
                             if el.child_start_idx >= 0 else -1),
            link_target_idx=(el.link_target_idx + d_elem
                             if el.link_target_idx >= 0 else -1),
        ))
    flows = [
        _dc.replace(f, idx=f.idx + d_flow, source_idx=f.source_idx + d_elem,
                    target_idx=f.target_idx + d_elem)
        for f in child.flows
    ]
    return elements, flows


_INLINE_MAX_DEPTH = 3


def _inline_call_activities(exe: ExecutableProcess, processes,
                            _depth: int = 0,
                            _chain: frozenset = frozenset(),
                            ) -> tuple[ExecutableProcess, list[_CallSegment]]:
    """Build a synthetic definition with statically-resolvable call
    activities inlined as scope regions. Returns (exe, []) unchanged when
    nothing inlines. ``processes`` is the partition's ProcessState.

    A call inlines only when: the called id resolves to a deployed latest
    version whose executable has a none start and no root-level event
    sub-processes; the call element itself carries no io mappings, boundary
    events, or multi-instance marker (those shapes stay host-escaped); and
    the CALLER has no flow conditions at all — a device-compiled parent
    condition could mis-route after a child completion propagates variables
    the admission-time slot prefetch cannot see. Recursion is depth-capped
    and self-recursive chains stay host-side. Version binding follows the
    reference (activation-time latest): admission re-checks that each
    segment's bound key is still the latest and declines to the sequential
    path otherwise."""
    import dataclasses as _dc
    import hashlib as _hashlib

    has_calls = any(
        el.element_type == BpmnElementType.CALL_ACTIVITY
        and el.called_process_id is not None
        for el in exe.elements[1:]
    )
    if not has_calls or _depth >= _INLINE_MAX_DEPTH:
        return exe, []
    if any(f.condition is not None for f in exe.flows):
        return exe, []  # propagation-taint guard (see docstring)

    elements = list(exe.elements)
    flows = list(exe.flows)
    segments: list[_CallSegment] = []
    for el in exe.elements[1:]:
        if (el.element_type != BpmnElementType.CALL_ACTIVITY
                or el.called_process_id is None
                or el.called_process_id in _chain
                or el.multi_instance is not None
                or el.inputs or el.outputs or el.boundary_idxs):
            continue
        meta = processes.get_latest_by_id(el.called_process_id)
        if meta is None or meta.get("deleted"):
            continue
        child = processes.executable(meta["processDefinitionKey"])
        if child is None or child.none_start_of(0) < 0:
            continue
        if any(
            # child-root ESP starts are openable mid-burst only when their
            # subscriptions need NO runtime expression evaluation: static
            # timer durations and signal/error/escalation starts. Message
            # starts evaluate correlation keys against the CHILD scope at
            # activation time — a mid-burst variable write before the call
            # activates would diverge from any admission-time prediction
            not (
                (esp_start := child.elements[esp.child_start_idx]).event_type
                in (BpmnEventType.ERROR, BpmnEventType.ESCALATION)
                or (esp_start.event_type == BpmnEventType.SIGNAL
                    and esp_start.signal_name)
                or (esp_start.event_type == BpmnEventType.TIMER
                    and esp_start.timer_duration is not None
                    and esp_start.timer_duration.is_static
                    and esp_start.timer_cycle is None
                    and esp_start.timer_date is None)
            )
            for esp in child.event_sub_processes_of(0)
        ):
            continue  # ESP needing runtime eval: sequential activation
        if any(f.condition is not None for f in child.flows):
            # child conditions read CHILD-scope variables the shared slot
            # prefetch cannot represent — a whole-child decline keeps the
            # lowering simple (the call stays host-escaped)
            continue
        child_syn, child_segs = _inline_call_activities(
            child, processes, _depth + 1,
            _chain | {exe.process_id, el.called_process_id},
        )
        d_elem, d_flow = len(elements), len(flows)
        seg_elements, seg_flows = _shifted_child_elements(
            child_syn, d_elem, d_flow, el.idx)
        elements.extend(seg_elements)
        flows.extend(seg_flows)
        # the call element itself becomes a scope whose inner start is the
        # placeholder row (the child root), which in turn scopes the child's
        # none start — the K_SCOPE spawn chain mirrors ACTIVATE(child root)
        # → ACTIVATE(child none start) exactly
        elements[el.idx] = _dc.replace(el, child_start_idx=d_elem)
        segments.append(_CallSegment(
            call_row=el.idx, root_row=d_elem, offset=d_elem,
            flow_offset=d_flow,
            child_def_key=meta["processDefinitionKey"],
            child_process_id=el.called_process_id,
            child_exe=child,
        ))
        # nested segments shift into this synthetic's row space
        for s in child_segs:
            segments.append(_dc.replace(
                s, call_row=s.call_row + d_elem, root_row=s.root_row + d_elem,
                offset=s.offset + d_elem, flow_offset=s.flow_offset + d_flow,
            ))
    if not segments:
        return exe, []
    digest = _hashlib.sha256(
        (exe.digest + "|" + "|".join(
            f"{s.child_def_key}:{s.child_exe.digest}" for s in segments
        )).encode()
    ).hexdigest()
    synthetic = ExecutableProcess(
        process_id=exe.process_id, elements=elements, flows=flows,
        by_id=exe.by_id, digest=digest,
    )
    return synthetic, segments


def _mi_body_device_eligible(exe: ExecutableProcess, el) -> bool:
    """True when a multi-instance activity may become a device K_MI body
    (kernel parity restrictions; anything else host-escapes):

    - the activity is a job-worker task with a static type (the inner
      instance parks at a job; containers stay host-side),
    - no boundary events, no io mappings on the body,
    - the input collection is a bare variable or a literal (admission
      predicts its cardinality; evaluation cannot fail mid-burst),
    - a bare-variable collection is not written mid-burst by ANY other
      writer (output mappings, script/decision result variables, another
      body's outputCollection, or a non-ancestor call activity's completion
      propagation) nor shadowed by any ancestor scope's input mappings —
      the admission prediction must equal the value the sequential engine
      reads at body activation,
    - the output element, when collected, is a safe expression (cannot
      raise mid-burst)."""
    mi = el.multi_instance
    if el.element_type not in _MI_BODY_TYPES:
        return False
    if el.job_type is None or not el.job_type.is_static:
        return False
    if el.job_retries is not None and not el.job_retries.is_static:
        return False
    if el.boundary_idxs or el.inputs or el.outputs:
        return False
    if el.form_id is not None or el.native_user_task or el.called_decision_id:
        return False
    if el.script_expression is not None:
        return False
    if mi.input_collection.is_static:
        # a static string never evaluates to a list: the sequential path
        # owns the guaranteed incident (host-escape keeps the REST of the
        # definition on the kernel instead of declining every command)
        return False
    ast = mi.input_collection.ast
    if isinstance(ast, _FeelLit):
        pass
    elif isinstance(ast, _FeelVar) and len(ast.path) == 1:
        v = ast.path[0]

        def is_ancestor(a_idx: int) -> bool:
            anc = el.parent_idx
            while anc > 0:
                if anc == a_idx:
                    return True
                anc = exe.elements[anc].parent_idx
            return False

        for other in exe.elements[1:]:
            if any(t == v for _e, t in other.outputs):
                return False  # an output mapping could rewrite it mid-burst
            if other.script_result_variable == v or other.decision_result_variable == v:
                # engine-computed results (script / business-rule tasks,
                # host-escaped or not) write mid-burst too
                return False
            if (other.multi_instance is not None
                    and other.multi_instance.output_collection == v):
                return False  # MI completion writes it to the parent scope
            if (other.element_type == BpmnElementType.CALL_ACTIVITY
                    and not is_ancestor(other.idx)):
                # a call's COMPLETION propagates arbitrary child variables
                # upward mid-burst; only an ANCESTOR call is safe (its
                # completion strictly postdates this body). Its ACTIVATION
                # propagation copies the very values admission predicted.
                return False
        # ancestor-scope input mappings could shadow it for collect(body)
        anc = el.parent_idx
        while anc > 0:
            if any(t == v for _e, t in exe.elements[anc].inputs):
                return False
            anc = exe.elements[anc].parent_idx
    else:
        return False  # computed collections re-evaluate; host-side only
    if mi.output_collection and mi.output_element is not None:
        if not _safe_mapping_expr(mi.output_element):
            return False
    return True


def _inline_mi_bodies(exe: ExecutableProcess,
                      ) -> tuple[ExecutableProcess, dict[int, int]]:
    """Append a synthetic INNER row per device-eligible multi-instance task:
    the body element keeps its row (child_start_idx → the inner row, lowered
    to K_MI by compile_tables), the inner copy drops the loop marker and
    lowers as a plain job-worker task whose parent scope is the body.
    Returns (exe', {body_row: inner_row}); unchanged when nothing qualifies.
    Reference: engine/…/processing/bpmn/container/MultiInstanceBodyProcessor
    .java — here spawn/completion counting runs on the device."""
    import dataclasses as _dc
    import hashlib as _hashlib

    bodies = [
        el for el in exe.elements[1:]
        if el.multi_instance is not None and el.child_start_idx < 0
        and _mi_body_device_eligible(exe, el)
    ]
    if bodies:
        # a body that can activate twice concurrently (unstructured merge
        # under a parallel split) or iteratively (cycle through the body)
        # would share its per-(instance, row) mi_left cell — exclude
        has_split = any(
            el.element_type == BpmnElementType.PARALLEL_GATEWAY
            and len(el.outgoing) > 1
            for el in exe.elements[1:]
        )
        unstructured = has_split and any(
            el.incoming_count > 1
            and el.element_type != BpmnElementType.PARALLEL_GATEWAY
            for el in exe.elements[1:]
        )
        if unstructured:
            bodies = []
        else:
            targets_of = {
                el.idx: [exe.flows[f].target_idx for f in el.outgoing]
                for el in exe.elements
            }

            def on_cycle(el) -> bool:
                seen: set[int] = set()
                stack = list(targets_of[el.idx])
                while stack:
                    n = stack.pop()
                    if n == el.idx:
                        return True
                    if n in seen:
                        continue
                    seen.add(n)
                    stack.extend(targets_of.get(n, ()))
                return False

            bodies = [el for el in bodies if not on_cycle(el)]
    if not bodies:
        return exe, {}
    elements = list(exe.elements)
    mi_inner: dict[int, int] = {}
    for el in bodies:
        inner_row = len(elements)
        elements.append(_dc.replace(
            el,
            idx=inner_row,
            parent_idx=el.idx,
            outgoing=[],
            default_flow_idx=-1,
            boundary_idxs=[],
            multi_instance=None,
        ))
        elements[el.idx] = _dc.replace(el, child_start_idx=inner_row)
        mi_inner[el.idx] = inner_row
    digest = _hashlib.sha256(
        (exe.digest + "|mi:" + ",".join(map(str, sorted(mi_inner)))).encode()
    ).hexdigest()
    return ExecutableProcess(
        process_id=exe.process_id, elements=elements, flows=list(exe.flows),
        by_id=exe.by_id, digest=digest,
    ), mi_inner


def _mi_burst_reach(exe: ExecutableProcess, ops_row,
                    mi_inner: dict[int, int]) -> dict[int, tuple]:
    """Per entry row, the K_MI body rows a single burst starting there can
    reach without crossing another wait state — over-approximate (scopes are
    both entered and crossed, since a waitless inside drains in-burst).
    Key -1 is the creation entry (the definition's none start); wait rows
    (tasks/catches) key their resume continuation, which also includes every
    ancestor scope's exit (a resume can drain ancestors) and, for an MI
    inner row, its own body (a sequential respawn re-reads the collection)."""
    targets_of = {
        el.idx: [exe.flows[f].target_idx for f in el.outgoing]
        for el in exe.elements
    }
    parking = {K_TASK, K_CATCH, K_HOST, K_MI}

    def closure(frontier) -> tuple:
        seen: set[int] = set()
        found: set[int] = set()
        stack = [x for x in frontier if x >= 0]
        while stack:
            x = stack.pop()
            if x in seen:
                continue
            seen.add(x)
            op = int(ops_row[x])
            if op == K_MI:
                found.add(x)
                continue  # the body parks; its children park at jobs
            el = exe.elements[x]
            if el.child_start_idx >= 0 and op == K_SCOPE:
                stack.append(el.child_start_idx)
                stack.extend(targets_of[x])  # may drain in-burst: cross it
                continue
            if op in parking:
                continue
            stack.extend(targets_of[x])
        return tuple(sorted(found))

    reach: dict[int, tuple] = {}
    start = exe.none_start_of(0)
    reach[-1] = closure([start] if start >= 0 else [])
    inner_to_body = {v: k for k, v in mi_inner.items()}
    for el in exe.elements[1:]:
        op = int(ops_row[el.idx])
        if op not in (K_TASK, K_CATCH):
            continue
        frontier = list(targets_of[el.idx])
        extra: set[int] = set()
        anc = el.parent_idx
        while anc > 0:
            if int(ops_row[anc]) == K_MI:
                extra.add(anc)
            frontier.extend(targets_of[anc])
            anc = exe.elements[anc].parent_idx
        body = inner_to_body.get(el.idx)
        if body is not None:
            extra.add(body)
            frontier.extend(targets_of[body])
        r = set(closure(frontier)) | extra
        if r:
            reach[el.idx] = tuple(sorted(r))
    return reach


def _esp_wait_counts(exe: ExecutableProcess, scope_row: int) -> tuple:
    """(timers, message subs, signal subs) a scope row's event
    sub-processes hold open on its instance."""
    starts = [exe.elements[esp.child_start_idx]
              for esp in exe.event_sub_processes_of(scope_row)]
    return (
        sum(1 for s in starts if s.timer_duration is not None),
        sum(1 for s in starts if s.message_name is not None),
        sum(1 for s in starts if s.signal_name is not None),
    )


@dataclass
class _DefInfo:
    index: int
    key: int
    exe: ExecutableProcess
    job_types: dict[int, str]  # element idx → static job type
    job_retries: dict[int, int]
    join_idxs: list[int]  # element idxs of K_JOIN gateways
    # task element idx → (# timer boundaries, # message boundaries) expected
    # open while the task is parked (reconstruction integrity check)
    boundary_waits: dict[int, tuple[int, int, int]]
    # element idxs lowered to K_HOST in the solo compile (forced again in
    # shared recompiles so the lowering stays stable across registrations)
    host_idxs: frozenset[int] = frozenset()
    # inlined called processes (exe is then SYNTHETIC: parent rows first,
    # then each segment's child rows); empty for plain definitions
    segments: tuple = ()
    # device multi-instance bodies: body row → synthetic inner row
    mi_inner: dict = field(default_factory=dict)
    # entry row → K_MI body rows a burst from that entry can reach without
    # crossing another wait state (-1 = the creation entry); admission must
    # predict those bodies' cardinalities before the group runs
    mi_reach: dict = field(default_factory=dict)
    # ROOT-level event sub-processes (their bodies host-escape; the ROOT
    # instance carries their start subscriptions): start-event element idxs
    # for admission pre-validation, and the expected open-subscription counts
    # (timers, message subs, signal subs) for reconstruction integrity
    root_esp_start_idxs: tuple = ()
    root_esp_waits: tuple = (0, 0, 0)
    # ditto for inlined child-root placeholder rows whose called definition
    # carries root ESPs: scope row -> (timers, msgs, signals) expected open
    # on that call frame's child process instance
    scope_esp_waits: dict = field(default_factory=dict)

    def segment_of_row(self, row: int):
        """The segment whose inlined region contains ``row`` (call_row and
        root_row included), or None for parent rows. Nested segments lie
        inside their parent's span; the MOST specific (highest offset ≤ row)
        wins, except that a call_row belongs to the OUTER region (the call
        element is part of the caller's graph)."""
        best = None
        for s in self.segments:
            if s.call_row == row:
                # the call element row: governed by the segment that inlined
                # it (an outer segment with offset ≤ row), not by itself
                continue
            if s.offset <= row < s.offset + len(s.child_exe.elements):
                if best is None or s.offset > best.offset:
                    best = s
        return best

    def call_segment(self, row: int):
        """The segment whose call activity element sits at ``row``, if any."""
        for s in self.segments:
            if s.call_row == row:
                return s
        return None


class KernelRegistry:
    """Per-partition registry of kernel-eligible definitions sharing one
    compiled table set (ops/tables.compile_tables). Grows as deployments are
    first touched; recompiles the shared tables on growth (deploys are rare)."""

    def __init__(self, max_definitions: int = 64) -> None:
        self.max_definitions = max_definitions
        self._by_key: dict[int, _DefInfo] = {}
        # definition key → typed catalog reason the registry declined it
        # for (engine/eligibility.py DEFINITION_REASONS) — the eligibility
        # report reads this, so the prediction IS the runtime's own verdict
        self._ineligible: dict[int, str] = {}
        # the most recent _build_info decline reason (set before each
        # ``return None`` so lookup can record it without re-deriving)
        self._last_decline: str | None = None
        self._infos: list[_DefInfo] = []
        self._tables: ProcessTables | None = None
        self._device = None
        self._device_by_dev: dict = {}  # router-chosen backend → DeviceTables
        self._tables_fp: tuple | None = None  # (tables identity, digest)

    def lookup(self, definition_key: int, exe: ExecutableProcess | None,
               processes=None) -> _DefInfo | None:
        info = self._by_key.get(definition_key)
        if info is not None:
            return info
        if definition_key in self._ineligible or exe is None:
            return None
        if len(self._infos) >= self.max_definitions:
            return None
        info = self._build_info(definition_key, exe, processes, len(self._infos))
        if info is None:
            self._ineligible[definition_key] = (
                self._last_decline or "condition-not-compilable")
            return None
        self._infos.append(info)
        self._by_key[definition_key] = info
        # recompile the SHARED set eagerly: definitions that solo-compile can
        # still conflict jointly (e.g. one uses a variable numerically, the
        # other in string comparisons — SlotMap kind clash downgrades the
        # offending gateway to a host escape in the shared lowering).
        try:
            self._tables = self._compile_shared()
        except ConditionNotCompilable:
            self._infos.pop()
            del self._by_key[definition_key]
            self._ineligible[definition_key] = "condition-not-compilable"
            self._tables = None  # previous set recompiles lazily
            return None
        self._device = None
        self._device_by_dev.clear()
        return info

    def decline_reason(self, definition_key: int) -> str | None:
        """The typed catalog reason a definition was declined for (None when
        never declined) — the eligibility report's definition-level truth."""
        return self._ineligible.get(definition_key)

    def refresh_segments(self, definition_key: int, exe, processes):
        """Re-inline a cached definition whose call segments went stale (a
        called id was redeployed). In place — the index, which any in-flight
        group arrays reference, is preserved. On failure the old info stays
        and admission keeps declining via the freshness check."""
        old = self._by_key.get(definition_key)
        if old is None or exe is None:
            return None
        new = self._build_info(definition_key, exe, processes, old.index)
        if new is None:
            return None
        self._infos[old.index] = new
        self._by_key[definition_key] = new
        try:
            self._tables = self._compile_shared()
        except ConditionNotCompilable:
            self._infos[old.index] = old
            self._by_key[definition_key] = old
            self._tables = None
            return None
        self._device = None
        self._device_by_dev.clear()
        return new

    def _build_info(self, definition_key: int, exe: ExecutableProcess,
                    processes, index: int) -> _DefInfo | None:
        """Compile one definition's solo lowering (with call activities
        inlined when resolvable) into a _DefInfo at ``index``. Returns None
        when it cannot ride the kernel; callers decide whether that marks
        the key ineligible (lookup) or keeps the old info (refresh)."""
        self._last_decline = None
        segments: tuple = ()
        if processes is not None:
            # statically-resolvable call activities inline as scope regions
            # (device-side call execution); the synthetic exe replaces the
            # real one for this definition's tables and trace decode
            exe, seg_list = _inline_call_activities(exe, processes)
            segments = tuple(seg_list)
        # device multi-instance bodies (incl. inside inlined call regions)
        exe, mi_inner = _inline_mi_bodies(exe)
        # elements outside the device subset become host escapes (K_HOST):
        # the device parks any token reaching them and the materializer hands
        # the continuation to the sequential engine — so the definition rides
        # the kernel for everything else instead of being rejected outright
        host = {el.idx for el in exe.elements[1:]
                if not check_element_eligibility(exe, el)}
        if exe.none_start_of(0) < 0:
            # only message/timer starts: every creation carries an explicit
            # start element — nothing for the kernel's entry path to run
            self._last_decline = "no-none-start"
            return None
        root_esp_start_idxs: list[int] = []
        for esp in exe.event_sub_processes_of(0):
            # root ESP bodies host-escape (their rows are outside the device
            # subset), but the DEFINITION rides the kernel: the creation
            # materializer opens the start subscriptions via the sequential
            # behavior verbatim, reconstruction counts them as root wait
            # state, and triggers route sequentially (a live ESP instance
            # makes resumes decline until it drains). Only subscription
            # shapes the reconstruction can count are eligible
            # (engine/eligibility.py esp_start_host_reason — shared with the
            # static classifier so prediction cannot drift).
            start = exe.elements[esp.child_start_idx]
            decline = esp_start_host_reason(start)
            if decline is not None:
                self._last_decline = decline
                return None  # e.g. cycle/date timers: sequential end to end
            root_esp_start_idxs.append(esp.child_start_idx)
        try:
            solo = compile_tables([exe], host_idxs=[host])
        except ConditionNotCompilable:
            self._last_decline = "condition-not-compilable"
            return None
        clock = lambda: 0  # noqa: E731 — static expressions ignore the clock
        job_types: dict[int, str] = {}
        job_retries: dict[int, int] = {}
        join_idxs: list[int] = []
        for el in exe.elements[1:]:
            if solo.kernel_op[0, el.idx] == K_TASK:
                job_types[el.idx] = el.job_type.evaluate({}, clock)
                job_retries[el.idx] = (
                    int(el.job_retries.evaluate({}, clock)) if el.job_retries is not None else 3
                )
            if solo.kernel_op[0, el.idx] == K_JOIN:
                join_idxs.append(el.idx)
        effective_host = frozenset(
            el.idx for el in exe.elements[1:]
            if solo.kernel_op[0, el.idx] == K_HOST
        )
        boundary_waits: dict[int, tuple[int, int, int]] = {}
        for el in exe.elements[1:]:
            if solo.kernel_op[0, el.idx] == K_TASK and el.boundary_idxs:
                bs = [exe.elements[b] for b in el.boundary_idxs]
                boundary_waits[el.idx] = (
                    sum(1 for b in bs if b.timer_duration is not None),
                    sum(1 for b in bs if b.message_name is not None),
                    sum(1 for b in bs if b.signal_name is not None),
                )
            elif (el.element_type == BpmnElementType.EVENT_BASED_GATEWAY
                  and el.idx not in effective_host):
                # an event-based gateway's wait states live on its own
                # instance, one per succeeding catch event
                ts = [exe.elements[exe.flows[f].target_idx] for f in el.outgoing]
                boundary_waits[el.idx] = (
                    sum(1 for t in ts if t.timer_duration is not None),
                    sum(1 for t in ts if t.message_name is not None),
                    sum(1 for t in ts if t.signal_name is not None),
                )
        return _DefInfo(
            index=index,
            key=definition_key,
            exe=exe,
            job_types=job_types,
            job_retries=job_retries,
            join_idxs=join_idxs,
            boundary_waits=boundary_waits,
            host_idxs=effective_host,
            segments=segments,
            mi_inner=mi_inner,
            mi_reach=(_mi_burst_reach(exe, solo.kernel_op[0], mi_inner)
                      if mi_inner else {}),
            root_esp_start_idxs=tuple(root_esp_start_idxs),
            root_esp_waits=(_esp_wait_counts(exe, 0)
                            if root_esp_start_idxs else (0, 0, 0)),
            scope_esp_waits={
                seg.root_row: waits
                for seg in segments
                if (waits := _esp_wait_counts(exe, seg.root_row)) != (0, 0, 0)
            },
        )

    def _compile_shared(self) -> ProcessTables:
        return compile_tables(
            [i.exe for i in self._infos],
            host_idxs=[set(i.host_idxs) for i in self._infos],
        )

    @property
    def tables(self) -> ProcessTables:
        if self._tables is None:
            self._tables = self._compile_shared()
        return self._tables

    @property
    def device_tables(self):
        if self._device is None:
            from zeebe_tpu.ops.automaton import DeviceTables

            self._device = DeviceTables.from_tables(self.tables)
        return self._device

    def device_tables_for(self, device):
        """Device tables committed to ``device`` (router-chosen backend).
        ``None`` = the process default device (the plain property)."""
        if device is None:
            return self.device_tables
        cached = self._device_by_dev.get(device)
        if cached is None:
            import jax

            from zeebe_tpu.ops.automaton import DeviceTables

            with jax.default_device(device):
                cached = DeviceTables.from_tables(self.tables)
            self._device_by_dev[device] = cached
        return cached

    @property
    def tables_fingerprint(self) -> str:
        """Identity of the compiled table set ACROSS partitions — a CONTENT
        digest of everything that shapes the sharded device program (table
        arrays, slot/interner assignments incl. order, job types): two
        partitions whose groups carry equal digests behave identically under
        the lead shard's replicated DeviceTables, so they may share one mesh
        dispatch. Content-based (not definition-key-based) so independently
        deployed copies of the same definitions coalesce too — the common
        case, since deployment distribution applies the same resources in
        the same order on every partition."""
        tables = self.tables
        fp = self._tables_fp
        if fp is None or fp[0] is not tables:
            import hashlib

            h = hashlib.sha256()
            for tag, arr in (("op", tables.kernel_op), ("ic", tables.in_count),
                             ("jt", tables.job_type), ("oc", tables.out_count),
                             ("ot", tables.out_target), ("oco", tables.out_cond),
                             ("ofi", tables.out_flow_idx),
                             ("ds", tables.default_slot),
                             ("se", tables.start_elem), ("ec", tables.elem_count),
                             ("ss", tables.scope_start), ("is", tables.in_scope),
                             ("mis", tables.mi_sequential),
                             ("cop", tables.cond_ops), ("ca", tables.cond_args)):
                # field tag + shape + dtype delimit each array: without them
                # raw byte streams could alias across array boundaries and two
                # different table sets could digest equal — and this digest
                # alone gates mesh-dispatch coalescing
                h.update(f"{tag}:{arr.shape}:{arr.dtype}".encode())
                h.update(arr.tobytes())
            h.update(repr(tables.job_type_names).encode())
            h.update(repr(list(tables.slot_map.names.items())).encode())
            h.update(repr(sorted(tables.slot_map.kinds.items())).encode())
            h.update(repr(list(tables.interner.ids.items())).encode())
            h.update(repr([sorted(v) for v in tables.cond_vars_by_def]).encode())
            fp = (tables, h.hexdigest())
            self._tables_fp = fp
        return fp[1]


@dataclass
class _Token:
    slot: int
    elem_idx: int
    key: int  # element instance key (-1 until minted at materialization)
    value: dict  # the record value the ACTIVATE command carried
    phase: int = _PHASE_AT
    # follow-up index of this token's ACTIVATE command in the burst being
    # materialized (-1 = predates the burst); host-escape cascades appended
    # before it must drain before this token's processing emits (FIFO)
    act_idx: int = -1


@dataclass
class _Inst:
    idx: int  # row in the device batch
    info: _DefInfo
    new: bool  # created by this group (vs reconstructed)
    pi_key: int = -1
    meta: dict | None = None  # creation: resolved definition metadata
    tokens: list[_Token] = field(default_factory=list)
    join_counts: dict[int, int] = field(default_factory=dict)  # elem idx → arrivals
    slots: dict[str, float] = field(default_factory=dict)  # condition variables
    done_emitted: bool = False
    # every process-instance key this device instance spans (self + call-
    # activity child frames + ancestors); the group conflict set must cover
    # them all so one family never resumes twice in one group
    family_pis: list[int] = field(default_factory=list)
    # K_MI bodies: body row → children left to spawn on device (admission-
    # predicted cardinality for unspawned bodies; reconstruction remainder
    # for parked sequential bodies; 0 for fully-spawned parallel bodies)
    mi_left: dict = field(default_factory=dict)
    # predicted cardinality per body row (the decoder's spawn-count oracle
    # is the sequential delegation itself; this sizes the token pool)
    mi_cards: dict = field(default_factory=dict)


@dataclass
class _Admitted:
    cmd: Any  # LoggedRecord
    inst: _Inst
    resume_token: _Token | None = None  # job complete: the PHASE_DONE token
    kind: str = "c"  # "c" creation | "j" job complete
    # instance-scoped documents the head processors will read — the burst
    # template's context fingerprint is computed over these (role-normalized)
    # at ADMISSION time (the docs are guaranteed unmutated there; holding
    # references past admission would race the group's own state writes),
    # then released
    fp_docs: list | None = None
    # False → this command must not ride a burst template (e.g. it touches
    # engine.await_results, which lives outside the captured state store)
    templatable: bool = True
    # clock-derived document fields (dueDate/deadline) extracted by the
    # fingerprint walk, in canonical order — resolved per command for the
    # template's ("fp", i) roles
    fp_values: list | None = None
    # the role-normalized byte image (template cache key component) and the
    # exact set of large ints the fingerprint pinned (the sound
    # Roles.allowed set) — both computed at admission
    fp_bytes: bytes | None = None
    fp_pinned: set | None = None
    # minted keys of parked wait states (timer keys), in reconstruction
    # order — role ("wait", j); they appear in cancel/trigger bursts but not
    # in any admission doc, so they need their own role kind
    wait_keys: list | None = None


def _device_ctx(dev):
    """Fresh placement context per dispatch (jax.default_device context
    managers are single-use)."""
    if dev is None:
        import contextlib

        return contextlib.nullcontext()
    import jax

    return jax.default_device(dev)


class DeviceWedgedError(RuntimeError):
    """A device dispatch/fetch exceeded the per-dispatch watchdog deadline
    (``ZEEBE_BROKER_DEVICE_DISPATCHTIMEOUTMS``) — the gray-failure shape a
    slow-but-alive device tunnel produces. Contained exactly like a
    dispatch exception: the group is abandoned and host re-executed."""


#: the device-chaos seam (ISSUE 15): ``testing/chaos_device.py`` installs a
#: controller here (worker entry, from ``ZEEBE_CHAOS_DEVICE``); the dispatch
#: path consults it with ONE is-None check per group when chaos is off
_DEVICE_CHAOS = None


def install_device_chaos(controller) -> None:
    """Install (or, with None, remove) the process-wide device-fault
    controller consulted at the kernel dispatch seam."""
    global _DEVICE_CHAOS
    _DEVICE_CHAOS = controller


def device_chaos():
    return _DEVICE_CHAOS


class _WatchdogWorker:
    """One reusable daemon thread of the dispatch-watchdog pool. A worker
    abandoned by a deadline miss keeps blocking on the wedged ``fn`` — but
    instead of dying (and leaking, one thread per expired dispatch, the
    old PR 15 behavior) it re-idles ITSELF when the wedged call finally
    returns, so a bounded pool serves any number of wedges."""

    def __init__(self, pool: "_WatchdogPool") -> None:
        import queue
        import threading

        self._pool = pool
        self._tasks: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="device-dispatch-watchdog")
        self._thread.start()

    def submit(self, fn, box: dict, done) -> None:
        self._tasks.put((fn, box, done))

    def _loop(self) -> None:
        while True:
            fn, box, done = self._tasks.get()
            try:
                box["value"] = fn()
            except BaseException as exc:  # noqa: BLE001 — re-raised on caller
                box["error"] = exc
            done.set()
            # re-idle AFTER the task finishes — a deadline-missed caller
            # already walked away, so this is what un-leaks a wedge; the
            # pool drops us when already at capacity and the thread exits
            if not self._pool.release(self):
                return


class _WatchdogPool:
    """Bounded free-list of :class:`_WatchdogWorker` threads."""

    MAX_IDLE = 8

    def __init__(self) -> None:
        import threading

        self._idle: list[_WatchdogWorker] = []
        self._lock = threading.Lock()

    def acquire(self) -> _WatchdogWorker:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return _WatchdogWorker(self)

    def release(self, worker: _WatchdogWorker) -> bool:
        with self._lock:
            if len(self._idle) < self.MAX_IDLE:
                self._idle.append(worker)
                return True
        return False


_WATCHDOG_POOL = _WatchdogPool()


def _watchdog_call(fn, deadline_s: float):
    """Run ``fn`` on a pooled daemon thread with a deadline — the dispatch
    watchdog. A deadline miss raises :class:`DeviceWedgedError` while the
    pooled worker keeps blocking on the wedged call; when that call
    eventually returns the worker re-idles itself, so repeated wedges
    reuse a bounded pool instead of leaking one thread per expiry."""
    import threading

    box: dict = {}
    done = threading.Event()
    worker = _WATCHDOG_POOL.acquire()
    worker.submit(fn, box, done)
    if not done.wait(deadline_s):
        raise DeviceWedgedError(
            f"device dispatch exceeded the {deadline_s * 1000:.0f}ms "
            f"watchdog deadline (wedged or badly degraded device)")
    if "error" in box:
        raise box["error"]
    return box["value"]


def _profiler_annotation(name: str):
    """``jax.profiler.TraceAnnotation`` around one kernel-chunk dispatch —
    the device-side counterpart of the observability spans: a
    ``jax.profiler.trace()`` capture taken while tracing is enabled shows the
    chunk boundaries by name in Perfetto/TensorBoard. A no-op context when
    tracing is off, so the dispatch hot path pays one attribute read."""
    from zeebe_tpu.observability.tracer import get_tracer

    if not get_tracer().enabled:
        import contextlib

        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name)


@dataclass
class _PendingGroup:
    """One admitted command group with its device run in flight — the
    double-buffered unit of the pipelined execution path (stream/processor
    .py process_available_batch): while this group's first chunk computes on
    the device, the processor runs the PREVIOUS group's deferred host work.
    Carries per-stage wall times for the stream_processor_pipeline_* stage
    histograms."""

    admitted: list
    failed: bool = False
    # typed catalog reason when the device run declines (geometry bounds,
    # non-quiescence, pool overflow, mesh errors) — finish_group feeds it
    # into the consolidated PathAccounting exactly once per failed group
    fail_reason: str | None = None
    # device chunks actually fetched (the kernel_wave flight event's
    # chunk-count field); mesh groups report 0 (the runner owns chunking)
    chunks_run: int = 0
    mesh: bool = False
    arrays: dict | None = None
    I: int = 0
    T: int = 0
    tables: Any = None
    config: Any = None
    dt: Any = None
    dev: Any = None
    bucket: Any = None
    run: Any = None  # (carry state, packed events) of the in-flight chunk
    # chunk k+1 prefetch is a win only on a REAL accelerator (device compute
    # overlaps host decode for free); on a host XLA backend the prefetched
    # chunk's threads compete with the decoding host thread for the same
    # cores (measured: ten_tasks regression on a 2-vCPU box)
    pipeline_chunks: bool = False
    # device-fault defense (ISSUE 15): shadow=True keeps the fetched result
    # rows for byte-for-byte comparison against the host oracle before the
    # group transaction commits; canary marks a quarantine re-proving
    # dispatch (forced shadow); corrupt_tokens are chaos-ledger sequences
    # the backend must report caught (shadow or containment)
    shadow: bool = False
    canary: bool = False
    raw_rows: list = field(default_factory=list)
    corrupt_tokens: list = field(default_factory=list)
    # stage wall times (seconds), observed by the stream processor
    t_admit: float = 0.0
    device_elapsed: float = 0.0
    t_materialize: float = 0.0
    t_shadow: float = 0.0


class KernelBackend:
    """Admits groups of commands, runs the automaton kernel, materializes the
    sequential-equivalent record stream. One instance per partition."""

    def __init__(self, engine, max_group: int = 256, max_steps: int = 4096,
                 chunk_steps: int = 8, use_templates: bool = True,
                 audit_templates: bool = False,
                 max_commands_in_batch: int = 100,
                 mesh_runner=None, router="shared") -> None:
        self.engine = engine
        self.registry = KernelRegistry()
        self.max_group = max_group
        self.max_steps = max_steps
        self.chunk_steps = chunk_steps
        # link-aware backend routing (utils/device_link.py): each group runs
        # on the accelerator only when the measured host↔device link
        # amortizes; behind a slow tunnel groups ride the host XLA backend
        # (the identical program). "shared" = the process-wide router.
        if router == "shared":
            from zeebe_tpu.utils.device_link import shared_router

            router = shared_router()
        self.router = router
        # (bucket, device) pairs already executed once by THIS backend — the
        # first run's wall time includes XLA compilation and is excluded from
        # the router's steady-state cost model
        self._runs_seen: set = set()
        # shared MeshKernelRunner (parallel/mesh_runner.py): when set, this
        # partition's groups run as shards of ONE mesh dispatch, coalescing
        # with other partitions' concurrently submitted groups
        self.mesh_runner = mesh_runner
        # must match the stream processor's batch budget: the host-escape
        # drain accounts commands exactly like the sequential batch loop
        self.max_commands_in_batch = max_commands_in_batch
        # burst templates (engine/burst_templates.py): replay a command's
        # whole record burst by patching a captured byte template. audit mode
        # (tests) shadows every template hit with the slow path and asserts
        # byte/state/response equality instead of serving the fast result.
        self.use_templates = use_templates
        self.audit_templates = audit_templates
        self._templates: dict = {}
        self._template_cache_limit = 1024
        # observability
        self.groups_processed = 0
        self.commands_processed = 0
        self.fallbacks = 0
        # consolidated path accounting (ISSUE 13): ONE reason catalog + ONE
        # counter home for every kernel-vs-host routing decision — feeds
        # zeebe_kernel_records_total{path,reason}, the per-definition
        # coverage gauge, and the static-vs-observed parity gate.
        # fallback_reasons aliases its Counter (VERDICT r4 item 5 / BENCH
        # back-compat: reason → count, full strings incl. head-*:<kind>)
        self.accounting = PathAccounting(engine.state.partition_id)
        self.fallback_reasons = self.accounting.reasons
        # mesh submit seam tracing (ISSUE 19): the singleton is mutated in
        # place by configure_tracing, so caching the reference is safe — one
        # attribute read per mesh submit when tracing is off
        from zeebe_tpu.observability.tracer import get_tracer

        self._tracer = get_tracer()
        self._partition_id = engine.state.partition_id
        self.template_hits = 0
        self.template_misses = 0
        self.template_audits = 0
        self.template_audit_skips = 0
        # device-fault defense (ISSUE 15): the per-broker health ladder
        # (shared across partitions like the router — the device is a
        # process resource), the shadow-verification sample rate, and the
        # dispatch watchdog deadline all bind from ZEEBE_BROKER_DEVICE_*
        from zeebe_tpu.engine.device_health import shared_device_health

        self.health = shared_device_health()
        self._shadow_seq = 0
        #: groups whose device result a shadow mismatch quarantined (the
        #: host oracle's result committed instead)
        self.shadow_quarantined = 0
        # per-I-bucket cached zero planes for _dispatch_first_chunk (jax
        # arrays are immutable, so sharing across groups is safe)
        self._zero_state: dict = {}
        # compile seam (observability/profiler.py): (bucket, device) pairs
        # whose first dispatch — the one that traces + lowers + compiles (or
        # loads the persistent-cache executable) — was already timed into
        # xla_compile_seconds / xla_compiles_total{cache=hit|miss}
        self._compiles_seen: set = set()

    # ONE source of truth for the device-defense knobs: the shared ladder's
    # cfg — a snapshot copied at construction would split-brain against the
    # live suspect_shadow_boost/shadow_seed reads in _shadow_sampled
    @property
    def shadow_sample_rate(self) -> float:
        return self.health.cfg.shadow_sample_rate

    @property
    def dispatch_timeout_ms(self) -> int:
        return self.health.cfg.dispatch_timeout_ms

    # -- candidate test (no state access) ----------------------------------

    def is_candidate(self, record) -> bool:
        return (record.value_type, int(record.intent)) in _CANDIDATE_COMMANDS

    def note_sequential_head(self, record) -> None:
        """The processor's batch scan found a non-candidate command at the
        HEAD of the pending log (a deployment, a message publish, …):
        ordinary sequential traffic, counted BY KIND so the bench fallback
        accounting separates it from kernel failures and from admission
        regressions (ISSUE 7: the bare "head-not-admittable" count hid
        what actually fell back — and end-of-log probes inflated it)."""
        self.fallbacks += 1
        self.accounting.note_host(
            f"head-sequential:{record.value_type.name}.{record.intent.name}",
            self._definition_of(record),
        )

    def _definition_of(self, record) -> str:
        """Best-effort bpmnProcessId attribution for a host-routed head
        command (the per-definition coverage split). Creations carry the id
        on the value; job completes resolve it through the job's state entry
        (we are inside the partition's open transaction on every caller
        path); everything else is unattributed ('-'). Attribution must never
        take routing down."""
        try:
            value = record.value
            definition = value.get("bpmnProcessId") if isinstance(value, dict) else None
            if definition:
                return definition
            if (record.value_type, int(record.intent)) == (
                    ValueType.JOB, int(JobIntent.COMPLETE)):
                job = self.engine.state.jobs.get(record.key)
                if job is not None and job.get("bpmnProcessId"):
                    return job["bpmnProcessId"]
        except Exception:  # noqa: BLE001 — attribution is best-effort
            pass
        return "-"

    # -- admission ----------------------------------------------------------

    def _admit(self, cmd, instances: dict[int, _Inst],
               admitted_pis: set[int], wave: dict) -> _Admitted | None:
        record = cmd.record
        kind = (record.value_type, int(record.intent))
        if kind == (ValueType.PROCESS_INSTANCE_CREATION, int(ProcessInstanceCreationIntent.CREATE)):
            adm = self._admit_creation(cmd, instances, wave)
        elif kind == (ValueType.JOB, int(JobIntent.COMPLETE)):
            adm = self._admit_job_complete(cmd, instances, admitted_pis, wave)
        elif kind == (ValueType.TIMER, int(TimerIntent.TRIGGER)):
            adm = self._admit_timer_trigger(cmd, instances, admitted_pis, wave)
        elif kind == (ValueType.PROCESS_MESSAGE_SUBSCRIPTION,
                      int(ProcessMessageSubscriptionIntent.CORRELATE)):
            adm = self._admit_message_correlate(cmd, instances, admitted_pis,
                                                wave)
        else:
            return None
        if adm is not None and self.use_templates and adm.templatable:
            # fingerprint NOW, over the live documents: nothing has mutated
            # them yet (materialization of earlier group members runs later
            # and only touches other instances), and doing it here lets the
            # admission docs be referenced instead of defensively copied
            adm.fp_bytes, adm.fp_values, adm.fp_pinned = self._fingerprint(adm)
            adm.fp_docs = None
        return adm

    # wave-context sentinel: distinguishes a memoized None from a cache miss
    _WAVE_MISS = object()

    def _wave_def_info(self, wave: dict, def_key: int) -> "_DefInfo | None":
        """Per-wave memo of registry lookup + segment freshness — the
        vectorized admission prevalidation (ISSUE 17): a wave of commands
        against one definition pays the eligibility lookup and the inlined-
        segment staleness probe once, not once per head. None is memoized
        too (a stale-segment definition declines for the whole wave; the
        refresh `_segments_fresh` triggers readmits it next wave)."""
        hit = wave.get(def_key, self._WAVE_MISS)
        if hit is not self._WAVE_MISS:
            return hit
        state = self.engine.state
        info = self.registry.lookup(def_key, state.processes.executable(def_key),
                                    processes=state.processes)
        if info is not None and not self._segments_fresh(info):
            info = None
        wave[def_key] = info
        return info

    def _condition_slots_cached(self, wave: dict, info: "_DefInfo",
                                merged: dict) -> dict[str, tuple] | None:
        """``_condition_slots`` with a per-wave memo keyed by the condition
        variables' VALUES: instances that agree on every device-read
        variable (the common wave shape — identical creation variables, or
        resumes whose root scopes converged) share one slot-plane
        computation. Unhashable values fall through to the direct path."""
        names = self.registry.tables.cond_vars_by_def[info.index]
        if not names:
            return {}
        try:
            key = ("slots", info.index,
                   tuple(merged.get(n) for n in names))
            hit = wave.get(key, self._WAVE_MISS)
        except TypeError:
            return self._condition_slots(info, merged)
        if hit is not self._WAVE_MISS:
            return hit
        slots = self._condition_slots(info, merged)
        wave[key] = slots
        return slots

    def _admit_creation(self, cmd, instances, wave: dict) -> _Admitted | None:
        state = self.engine.state
        value = cmd.record.value
        if value.get("startInstructions"):
            return None
        if value.get("startElementId"):
            # message/timer-start creations activate an explicit start element
            # — the kernel's creation materializer always enters through the
            # none start, so these stay sequential
            return None
        from zeebe_tpu.protocol import DEFAULT_TENANT

        if value.get("tenantId", DEFAULT_TENANT) != DEFAULT_TENANT:
            # non-default tenants ride the sequential path: the kernel's value
            # builders emit the default tenant's record shape
            return None
        bpmn_process_id = value.get("bpmnProcessId", "")
        definition_key = value.get("processDefinitionKey", -1)
        version = value.get("version", -1)
        if definition_key > 0:
            meta = state.processes.get_by_key(definition_key)
        elif version > 0:
            key = state.processes.get_key_by_id_version(bpmn_process_id, version)
            meta = None if key is None else state.processes.get_by_key(key)
        else:
            meta = wave.get(("latest", bpmn_process_id), self._WAVE_MISS)
            if meta is self._WAVE_MISS:
                meta = state.processes.get_latest_by_id(bpmn_process_id)
                wave[("latest", bpmn_process_id)] = meta
        if meta is None or meta.get("deleted"):
            return None  # sequential path writes the NOT_FOUND rejection
        def_key = meta["processDefinitionKey"]
        info = self._wave_def_info(wave, def_key)
        if info is None:
            return None
        variables = value.get("variables") or {}
        slots = self._condition_slots_cached(wave, info, variables)
        if slots is None:
            # a condition could read a variable whose runtime type the device
            # slot kind cannot represent: host and device would disagree
            return None
        if info.root_esp_start_idxs and not self._esp_exprs_admit(
                info, variables):
            return None  # sequential path raises the proper incident
        mi_cards: dict[int, int] = {}
        if info.mi_inner:
            needed = info.mi_reach.get(-1, ())
            if needed:
                cards = self._predict_mi_cards(info, needed, variables)
                if cards is None:
                    return None
                mi_cards = cards
        inst = _Inst(idx=len(instances), info=info, new=True, meta=meta,
                     slots=slots, mi_left=dict(mi_cards), mi_cards=mi_cards)
        templatable = not (value.get("awaitResult") and cmd.record.request_id >= 0)
        return _Admitted(cmd=cmd, inst=inst, kind="c",
                         fp_docs=[value, meta], templatable=templatable)

    def _predict_mi_cards(self, info: _DefInfo, needed,
                          merged: dict) -> dict[int, int] | None:
        """Cardinality of each needed K_MI body's input collection, evaluated
        over the admission-time variable view. Eligibility guarantees no
        other writer can change the collection before the body activates
        mid-burst, so this equals what the sequential delegation will read.
        None = a needed collection is missing/invalid/empty/too large — the
        command declines to the sequential path (which raises the proper
        incident or runs the large fan-out chunked)."""
        cards: dict[int, int] = {}
        for row in needed:
            mi = info.exe.elements[row].multi_instance
            try:
                items = mi.input_collection.evaluate(merged, lambda: 0)
            except Exception:  # noqa: BLE001 — any eval failure → sequential
                return None
            if not isinstance(items, list):
                return None
            if not items or len(items) > _MI_MAX_CARD:
                # empty bodies complete during activation (a different burst
                # shape than park-and-drain); big fan-outs ride chunking
                return None
            cards[row] = len(items)
        return cards

    def _segments_fresh(self, info: _DefInfo) -> bool:
        """Inlined call segments bind the latest called version at compile
        time; activation resolves latest at ACTIVATION time (reference:
        CallActivityProcessor) — a newer deploy of a called id makes the
        inlining stale, so such commands take the sequential path until the
        registry recompiles."""
        if not info.segments:
            return True
        processes = self.engine.state.processes
        for seg in info.segments:
            meta = processes.get_latest_by_id(seg.child_process_id)
            if meta is None or meta["processDefinitionKey"] != seg.child_def_key:
                # re-inline against the new latest so FUTURE commands ride
                # the kernel again; the current command still declines (its
                # caller already resolved the stale info)
                self.registry.refresh_segments(
                    info.key, self.engine.state.processes.executable(info.key),
                    processes)
                return False
        return True

    def _reconstruct(self, pi_key: int, info: _DefInfo, resume_key: int,
                     root=None):
        """Rebuild a running instance's device tokens from element-instance
        state. Every live element instance must be parked in a kernel wait
        state (task on a job, catch on a timer/subscription, or a sub-process
        scope whose descendants are parked) — anything else (mid-transition,
        incident, scope drain in flight) is not reconstructable. Returns
        (tokens, resume_token, root, wait_docs, scope_keys, join_counts) or
        None; wait_docs are the parked wait-state records (for the template
        fingerprint), scope_keys maps scope element idx → instance key
        (0 → the process instance), join_counts maps join gateway element
        idx → unconsumed arrivals."""
        state = self.engine.state
        if root is None:
            root = state.element_instances.get(pi_key)
        from zeebe_tpu.engine.engine_state import EI_ACTIVATED

        if root is None or root["state"] != EI_ACTIVATED:
            return None
        exe = info.exe
        tokens: list[_Token] = []
        resume: _Token | None = None
        wait_docs: list = []
        wait_keys: list[int] = []
        if not self._esp_waits_ok(info.root_esp_waits, pi_key, wait_docs,
                                  wait_keys):
            return None
        family: list[int] = []  # call-child process instance keys
        mi_parked: dict[int, int | None] = {}  # K_MI body row → live inner lc
        # elem idx of a scope (0 = process root) → its instance key: join
        # counters and sub-process drain checks key off the scope instance
        scope_keys: dict[int, int] = {0: pi_key}
        # depth-first walk of the element-instance tree: K_SCOPE children are
        # parked tokens whose own children are walked recursively. Entries
        # carry the call segment whose inlined region the instance lives in
        # (None = the caller's own rows); ids resolve through the segment's
        # child executable, offset into synthetic rows.
        pending_walk = [
            (k, None) for k in sorted(state.element_instances.children_keys(pi_key))
        ]
        for child_key, seg in pending_walk:
            child = state.element_instances.get(child_key)
            if child is None or child["state"] != EI_ACTIVATED:
                return None
            elem_id = child["value"].get("elementId", "")
            id_map = exe.by_id if seg is None else seg.child_exe.by_id
            if elem_id not in id_map:
                return None
            row = id_map[elem_id] + (0 if seg is None else seg.offset)
            el = exe.elements[row]
            if (el.multi_instance is not None and el.child_start_idx >= 0
                    and child["value"].get("bpmnElementType")
                    != BpmnElementType.MULTI_INSTANCE_BODY.name):
                # an MI element id names BOTH the body and its inner
                # instances; the inner rides the synthetic inner row
                row = info.mi_inner[row]
                el = exe.elements[row]
            op = self.registry.tables.kernel_op[info.index, row]
            if op == K_MI:
                if child.get("miActivationIndex") is not None:
                    return None  # chunked fan-out: sequential path owns it
                lc = None
                for k in state.element_instances.children_keys(child_key):
                    inner = state.element_instances.get(k)
                    if inner is not None:
                        lc = max(lc or 0, inner["value"].get("loopCounter", 0))
                mi_parked[row] = lc  # None = no live inner (drain mid-flight)
                scope_keys[row] = child_key
                pending_walk.extend(
                    (k, seg)
                    for k in sorted(state.element_instances.children_keys(child_key))
                )
            elif op == K_SCOPE:
                call_seg = info.call_segment(row)
                if call_seg is not None:
                    # call activity frame: descend into the called child
                    # instance through the back-link; the child ROOT walks as
                    # the placeholder row (its elementId — the process id —
                    # maps to the segment's row 0)
                    child_pi = child.get("calledChildInstanceKey", -1)
                    child_root = state.element_instances.get(child_pi)
                    if child_root is None:
                        return None
                    if (child_root["value"].get("processDefinitionKey")
                            != call_seg.child_def_key):
                        return None  # instance bound an older called version
                    family.append(child_pi)
                    scope_keys[row] = child_key
                    pending_walk.append((child_pi, call_seg))
                else:
                    esp_expected = info.scope_esp_waits.get(row)
                    if esp_expected is not None and not self._esp_waits_ok(
                            esp_expected, child_key, wait_docs, wait_keys):
                        return None  # an ESP trigger owns this call frame
                    scope_keys[row] = child_key
                    pending_walk.extend(
                        (k, seg)
                        for k in sorted(state.element_instances.children_keys(child_key))
                    )
            elif op == K_TASK:
                if child.get("jobKey", -1) < 0:
                    return None
                # boundary subscriptions must be intact: a missing timer/sub
                # means a trigger is mid-flight (its internal TERMINATE/
                # ACTIVATE commands own this instance now) — decline so the
                # sequential path resolves the race
                if not self._collect_wait_states(info, el.idx, child_key,
                                                 wait_docs, wait_keys):
                    return None
            elif op == K_CATCH:
                if el.element_type == BpmnElementType.EVENT_BASED_GATEWAY:
                    # every succeeding catch must have its wait state open on
                    # the gateway instance; anything less means a trigger is
                    # mid-flight (its COMPLETE_ELEMENT owns this instance)
                    if not self._collect_wait_states(info, el.idx, child_key,
                                                     wait_docs, wait_keys):
                        return None
                elif el.timer_duration is not None:
                    timers = state.timers.timers_for_element_instance(child_key)
                    if not timers:
                        return None  # incident-parked or already fired
                    wait_docs.extend(t for _k, t in timers)
                    wait_keys.extend(k for k, _t in timers)
                elif el.signal_name is not None:
                    subs = state.signal_subscriptions.subscriptions_of(child_key)
                    if not subs:
                        return None  # broadcast mid-flight owns the instance
                    wait_docs.extend(subs)
                else:
                    sub = state.process_message_subscriptions.get(
                        child_key, el.message_name
                    )
                    if sub is None:
                        return None
                    wait_docs.append(sub)
            else:
                return None
            tok = _Token(slot=-1, elem_idx=el.idx, key=child_key,
                         value=dict(child["value"]), phase=_PHASE_WAIT)
            if child_key == resume_key:
                tok.phase = _PHASE_DONE
                resume = tok
            tokens.append(tok)
        if resume is None:
            return None
        join_counts = self._join_counts(info, scope_keys)
        # drain integrity: a scope instance with no parked descendant token
        # and no pending join arrival inside has its COMPLETE_ELEMENT command
        # in flight — the device would re-complete it (duplicate records), so
        # the sequential path must finish that window
        for scope_idx in scope_keys:
            if scope_idx == 0:
                continue
            if any(self._inside(exe, t.elem_idx, scope_idx) for t in tokens):
                continue
            if any(join_counts.get(j) and self._inside(exe, j, scope_idx)
                   for j in info.join_idxs):
                continue
            return None
        return (tokens, resume, root, wait_docs, wait_keys, scope_keys,
                join_counts, family, mi_parked)

    def _esp_exprs_admit(self, info: _DefInfo, variables: dict) -> bool:
        """Pre-validate root event-sub-process start expressions over the
        creation variables (the same values _open_scope_event_subscriptions
        will read from the seeded root scope) — THE SAME shared helper the
        sequential open uses, so admission and emission cannot diverge; an
        eval failure takes the sequential path for the engine's own
        incident shape."""
        return self.engine.bpmn.prevalidate_scope_event_subscriptions(
            info.root_esp_start_idxs, info.exe, variables) is None

    def _esp_waits_ok(self, expected: tuple, instance_key: int,
                      wait_docs: list, wait_keys: list) -> bool:
        """A scope's ESP start subscriptions must ALL be open on its
        instance — anything less means a trigger owns the instance right now
        (mirror of _collect_wait_states for scope instances). Applies to
        the process root (root_esp_waits) and to inlined call frames' child
        roots (scope_esp_waits)."""
        expected_timers, expected_subs, expected_signals = expected
        if not (expected_timers or expected_subs or expected_signals):
            return True
        state = self.engine.state
        timers = state.timers.timers_for_element_instance(instance_key)
        subs = state.process_message_subscriptions.subscriptions_of(instance_key)
        signals = state.signal_subscriptions.subscriptions_of(instance_key)
        if (len(timers) != expected_timers or len(subs) != expected_subs
                or len(signals) != expected_signals):
            return False
        wait_docs.extend(t for _k, t in timers)
        wait_keys.extend(k for k, _t in timers)
        wait_docs.extend(subs)
        wait_docs.extend(signals)
        return True

    def _collect_wait_states(self, info: _DefInfo, el_idx: int, child_key: int,
                             wait_docs: list, wait_keys: list) -> bool:
        """Verify the expected wait states (boundary subscriptions of a task,
        or an event-based gateway's per-target subscriptions) are all open on
        ``child_key``, appending their records to ``wait_docs`` and the
        timers' minted keys to ``wait_keys``. False means a trigger is
        mid-flight and the instance is not reconstructable."""
        expected_timers, expected_subs, expected_signals = (
            info.boundary_waits.get(el_idx, (0, 0, 0)))
        if not (expected_timers or expected_subs or expected_signals):
            return True
        state = self.engine.state
        timers = state.timers.timers_for_element_instance(child_key)
        subs = state.process_message_subscriptions.subscriptions_of(child_key)
        signals = state.signal_subscriptions.subscriptions_of(child_key)
        if (len(timers) != expected_timers or len(subs) != expected_subs
                or len(signals) != expected_signals):
            return False
        wait_docs.extend(t for _k, t in timers)
        wait_keys.extend(k for k, _t in timers)
        wait_docs.extend(subs)
        wait_docs.extend(signals)
        return True

    @staticmethod
    def _inside(exe: ExecutableProcess, elem_idx: int, scope_idx: int) -> bool:
        """True when elem_idx lies strictly inside scope_idx's scope chain."""
        anc = exe.elements[elem_idx].parent_idx
        while anc > 0:
            if anc == scope_idx:
                return True
            anc = exe.elements[anc].parent_idx
        return False

    def _join_counts(self, info: _DefInfo, scope_keys: dict[int, int]) -> dict[int, int]:
        state = self.engine.state
        exe = info.exe
        join_counts: dict[int, int] = {}
        for jidx in info.join_idxs:
            # NUMBER_OF_TAKEN_SEQUENCE_FLOWS counters key off the gateway's
            # flow-scope INSTANCE (process root or sub-process instance)
            scope_key = scope_keys.get(exe.elements[jidx].parent_idx)
            if scope_key is None:
                continue  # scope not instantiated → no arrivals
            # the state's counters were written by the sequential appliers,
            # which resolve elements/flows through the CHILD executable for
            # call-frame records — translate inlined synthetic rows back to
            # the segment-local index space before reading
            seg = info.segment_of_row(jidx)
            d_elem = 0 if seg is None else seg.offset
            d_flow = 0 if seg is None else seg.flow_offset
            total = sum(
                state.element_instances.taken_flow_count(
                    scope_key, jidx - d_elem, f.idx - d_flow)
                for f in exe.flows
                if f.target_idx == jidx
            )
            if total:
                join_counts[jidx] = total
        return join_counts

    def _condition_slots(self, info: _DefInfo, merged: dict) -> dict[str, tuple] | None:
        """Prefetch the condition variables into device-slot key planes:
        numeric slots carry the float64 order key, string slots the interned
        id (the host document store ↔ device slot split, SURVEY §7(c)).
        None = this instance cannot ride the kernel (type mismatch or
        order-unsafe unknown string would diverge from host FEEL)."""
        from zeebe_tpu.ops.tables import f64_key_planes

        tables = self.registry.tables
        slots: dict[str, tuple] = {}
        # variables read by THIS definition's device-compiled conditions in
        # the SHARED lowering (a shared-set SlotMap clash may have downgraded
        # a gateway to K_HOST — its variables then need no prefetch and must
        # not gate admission)
        for name in tables.cond_vars_by_def[info.index]:
            v = merged.get(name)
            if tables.slot_map.kinds.get(name) == "str":
                if not isinstance(v, str):
                    return None
                key_hi, _known = tables.interner.order_key_of(v)
                # unknown strings get odd insertion-rank keys — exact
                # against every literal, and device programs never compare
                # two string slots (compile_condition types "str" only
                # opposite a literal), so collisions between two unknown
                # keys are unreachable
                slots[name] = (key_hi, 0)
                continue
            if not _is_numeric(v):
                return None
            if type(v) is int and not _f64_exact(v):
                # host FEEL compares Python ints exactly; an int beyond 2^53
                # would round into its float64 neighbor's order key and the
                # device could diverge (e.g. EQ against the neighbor)
                return None
            value = float(v)
            if value != value:  # NaN has no order key
                return None
            slots[name] = f64_key_planes(value)
        return slots

    def _admit_resume(self, cmd, instances, admitted_pis: set[int],
                      pi_key: int, resume_key: int,
                      kind: str, head_docs: list, extra_variables: dict | None,
                      require_op: int, wave: dict) -> _Admitted | None:
        """Shared admission for resume commands (job complete, timer trigger,
        message correlate). A command whose instance is a call-activity child
        first tries the TOP ancestor instance — when the caller's definition
        inlines the child, the resume reconstructs the WHOLE family as one
        device instance and the call return executes on the device; otherwise
        it falls back to the child-frame instance (the child's own tables,
        with a sequential continuation into the parent)."""
        state = self.engine.state
        root_meta = state.element_instances.get(pi_key)
        if root_meta is None:
            return None
        top_pi, top_meta, ancestors = pi_key, root_meta, []
        for _ in range(_INLINE_MAX_DEPTH + 1):
            ppi = top_meta["value"].get("parentProcessInstanceKey", -1)
            if ppi < 0:
                break
            m = state.element_instances.get(ppi)
            if m is None:
                break
            top_pi, top_meta = ppi, m
            ancestors.append(ppi)
        if top_pi != pi_key:
            adm = self._admit_resume_at(
                cmd, instances, admitted_pis, top_pi, top_meta, resume_key,
                kind, head_docs, extra_variables, require_op, wave,
                require_segments=True)
            if adm is not None:
                return adm
        return self._admit_resume_at(
            cmd, instances, admitted_pis, pi_key, root_meta, resume_key,
            kind, head_docs, extra_variables, require_op, wave,
            extra_family=ancestors)

    def _admit_resume_at(self, cmd, instances, admitted_pis: set[int],
                         pi_key: int, root_meta, resume_key: int,
                         kind: str, head_docs: list,
                         extra_variables: dict | None, require_op: int,
                         wave: dict,
                         require_segments: bool = False,
                         extra_family: list | None = None,
                         ) -> _Admitted | None:
        state = self.engine.state
        if pi_key in admitted_pis:
            return None  # same-instance conflict: next group
        if "tenantId" in root_meta["value"]:
            # non-default-tenant instances stay on the sequential path end to
            # end (the kernel's value builders emit default-tenant shapes)
            return None
        def_key = root_meta["value"].get("processDefinitionKey", -1)
        info = self._wave_def_info(wave, def_key)
        if info is None:
            return None
        if require_segments and not info.segments:
            # the hop to the top ancestor only pays off when the caller
            # inlines its call activities — otherwise the call element is a
            # host escape and reconstruction would decline at it anyway
            return None
        rebuilt = self._reconstruct(pi_key, info, resume_key, root_meta)
        if rebuilt is None:
            return None
        (tokens, resume, root, wait_docs, wait_keys, scope_keys,
         join_counts, family, mi_parked) = rebuilt
        family = [pi_key, *family, *(extra_family or ())]
        if any(p in admitted_pis for p in family):
            return None  # a family member is already resumed in this group
        resume_el = info.exe.elements[resume.elem_idx]
        has_cond_slots = bool(
            self.registry.tables.cond_vars_by_def[info.index])
        if extra_variables:
            if kind == "j" and resume_el.outputs:
                # the sequential job-complete merges ALL completion variables
                # into the element's LOCAL scope when the element has output
                # mappings (processors.py merge_local) — they die with the
                # element and must never reach the root condition slots
                extra_variables = None
            elif has_cond_slots:
                # default propagation: each variable lands on the nearest
                # scope that already holds it locally, else the root. A
                # mid-chain local (input-mapped element scope, or a
                # sub-process scope written by an inner output mapping)
                # would absorb the variable where the device's root-slot
                # prefetch cannot see it — decline those resumes. With no
                # device-compiled conditions (always the case for inlined
                # call definitions) there are no slots to invalidate.
                for name in extra_variables:
                    scope = state.variables.find_scope_with(resume_key, name)
                    if scope is not None and scope != pi_key:
                        return None
        if self.registry.tables.kernel_op[info.index, resume.elem_idx] != require_op:
            return None
        merged = state.variables.collect(pi_key)
        merged.update(extra_variables or {})
        slots = self._condition_slots_cached(wave, info, merged)
        if slots is None:
            return None
        mi_left: dict[int, int] = {}
        mi_cards: dict[int, int] = {}
        if info.mi_inner:
            tables = self.registry.tables
            seq_rows = {
                row for row in info.mi_inner
                if tables.mi_sequential[info.index, row]
            }
            # cards are needed for burst-reachable unspawned bodies AND for
            # parked sequential bodies (the respawn remainder); parallel
            # parked bodies are fully spawned (mi_left 0, no card needed)
            needed = set(info.mi_reach.get(resume.elem_idx, ()))
            needed |= {r for r in mi_parked if r in seq_rows}
            if needed:
                # a collection variable shadowed by ANY live scope/token
                # local would make the root-merged prediction diverge from
                # the sequential collect(body) — decline those
                local_names: set[str] = set()
                for t in tokens:
                    local_names.update(state.variables.locals_of(t.key))
                for _idx, k in scope_keys.items():
                    if k != pi_key:
                        local_names.update(state.variables.locals_of(k))
                for row in needed:
                    ast = info.exe.elements[row].multi_instance.input_collection.ast
                    if isinstance(ast, _FeelVar) and ast.path[0] in local_names:
                        return None
                cards = self._predict_mi_cards(info, needed, merged)
                if cards is None:
                    return None
                mi_cards = cards
            for row, lc in mi_parked.items():
                if row in seq_rows:
                    card = mi_cards.get(row)
                    if card is None or lc is None or lc > card:
                        return None
                    mi_left[row] = card - lc
                else:
                    mi_left[row] = 0  # parallel: fully spawned at rest
            for row in needed:
                if row not in mi_parked:
                    mi_left[row] = mi_cards[row]
        inst = _Inst(idx=len(instances), info=info, new=False, pi_key=pi_key,
                     tokens=tokens, join_counts=join_counts, slots=slots,
                     family_pis=family, mi_left=mi_left, mi_cards=mi_cards)
        # timer-touching bursts ARE templatable: clock-derived dueDate /
        # deadline fields in the admission docs are extracted as ("fp", i)
        # roles by the fingerprint walk (so instances with different due
        # dates share a template), and freshly computed due dates in the
        # burst itself resolve as ("clock", delta) roles
        # locals of EVERY parked token: input mappings create them, but so
        # can SetVariables(local=true) on any element instance — and output
        # mappings / variable propagation read them, so the template
        # fingerprint must pin them all (root-scope variables are pinned
        # via ``merged`` already)
        mapped_locals = [
            sorted(state.variables.locals_of(t.key).items()) for t in tokens
        ]
        # sub-process scope locals (written e.g. by inner output mappings):
        # mapping/condition evaluation reads them through collect(), so two
        # instances differing only there must fingerprint apart
        scope_locals = [
            (idx, sorted(state.variables.locals_of(k).items()))
            for idx, k in sorted(scope_keys.items()) if idx != 0
        ]
        return _Admitted(
            cmd=cmd, inst=inst, resume_token=resume, kind=kind,
            fp_docs=[
                cmd.record.value,
                *head_docs,
                root["value"],
                [t.value for t in tokens],
                wait_docs,
                sorted(merged.items()),
                sorted(join_counts.items()),
                mapped_locals,
                scope_locals,
            ],
            templatable=pi_key not in self.engine.await_results,
            wait_keys=wait_keys,
        )

    def _admit_job_complete(self, cmd, instances, admitted_pis,
                            wave) -> _Admitted | None:
        state = self.engine.state
        job = state.jobs.get(cmd.record.key)
        if job is None:
            return None  # sequential path writes the NOT_FOUND rejection
        return self._admit_resume(
            cmd, instances, admitted_pis,
            pi_key=job.get("processInstanceKey", -1),
            resume_key=job.get("elementInstanceKey", -1),
            kind="j",
            head_docs=[job],
            extra_variables=cmd.record.value.get("variables"),
            require_op=K_TASK,
            wave=wave,
        )

    def _admit_timer_trigger(self, cmd, instances, admitted_pis,
                             wave) -> _Admitted | None:
        state = self.engine.state
        timer = state.timers.get(cmd.record.key)
        if timer is None:
            return None  # sequential path writes the NOT_FOUND rejection
        eik = timer.get("elementInstanceKey", -1)
        if eik < 0:
            return None  # timer start event → host path
        instance = state.element_instances.get(eik)
        if instance is None:
            return None  # element gone; host records TRIGGERED only
        # only the waiting catch element itself (route_trigger's first
        # branch); boundary / event-based-gateway routing stays on the host
        if timer.get("targetElementId") != instance["value"].get("elementId"):
            return None
        return self._admit_resume(
            cmd, instances, admitted_pis,
            pi_key=instance["value"].get("processInstanceKey", -1),
            resume_key=eik,
            kind="t",
            head_docs=[timer],
            extra_variables=None,
            require_op=K_CATCH,
            wave=wave,
        )

    def _admit_message_correlate(self, cmd, instances, admitted_pis,
                                 wave) -> _Admitted | None:
        state = self.engine.state
        value = cmd.record.value
        eik = value.get("elementInstanceKey", -1)
        sub = state.process_message_subscriptions.get(eik, value.get("messageName", ""))
        instance = state.element_instances.get(eik)
        if sub is None or instance is None:
            return None  # at-least-once redelivery → host no-op path
        if sub.get("targetElementId") != instance["value"].get("elementId"):
            return None  # boundary / event-based gateway → host
        return self._admit_resume(
            cmd, instances, admitted_pis,
            pi_key=instance["value"].get("processInstanceKey", -1),
            resume_key=eik,
            kind="m",
            head_docs=[sub],
            extra_variables=value.get("variables"),
            require_op=K_CATCH,
            wave=wave,
        )

    # -- device run ----------------------------------------------------------

    @staticmethod
    def _pow2(n: int) -> int:
        p = 8
        while p < n:
            p *= 2
        return p

    def _build_group_arrays(self, admitted: list[_Admitted]):
        """Host (numpy) arrays for one admitted group, padded to the shape
        bucket: (arrays dict, I, T), or None when the geometry exceeds the
        event-packing bounds. Shared by the single-device path and the
        mesh-runner path (which treats the group as one shard block)."""
        from zeebe_tpu.ops.automaton import PACK_MAX_ELEMENTS, PACK_MAX_TOKENS

        tables = self.registry.tables
        insts = [a.inst for a in admitted]
        n_real = len(insts)
        n_tokens = sum(max(1, len(i.tokens)) for i in insts)
        # two shape buckets: XLA specializes on shapes, not occupancy, so
        # groups are padded to either the small (64) or the max-group
        # geometry — exactly two compilations per table set, small groups
        # don't pay the big bucket's device time, and a warmup at each bucket
        # keeps compilation out of steady state. Token-heavy groups overflow
        # to the next power of two (rare; costs one extra compile).
        small = min(64, self._pow2(self.max_group))
        I = small if n_real <= small else self._pow2(self.max_group)
        # token pool: the set's static live-width bound (tables.token_width)
        # sizes it exactly — a one-token-per-instance set runs at T == I
        # instead of 4x, which is pure device-time savings; with no sound
        # bound (parallel split on a cycle) keep the legacy 4x factor.
        # Overflow is detected and falls back, so an undersized pool is a
        # perf bug, not a correctness one — but the bound is sound, so it
        # cannot happen for bounded sets.
        width = tables.token_width
        # parallel MI fan-out is dynamic: admission-predicted cardinalities
        # bound the extra live tokens beyond the static analysis
        mi_extra = sum(sum(i.mi_cards.values()) for i in insts if i.mi_cards)
        if width > 0:
            T = self._pow2(max(width * I, n_tokens))
        else:
            T = self._pow2(max(4 * I, 4 * n_tokens, n_tokens + mi_extra + I))
        E = tables.max_elements
        S = tables.num_slots
        if T > PACK_MAX_TOKENS or E >= PACK_MAX_ELEMENTS:
            # the bit-packed event tensor carries dest in 16 bits and elem in
            # 14 — geometries beyond that (absurd for real workloads) take
            # the sequential path instead of corrupting the decode
            logger.warning("kernel geometry T=%d E=%d exceeds event packing "
                           "bounds; falling back", T, E)
            return None

        elem = np.full(T, -1, np.int32)
        phase = np.zeros(T, np.int32)
        inst_arr = np.zeros(T, np.int32)
        def_of = np.zeros(I, np.int32)
        var_slots = np.zeros((I, S, 2), np.int32)
        join_counts = np.zeros((I, E), np.int32)
        mi_left = np.zeros((I, E), np.int32)
        done = np.zeros(I, np.bool_)
        done[n_real:] = True  # padding rows must never report newly_done

        slot = 0
        for i in insts:
            def_of[i.idx] = i.info.index
            for name, v in i.slots.items():
                var_slots[i.idx, tables.slot_map.names[name]] = v
            for jidx, count in i.join_counts.items():
                join_counts[i.idx, jidx] = count
            for row, n in i.mi_left.items():
                mi_left[i.idx, row] = n
            if i.new:
                i.tokens = [_Token(slot=slot, elem_idx=int(tables.start_elem[i.info.index]),
                                   key=-1, value={})]
                elem[slot] = i.tokens[0].elem_idx
                phase[slot] = _PHASE_AT
                inst_arr[slot] = i.idx
                slot += 1
            else:
                for tok in i.tokens:
                    tok.slot = slot
                    elem[slot] = tok.elem_idx
                    phase[slot] = tok.phase
                    inst_arr[slot] = i.idx
                    slot += 1
        arrays = {
            "elem": elem, "phase": phase, "inst": inst_arr, "def_of": def_of,
            "var_slots": var_slots, "join_counts": join_counts,
            "mi_left": mi_left, "done": done,
        }
        return arrays, I, T

    def _start_kernel(self, pg: "_PendingGroup") -> None:
        """Stage 1 of the split device run: build the group arrays and
        DISPATCH the first chunk asynchronously (JAX async dispatch) — the
        caller overlaps host work with the device compute before calling
        ``_await_kernel``. Mesh groups stay synchronous (the runner's submit
        blocks), so they only record the build."""
        built = self._build_group_arrays(pg.admitted)
        if built is None:
            pg.failed = True
            pg.fail_reason = "geometry-bounds"
            return
        pg.arrays, pg.I, pg.T = built
        pg.tables = self.registry.tables
        if self.mesh_runner is not None:
            pg.mesh = True
            return

        import time as _time

        # link-aware backend choice: the identical program, on the device
        # where (link + compute) is cheapest for this shape bucket. The
        # bucket carries the table-set CONTENT digest: different deployed
        # sets are different programs with different compute costs (and
        # compiles), and the digest — unlike id() — cannot alias a reused
        # allocation after a redeploy recompile, and lets partitions with
        # equal sets share cost observations through the shared router.
        pg.bucket = (self.registry.tables_fingerprint, pg.I, pg.T)
        dev = None
        if self.router is not None:
            if pg.canary:
                # a canary must probe the SUSPECT device: pin the
                # accelerator rather than ask choose(), whose quarantine
                # host-ward bias (route_threshold_s=+inf) would send the
                # canary to the host — where it trivially byte-matches
                # the host oracle and re-proves nothing
                dev = self.router.accel_device()
            if dev is None:
                dev = self.router.choose(pg.bucket)
        pg.dev = dev
        if dev is not None:
            pg.pipeline_chunks = getattr(dev, "platform", "cpu") != "cpu"
        else:
            import jax

            pg.pipeline_chunks = jax.default_backend() != "cpu"
        # shadow sampling decided BEFORE dispatch: only sampled groups pay
        # the fetched-row retention (canaries are forced-shadow)
        pg.shadow = pg.canary or self._shadow_sampled()
        t0 = _time.perf_counter()
        try:
            chaos = _DEVICE_CHAOS
            if chaos is not None:
                chaos.dispatch_fault()
            self._dispatch_first_chunk(pg)
        except Exception as exc:  # noqa: BLE001 — containment: a device
            # failure (chaos-injected or real) must degrade to the host
            # path, never poison the pump
            self._contain_device_failure(pg, exc, where="dispatch")
            return
        # device_elapsed feeds the router's cost model: it must cover only
        # dispatch + fetch/decode windows, never the host work the caller
        # overlaps between them
        pg.device_elapsed = _time.perf_counter() - t0

    def _await_kernel(self, pg: "_PendingGroup") -> list[dict] | None:
        """Stage 2: block on the in-flight device run (or submit the mesh
        request) and return the decoded per-step events, None on fallback."""
        import time as _time

        if pg.failed:
            return None
        if pg.mesh:
            from zeebe_tpu.parallel.mesh_runner import GroupRequest

            t0 = _time.perf_counter()
            result = self.mesh_runner.submit(GroupRequest(
                device_tables=self.registry.device_tables,
                config=pg.tables.kernel_config,
                tables_fingerprint=self.registry.tables_fingerprint,
                arrays=pg.arrays,
                num_instances=pg.I,
                num_tokens=pg.T,
                max_steps=self.max_steps,
                chunk_steps=self.chunk_steps,
            ))
            submit_dur = _time.perf_counter() - t0
            pg.device_elapsed += submit_dur
            if result.steps is None:
                pg.fail_reason = "mesh-dispatch-error"
                logger.warning("mesh kernel dispatch errored; falling back")
            elif not result.quiesced:
                pg.fail_reason = "mesh-no-quiesce"
                logger.warning("mesh kernel group did not quiesce; falling back")
            elif result.overflow:
                pg.fail_reason = "mesh-token-overflow"
                logger.warning("mesh kernel token pool overflow (T=%d); falling back", pg.T)
            # the mesh submit seam span (ISSUE 19): ROADMAP item 1's
            # fused-dispatch refactor changes exactly this window, so it
            # must arrive measurable — one span per submit on the wave's
            # group trace, outcome included so declined submits are visible
            tracer = self._tracer
            if tracer.enabled and pg.admitted:
                group_trace = (f"{self._partition_id}:"
                               f"g{pg.admitted[0].cmd.position}")
                # group spans bypass head sampling — they carry the
                # substitution intervals for every sampled command
                tracer.emit(
                    group_trace, "kernel.mesh_submit", submit_dur,
                    self._partition_id, parent="processor.kernel_group",
                    attrs={"instances": pg.I, "tokens": pg.T,
                           "outcome": pg.fail_reason or "ok"})
            if pg.fail_reason:
                return None
            return result.steps

        t0 = _time.perf_counter()
        try:
            steps = self._complete_device_run(pg)
        except Exception as exc:  # noqa: BLE001 — containment: a mid-group
            # fetch failure or watchdog-expired stall abandons the group
            self._contain_device_failure(pg, exc, where="fetch")
            pg.device_elapsed += _time.perf_counter() - t0
            return None
        pg.device_elapsed += _time.perf_counter() - t0
        if self.router is not None and pg.dev is not None and steps is not None:
            # failed runs (non-quiescence, pool overflow) fall back to the
            # sequential path; their pathological wall times say nothing
            # about the backend's steady-state group cost
            run_key = (pg.bucket, pg.dev)
            self.router.record(pg.bucket, pg.dev, pg.device_elapsed,
                               first_run=run_key not in self._runs_seen)
            self._runs_seen.add(run_key)
        return steps

    @staticmethod
    def _observe_compile(I: int, T: int, seconds: float) -> None:
        """Feed one first-dispatch wall time into the XLA compile telemetry
        (observability/profiler.py): the histogram is labeled by geometry
        bucket, the counter classifies hit/miss against the persistent-cache
        threshold. Telemetry must never take a dispatch down."""
        try:
            from zeebe_tpu.observability.profiler import observe_compile

            observe_compile(f"I{I}xT{T}", seconds)
        except Exception:  # noqa: BLE001
            pass

    def _group_state(self, pg: "_PendingGroup", dev) -> dict:
        """The group's initial kernel state dict: the host-filled arrays
        plus cached zero planes. Must be called inside ``_device_ctx(dev)``
        — the zero planes must materialize in the placement context, or a
        routed accelerator's cache entry would hold default-device arrays
        and pay the transfer the cache exists to eliminate.

        Fresh per-group zero planes are IDENTICAL every group: cache the
        immutable device constants per (device, I) bucket — each jnp.zeros
        call otherwise costs a dispatch (~0.1ms × 5 per group adds up at
        small group sizes); the key carries the device because the link
        router alternates a bucket between host and accelerator and planes
        cached on one device must not leak into a group running on the
        other. The real (host-filled) arrays convert inside the jit call
        itself. Shared by the dispatch path and the shadow oracle — both
        must start from byte-identical state."""
        import jax.numpy as jnp

        I = pg.I
        arrays = pg.arrays
        zeros = self._zero_state.get((dev, I))
        if zeros is None:
            zeros = {
                "incident": jnp.zeros(I, jnp.bool_),
                "transitions": jnp.zeros((), jnp.int32),
                "jobs_created": jnp.zeros((), jnp.int32),
                "completed": jnp.zeros((), jnp.int32),
                "overflow": jnp.zeros((), jnp.bool_),
            }
            self._zero_state[(dev, I)] = zeros
        return {
            "elem": arrays["elem"],
            "phase": arrays["phase"],
            "inst": arrays["inst"],
            "def_of": arrays["def_of"],
            "var_slots": arrays["var_slots"],
            "join_counts": arrays["join_counts"],
            "mi_left": arrays["mi_left"],
            "done": arrays["done"],
            **zeros,
        }

    def _dispatch_first_chunk(self, pg: "_PendingGroup") -> None:
        from zeebe_tpu.ops.automaton import run_collect

        dev, I = pg.dev, pg.I
        pg.config = pg.tables.kernel_config
        pg.dt = self.registry.device_tables_for(dev)
        with _device_ctx(dev):
            state = self._group_state(pg, dev)
            # JAX async dispatch: the call returns with the device still
            # computing; the first host transfer (in _complete_device_run)
            # is the synchronization point
            # compile seam: the FIRST dispatch per (table-set content, shape
            # bucket, device) is where jit tracing + lowering + XLA compile
            # (or the persistent-cache load) happen synchronously — time
            # that call; later dispatches of the same geometry are tracing-
            # cache hits and stay untimed
            compile_key = (pg.bucket, None if dev is None
                           else getattr(dev, "id", dev))
            first_dispatch = compile_key not in self._compiles_seen
            if first_dispatch:
                import time as _time

                t_compile = _time.perf_counter()
            with _profiler_annotation("zeebe.kernel_chunk.first"):
                pg.run = run_collect(pg.dt, state, n_steps=self.chunk_steps,
                                     config=pg.config)
            if first_dispatch:
                self._compiles_seen.add(compile_key)
                self._observe_compile(pg.I, pg.T,
                                      _time.perf_counter() - t_compile)

    def _complete_device_run(self, pg: "_PendingGroup"):
        from zeebe_tpu.ops.automaton import run_collect, unpack_events

        # chunked device loop: one dispatch + ONE host transfer per chunk of
        # lock-steps (vs two transfers per step). Quiesced states are fixed
        # points of step(), so a chunk may harmlessly over-run past
        # quiescence. (The router keeps this path off accelerators whose
        # measured link floor would dominate the chunk fetches.)
        # Double-buffered from the second chunk on (accelerators only — see
        # _PendingGroup.pipeline_chunks): chunk k+1 dispatches off chunk k's
        # device-side carry BEFORE chunk k's host transfer, so the device
        # computes while the host decodes. The first chunk never prefetches —
        # groups that quiesce immediately (the common case for small resume
        # bursts) would pay a wasted chunk of device compute.
        chunk = self.chunk_steps
        T, I = pg.T, pg.I
        steps: list[dict] = []
        overflow = False
        FO = pg.tables.out_target.shape[2]
        state, packed = pg.run
        nxt = None
        max_chunks = max(1, self.max_steps // chunk)
        hit_quiescence = False
        for k in range(max_chunks):
            if pg.pipeline_chunks and k >= 1 and k + 1 < max_chunks:
                with _device_ctx(pg.dev), \
                        _profiler_annotation("zeebe.kernel_chunk.prefetch"):
                    nxt = run_collect(pg.dt, state, n_steps=chunk,
                                      config=pg.config)
            flat = self._fetch_rows(pg, packed, k)
            pg.chunks_run = k + 1
            # per row: T*(2+FO) packed event ints + (active, overflow) tail
            events_host = flat[:, :-2].reshape(chunk, T, 2 + FO)
            active = flat[:, -2]
            # overflow is cumulative in device state; with run_collect's
            # early exit the rows past quiescence are unwritten zeros, so
            # any written row carrying the bit is the signal
            overflow = overflow or bool(flat[:, -1].any())
            # steps after quiescence emit nothing — truncate so the host
            # decoder never walks empty tail steps
            quiesced = np.flatnonzero(active == 0)
            keep = int(quiesced[0]) + 1 if quiesced.size else chunk
            for s in range(keep):
                steps.append(unpack_events(events_host[s], I))
            if quiesced.size:
                hit_quiescence = True
                break  # a prefetched over-run chunk is simply never fetched
            if nxt is not None:
                state, packed = nxt
                nxt = None
            elif k + 1 < max_chunks:
                # last iteration dispatches nothing: a non-quiescing group is
                # about to fall back, and the chunk would never be fetched
                with _device_ctx(pg.dev), \
                        _profiler_annotation("zeebe.kernel_chunk"):
                    state, packed = run_collect(pg.dt, state, n_steps=chunk,
                                                config=pg.config)
        if not hit_quiescence:
            pg.fail_reason = "no-quiesce"
            logger.warning("kernel group did not quiesce in %d steps; falling back", self.max_steps)
            return None
        if bool(overflow):
            pg.fail_reason = "token-overflow"
            logger.warning("kernel token pool overflow (T=%d); falling back", T)
            return None
        return steps

    # -- device-fault defense (ISSUE 15) --------------------------------------

    def _fetch_rows(self, pg: "_PendingGroup", packed, chunk_index: int):
        """The ONE device→host ingestion point for kernel results: every
        fetched chunk of packed event rows passes through here before
        decode. The chaos seam (stalls, partial-chunk failures, result
        corruption) and the dispatch watchdog live exactly here; sampled
        groups additionally retain the rows for shadow comparison."""
        import jax

        chaos = _DEVICE_CHAOS

        def fetch():
            if chaos is not None:
                chaos.fetch_fault(chunk_index)
            return jax.device_get(packed)

        deadline_ms = self.dispatch_timeout_ms
        # the watchdog thread-hop is paid only where it can pay off: on a
        # real accelerator (a tunnel can wedge) or under the chaos plane —
        # the plain host XLA path keeps its direct, zero-overhead fetch
        if deadline_ms > 0 and (chaos is not None or pg.pipeline_chunks):
            flat = _watchdog_call(fetch, deadline_ms / 1000.0)
        else:
            flat = fetch()
        if chaos is not None:
            # device_get may hand back a read-only view; corruption needs a
            # writable copy (chaos-only cost, never on the clean path)
            flat = np.array(flat)
            token = chaos.corrupt_rows(flat, chunk_index)
            if token is not None:
                pg.corrupt_tokens.append(token)
        if pg.shadow:
            pg.raw_rows.append(flat)
        return flat

    def _contain_device_failure(self, pg: "_PendingGroup", exc,
                                where: str) -> None:
        """Containment: a dispatch exception, compile failure, or watchdog-
        expired stall abandons the group with a TYPED reason — the caller
        falls back to the sequential host path inside the same pump pass
        (byte-identical by the template-shadow discipline), the health
        ladder hears about it, and any chaos-injected corruption riding
        the abandoned group is reported caught (its rows are discarded)."""
        kind = ("device-wedged" if isinstance(exc, DeviceWedgedError)
                else "device-dispatch-error")
        pg.failed = True
        pg.fail_reason = kind
        chaos = _DEVICE_CHAOS
        if chaos is not None and pg.corrupt_tokens:
            for token in pg.corrupt_tokens:
                chaos.note_caught(token, "contained")
            pg.corrupt_tokens = []
        logger.warning("device failure contained at %s (%s): %r — group "
                       "host re-executed", where, kind, exc)
        self.health.note_fault(kind, detail=f"{where}: {exc!r}"[:200])

    def _shadow_sampled(self) -> bool:
        """Deterministic seeded sampling stream for shadow verification:
        one decision per dispatched group, boosted while SUSPECT. Counter-
        hash based (no ``random`` module — kernel-path decisions must be
        reproducible for a fixed seed + group sequence)."""
        rate = self.shadow_sample_rate
        if rate <= 0:
            return False
        cfg = self.health.cfg
        from zeebe_tpu.engine.device_health import SUSPECT

        if self.health.state == SUSPECT:
            rate = min(1.0, rate * cfg.suspect_shadow_boost)
        if rate >= 1.0:
            return True
        import zlib

        self._shadow_seq += 1
        h = zlib.crc32(
            f"{cfg.shadow_seed}:{self.accounting.partition}:"
            f"{self._shadow_seq}".encode("ascii"))
        return (h % 1_000_000) < rate * 1_000_000

    def _shadow_execute(self, pg: "_PendingGroup"):
        """Re-execute the group's kernel program on the HOST backend from
        the same initial arrays — the known-answer oracle for shadow
        verification and quarantine canaries. Runs the identical jitted
        program with the identical chunking, WITHOUT the chaos/watchdog
        seam (the oracle path must not be faultable), and returns
        (steps, rows) for byte-for-byte comparison.

        Honest caveat (docs/device-faults.md): the oracle assumes the host
        engine/XLA-CPU path is correct — it detects *divergence*, and the
        host result is the one trusted. On a host-default process the
        \"device\" and the oracle share a backend; the seam still catches
        everything injected between fetch and decode (the chaos plane's
        corruption model), which is what the gate proves."""
        import jax

        from zeebe_tpu.ops.automaton import run_collect, unpack_events

        router = self.router
        host_dev = None
        if router is not None and getattr(router, "enabled", False):
            host_dev = router._host
        dt = (self.registry.device_tables_for(host_dev)
              if host_dev is not None else self.registry.device_tables)
        config = pg.tables.kernel_config
        chunk = self.chunk_steps
        T, I = pg.T, pg.I
        FO = pg.tables.out_target.shape[2]
        steps: list[dict] = []
        rows: list = []
        max_chunks = max(1, self.max_steps // chunk)
        with _device_ctx(host_dev), \
                _profiler_annotation("zeebe.kernel_chunk.shadow"):
            state = self._group_state(pg, host_dev)
            run = run_collect(dt, state, n_steps=chunk, config=config)
        for k in range(max_chunks):
            carry, packed = run
            flat = jax.device_get(packed)
            rows.append(flat)
            events_host = flat[:, :-2].reshape(chunk, T, 2 + FO)
            active = flat[:, -2]
            quiesced = np.flatnonzero(active == 0)
            keep = int(quiesced[0]) + 1 if quiesced.size else chunk
            for s in range(keep):
                steps.append(unpack_events(events_host[s], I))
            if quiesced.size:
                return steps, rows
            if k + 1 < max_chunks:
                with _device_ctx(host_dev), \
                        _profiler_annotation("zeebe.kernel_chunk.shadow"):
                    run = run_collect(dt, carry, n_steps=chunk, config=config)
        # the oracle did not quiesce: the group is genuinely pathological —
        # raise so the caller abandons it (sequential host re-execution)
        raise RuntimeError(
            f"shadow oracle did not quiesce in {self.max_steps} steps")

    def _verify_steps(self, pg: "_PendingGroup", steps):
        """Sampled shadow verification: compare the device's fetched result
        rows byte-for-byte against the host oracle BEFORE anything from
        this group enters the group transaction. On mismatch the device
        result is quarantined — the HOST result is decoded and committed
        instead, so a silently-corrupting device can never reach the
        replicated log — and the health ladder latches SUSPECT. Returns
        the steps to materialize (None → abandon the group)."""
        import time as _time

        health = self.health
        health.note_shadow_check()
        t0 = _time.perf_counter()
        try:
            shadow_steps, shadow_rows = self._shadow_execute(pg)
        except Exception as exc:  # noqa: BLE001 — oracle failure: abandon
            # the group rather than commit an unverified device result; the
            # failed canary is noted ONCE, by finish_group's decline branch
            # (the same seam that notes containment-declined canaries)
            self._contain_device_failure(pg, exc, where="shadow")
            return None
        pg.t_shadow = _time.perf_counter() - t0
        rows = pg.raw_rows
        match = (len(rows) == len(shadow_rows)
                 and all(np.array_equal(a, b)
                         for a, b in zip(rows, shadow_rows)))
        if match:
            if pg.canary:
                health.note_canary(True)
            return steps
        chaos = _DEVICE_CHAOS
        if chaos is not None and pg.corrupt_tokens:
            for token in pg.corrupt_tokens:
                chaos.note_caught(token, "shadow")
            pg.corrupt_tokens = []
        self.shadow_quarantined += 1
        health.note_shadow_mismatch(
            detail=f"I={pg.I} T={pg.T} deviceChunks={len(rows)} "
                   f"oracleChunks={len(shadow_rows)}")
        if pg.canary:
            health.note_canary(False, detail="shadow mismatch")
        logger.warning(
            "shadow verification MISMATCH (I=%d T=%d): device result "
            "quarantined, host oracle result committed", pg.I, pg.T)
        return shadow_steps

    def device_status(self) -> dict:
        """The ``device`` block under ``kernelCoverage`` on /health and
        /cluster/status: ladder state + shadow/canary counters."""
        return {**self.health.status(),
                "shadowQuarantinedGroups": self.shadow_quarantined}

    # -- materialization ------------------------------------------------------

    def process_group(self, cmds, make_builder: Callable[[], Any]) -> tuple[list, list]:
        """Pull commands from the ``cmds`` iterator while they admit (lazy: a
        non-admittable head costs one log read, not a full peek), run the
        kernel, and materialize each admitted command's record burst — either
        through a burst template (fast path: patched bytes + state deltas) or
        through the Writers/appliers slow path (which doubles as template
        capture). Returns (admitted_cmds, results) where each result is a
        ProcessingResultBuilder or a PreparedBurst; empty lists mean the
        caller should process the head command sequentially.

        Must run inside the partition's open db transaction. The synchronous
        begin+finish composition; the pipelined processor calls the halves
        itself and overlaps host work between them."""
        return self.finish_group(self.begin_group(cmds), make_builder)

    def begin_group(self, cmds, speculative: bool = False) -> _PendingGroup | None:
        """Admit a group and dispatch its first device chunk asynchronously.
        Returns None when the head command is not admittable (sequential
        traffic). Must run inside the partition's open db transaction, and
        the same transaction must stay open through ``finish_group``.

        ``speculative`` (ISSUE 17, cross-wave double buffering): the
        processor is beginning wave k+1 inside wave k's still-open
        transaction, right after wave k materialized — admission reads the
        post-wave overlay, which is byte-identical to the committed state
        the next round's transaction will open over. A speculative begin is
        silent on decline (no fallback counters, no typed host notes, no
        quarantine reroute accounting): the group may never be consumed, so
        the NEXT round's authoritative scan owns all accounting. It also
        never claims a canary slot — under quarantine the ladder's one-
        probe-per-interval discipline belongs to the real scan."""
        import time as _time

        if speculative and (self.mesh_runner is not None
                            or self.health.is_quarantined()):
            # mesh has its own submit pipeline; a quarantined device gets
            # exactly the canary probes the health ladder schedules, never
            # an extra speculative dispatch
            return None

        # device health gating (ISSUE 15): while QUARANTINED every group is
        # host-routed (typed accounting) except the periodic canary — ONE
        # group per interval dispatched under FORCED shadow verification (a
        # known-answer probe: the host oracle is the answer, so a wrong
        # canary cannot commit wrong bytes). Mesh dispatch has its own
        # killable probe (PR 7) and is not gated here.
        canary = False
        if self.mesh_runner is None and self.health.is_quarantined():
            if self.health.canary_due():
                canary = True
            else:
                head = next(iter(cmds), None)
                if head is None:
                    return None  # end-of-log probe, not a reroute
                self.fallbacks += 1
                self.accounting.note_host("device-quarantined",
                                          self._definition_of(head.record))
                self.health.note_host_reroute()
                return None

        t0 = _time.perf_counter()
        instances: dict[int, _Inst] = {}
        # pi_key conflict index: one command per instance per group; a set
        # keeps admission O(1) instead of O(group) per command
        admitted_pis: set[int] = set()
        admitted: list[_Admitted] = []
        # per-wave admission memo (definition lookups, segment freshness,
        # condition slot planes): admission runs inside one open transaction
        # over state nothing mutates until materialization, so everything it
        # derives from state alone is stable for the whole wave
        wave: dict = {}
        head_cmd = None
        for cmd in cmds:
            if head_cmd is None:
                head_cmd = cmd
            adm = self._admit(cmd, instances, admitted_pis, wave)
            if adm is None:
                break
            instances[adm.inst.idx] = adm.inst
            if adm.inst.pi_key is not None and adm.inst.pi_key >= 0:
                admitted_pis.add(adm.inst.pi_key)
            admitted_pis.update(adm.inst.family_pis)
            admitted.append(adm)
            if len(admitted) >= self.max_group:
                break
        if not admitted:
            if canary:
                # the claimed canary slot never dispatched: un-claim it so
                # the next admittable group can probe immediately instead
                # of waiting out an interval the device never saw
                self.health.release_canary()
            if speculative:
                # nothing speculatively admittable — no accounting: the next
                # round's real scan re-encounters this head and notes it once
                return None
            if head_cmd is None:
                # the candidate iterator was EMPTY — an end-of-log probe, not
                # a fallback (ISSUE 7: these probes were counted as
                # "head-not-admittable" and made mesh_serving p1 report 4
                # phantom fallbacks per run)
                return None
            # the head command is not kernel-admittable (deploys, unknown
            # defs, non-default tenants, …): normal sequential traffic, but
            # counted — WITH the head's kind — so BENCH separates ordinary
            # sequential commands (a deployment, a message publish) from a
            # regression where an admittable kind stopped admitting
            self.fallbacks += 1
            rec = head_cmd.record
            self.accounting.note_host(
                f"head-not-admittable:{rec.value_type.name}.{rec.intent.name}",
                self._definition_of(rec),
            )
            return None
        pg = _PendingGroup(admitted)
        pg.canary = canary
        pg.t_admit = _time.perf_counter() - t0
        self._start_kernel(pg)
        return pg

    def finish_group(self, pg: _PendingGroup | None,
                     make_builder: Callable[[], Any]) -> tuple[list, list]:
        """Block on the in-flight device run and materialize the bursts.
        ([], []) → the caller should process the head command sequentially."""
        import time as _time

        if pg is None:
            return [], []
        steps = self._await_kernel(pg)
        if steps is not None and not pg.mesh and pg.shadow:
            # the validation/shadow seam (ISSUE 15): the ONLY way a device
            # result may proceed toward the group transaction when sampled
            # — on mismatch the host oracle's steps come back instead
            steps = self._verify_steps(pg, steps)
        if steps is None:
            # the whole group declined at dispatch; the HEAD is what the
            # caller processes sequentially next (the rest re-admit), so
            # exactly one host record is noted, with the typed reason
            self.fallbacks += 1
            chaos = _DEVICE_CHAOS
            if chaos is not None and pg.corrupt_tokens:
                # a typed decline (no-quiesce/overflow a corruption itself
                # provoked) discards the fetched rows: caught by containment
                for token in pg.corrupt_tokens:
                    chaos.note_caught(token, "contained")
                pg.corrupt_tokens = []
            if pg.canary:
                if pg.fail_reason in ("device-dispatch-error",
                                      "device-wedged"):
                    # the probe reached the device and the device failed:
                    # a real failed canary, the recovery streak resets
                    self.health.note_canary(
                        False, detail=pg.fail_reason)
                else:
                    # a host-side decline (geometry-bounds, no-quiesce,
                    # token-overflow) never proved anything about the
                    # device — un-claim the slot so the next admittable
                    # group probes immediately, and leave the verified
                    # streak alone (a pathological GROUP must not hold
                    # the device in quarantine)
                    self.health.release_canary()
            head = pg.admitted[0]
            self.accounting.note_host(
                pg.fail_reason or "group-error",
                head.inst.info.exe.process_id,
            )
            return [], []

        t0 = _time.perf_counter()
        admitted = pg.admitted
        results = []
        for adm in admitted:
            ops = self._cascade_ops(adm.inst, steps)
            results.append(self._materialize(adm, ops, make_builder))
        pg.t_materialize = _time.perf_counter() - t0
        self.groups_processed += 1
        self.commands_processed += len(admitted)
        return [a.cmd for a in admitted], results

    def note_group_success(self, pg: _PendingGroup) -> None:
        """Per-definition kernel-path accounting for one materialized group
        (coverage gauge + parity gate), batched per definition to bound
        gauge writes. Called by the processor AFTER the group's transaction
        commits — noting inside ``finish_group`` would double-count the
        group when a post-materialization commit failure rolls it back and
        the same commands re-admit on the next pump."""
        defs: dict[str, int] = {}
        for adm in pg.admitted:
            pid = adm.inst.info.exe.process_id
            defs[pid] = defs.get(pid, 0) + 1
        for pid, n in defs.items():
            self.accounting.note_kernel(pid, n)
        # clean-group evidence for the health ladder: a committed group
        # with no fault steps SUSPECT back toward HEALTHY after the
        # configured quiet window
        self.health.note_group_ok()

    # -- template routing ----------------------------------------------------

    def _materialize(self, adm: _Admitted, ops: list, make_builder):
        from zeebe_tpu.engine import burst_templates as bt
        from zeebe_tpu.engine.writers import Writers

        template = None
        key = None
        if self.use_templates and adm.templatable:
            # request presence is part of the burst SHAPE (Writers.respond
            # only emits a client response when request_id >= 0), so it must
            # be in the key — the ids themselves are patched roles
            fp_bytes = adm.fp_bytes
            if fp_bytes is None:  # admission-time fingerprint unavailable
                fp_bytes, adm.fp_values, adm.fp_pinned = self._fingerprint(adm)
            # segment child-def keys are in the key: a refresh_segments swap
            # reuses the info index, and a stale template would patch the OLD
            # child definition's baked constants into new-binding bursts
            key = (adm.kind, adm.inst.info.index,
                   tuple(s.child_def_key for s in adm.inst.info.segments),
                   adm.cmd.record.request_id >= 0, tuple(ops), fp_bytes)
            template = self._templates.get(key, _MISSING)
            if template is _MISSING:
                template = None
                miss = True
            else:
                miss = False
                # move-to-end so eviction (oldest-half sweep) drops cold
                # entries, not the hottest templates
                del self._templates[key]
                self._templates[key] = template
            if template is not None and not self.audit_templates:
                self.template_hits += 1
                return self._instantiate(template, adm)
        else:
            miss = False

        # slow path (also: template capture on first miss, audit on hit)
        capture = self.use_templates and adm.templatable and miss
        auditing = template is not None and self.audit_templates
        txn = self.engine.state.db.require_transaction()
        state = self.engine.state
        role_map, wrapped = self._roles_for(adm)
        mints: list[int] = []
        orig_next_key = state.next_key
        if capture or auditing:
            def tagged_next_key():
                v = orig_next_key()
                mints.append(v)
                return v
            state.next_key = tagged_next_key
            txn.capture = cap_log = []
            # collect clock-derived values (dueDate = clock + clock-free
            # delta) the engine computes during this run — they become
            # ("clock", delta) roles; a poison note (now()-entangled delta)
            # declines the template
            bt.clock_note_begin()
        builder = make_builder()
        writers = Writers(builder, self.engine.appliers)
        try:
            if adm.inst.new:
                self._materialize_creation(wrapped, adm, ops, writers, builder)
            else:
                self._materialize_resume(wrapped, adm, ops, writers, builder)
            if any(f.record.is_command and not f.processed
                   for f in builder.follow_ups):
                self._drain_host_escapes(wrapped.position, builder)
        finally:
            if capture or auditing:
                state.next_key = orig_next_key
                txn.capture = None
                clock_notes, clock_poison = bt.clock_note_end()
        if capture:
            self.template_misses += 1
            allowed = adm.fp_pinned if adm.fp_pinned is not None else set()
            if adm.inst.info.segments:
                # called-definition keys resolve mid-burst (CallActivity
                # latest-binding) and are sound template constants: the
                # admission freshness check pins the binding, and the keys
                # are part of the template cache key
                allowed = allowed | {
                    s.child_def_key for s in adm.inst.info.segments
                }
            if clock_poison:
                role_map = None
            for i, v in enumerate(mints):
                if role_map is None:
                    break
                if v in role_map:
                    role_map = None  # role collision → not templatable
                    break
                role_map[v] = ("mint", i)
            for i, v in enumerate(adm.fp_values or ()):
                if role_map is None:
                    break
                if v in role_map:
                    # a clock-field value colliding with a key/mint would
                    # patch the wrong quantity — decline instead
                    role_map = None
                    break
                role_map[v] = ("fp", i)
            # delta → value of this run's clock notes: capture validation
            # resolves against the exact values the slow path wrote (immune
            # to a clock tick mid-run)
            clock_values: dict[int, int] = {}
            for v, delta in clock_notes:
                if role_map is None:
                    break
                if v < _ROLE_VALUE_MIN:
                    # a small (test-clock) due date cannot be a patchable
                    # role and would bake stale — decline the template
                    role_map = None
                    break
                existing = role_map.get(v)
                if existing is not None and existing != ("clock", delta):
                    role_map = None  # same value, conflicting meaning
                    break
                if v in allowed or clock_values.get(delta, v) != v:
                    # fingerprint-pinned elsewhere, or two different values
                    # for one delta (clock ticked between two same-duration
                    # timers): ambiguous — decline
                    role_map = None
                    break
                role_map[v] = ("clock", delta)
                clock_values[delta] = v
            if role_map is not None:
                roles_ctx = bt.Roles(role_map, allowed=allowed)
                try:
                    tmpl = bt.build_template(
                        builder, cap_log, roles_ctx, len(mints),
                        state.partition_id,
                    )
                    bt.validate_template(
                        tmpl, builder,
                        self._resolver(adm, mints, clock_values))
                    self._store_template(key, tmpl)
                except bt.NotTemplatable as exc:
                    logger.debug("trace not templatable: %s", exc)
                    self._store_template(key, None)
            else:
                self._store_template(key, None)
        elif auditing:
            audit_clock_values: dict[int, int] = {}
            conflict = clock_poison
            for v, delta in clock_notes:
                if audit_clock_values.setdefault(delta, v) != v:
                    # the wall clock ticked between two same-duration timer
                    # creations in this run: the single delta→value map can't
                    # represent both, so the audit would assert spuriously —
                    # skip it (capture declines this shape, so no template
                    # was ever built from such a run)
                    conflict = True
                    break
            if conflict:
                self.template_audit_skips += 1
            else:
                self.template_audits += 1
                self._audit_template(template, adm, builder, cap_log, mints,
                                     audit_clock_values)
        return builder

    def _drain_host_escapes(self, source_position: int, builder,
                            limit: int | None = None,
                            end_idx: int | None = None,
                            reserved_keys: set | None = None) -> None:
        """Process follow-up commands left unprocessed (flows into K_HOST
        elements, and whatever those spawn) with the sequential engine, FIFO,
        within the batch budget — so the flattened burst matches the
        sequential batch loop (stream/processor.py _batch_process) byte for
        byte: same record order, same positions, same processed flags, same
        source position. ``limit=1`` drains exactly one command (the trace
        interleaves it at the escaped token's arrival position);
        ``end_idx`` drains only commands appended before that follow-up
        index (a device token's processing must first flush escape cascades
        that precede its ACTIVATE in the queue); the final unbounded call
        flushes whatever remains. Commands beyond the budget stay
        unprocessed on the log and the stream processor picks them up as
        the next commands, exactly like a sequential batch that hit its
        limit."""
        from zeebe_tpu.logstreams.log_stream import LoggedRecord

        budget = self.max_commands_in_batch - 1 - sum(
            1 for f in builder.follow_ups if f.record.is_command and f.processed
        )
        if limit is not None:
            budget = min(budget, limit)
        scan = 0
        while budget > 0:
            follow_up = None
            bound = len(builder.follow_ups) if end_idx is None else end_idx
            while scan < bound:
                entry = builder.follow_ups[scan]
                if entry.record.is_command and not entry.processed:
                    if (reserved_keys
                            and entry.record.value_type == ValueType.PROCESS_INSTANCE
                            and int(entry.record.intent) == int(PI.COMPLETE_ELEMENT)
                            and entry.record.key in reserved_keys):
                        # a device MI body's completion command: its "done"
                        # op pairs with it (device-side drain detection) —
                        # draining it here would double-complete the body
                        scan += 1
                        continue
                    follow_up = entry
                    break
                scan += 1
            if follow_up is None:
                return
            follow_up.processed = True
            budget -= 1
            logged = LoggedRecord(
                record=follow_up.record, position=-1,
                source_position=source_position, processed=True,
            )
            self.engine.process(logged, builder)
            scan += 1

    def _store_template(self, key, template) -> None:
        cache = self._templates
        if len(cache) >= self._template_cache_limit:
            for k in list(cache)[: self._template_cache_limit // 2]:
                del cache[k]
        cache[key] = template

    # document fields whose int values are clock-derived and copied verbatim
    # by the slow path (never transformed into non-int outputs): they are
    # extracted as per-command template inputs (("fp", i) roles) instead of
    # pinned in the fingerprint, so e.g. timer-carrying instances with
    # different due dates share one burst template
    _FP_FIELDS = frozenset(("dueDate", "deadline"))

    def _fingerprint(self, adm: _Admitted) -> tuple[bytes, list[int], set[int]]:
        """(byte image, extracted clock-field values, pinned large ints) of
        the instance-scoped documents the slow path reads. Role values (keys
        known at admission) and whitelisted clock-derived fields are
        normalized away so two commands differing only in key identity / due
        dates fingerprint equal; everything else is pinned byte-for-byte —
        the returned pinned set is exactly the template's sound constant
        allowance (Roles.allowed)."""
        roles = {}
        inst = adm.inst
        if inst.pi_key >= _ROLE_VALUE_MIN:
            roles[inst.pi_key] = "p"
        for j, tok in enumerate(inst.tokens):
            if tok.key >= _ROLE_VALUE_MIN:
                roles[tok.key] = f"t{j}"
        if adm.cmd.record.key >= _ROLE_VALUE_MIN:
            roles[adm.cmd.record.key] = "k"
        for j, wk in enumerate(adm.wait_keys or ()):
            if wk >= _ROLE_VALUE_MIN:
                roles.setdefault(wk, f"w{j}")
        if _native_pack_fingerprint is not None:
            return _native_pack_fingerprint(adm.fp_docs, roles, self._FP_FIELDS)
        return _py_pack_fingerprint(adm.fp_docs, roles, self._FP_FIELDS)

    def _roles_for(self, adm: _Admitted):
        """(value→role map, role-tagged command) for capture/audit runs."""
        from zeebe_tpu.engine.burst_templates import RoleInt

        role_map: dict[int, tuple] = {}
        inst = adm.inst
        if inst.pi_key >= _ROLE_VALUE_MIN:
            role_map[inst.pi_key] = ("pi",)
        for j, tok in enumerate(inst.tokens):
            if tok.key >= _ROLE_VALUE_MIN:
                role_map[tok.key] = ("tok", j)
        for j, wk in enumerate(adm.wait_keys or ()):
            if wk >= _ROLE_VALUE_MIN:
                role_map.setdefault(wk, ("wait", j))
        cmd = adm.cmd
        rec = cmd.record
        if rec.key >= _ROLE_VALUE_MIN:
            role_map.setdefault(rec.key, ("cmd_key",))
        wrapped_rec = rec.replace(
            request_stream_id=RoleInt(rec.request_stream_id, ("req_stream",)),
            request_id=RoleInt(rec.request_id, ("req_id",)),
            operation_reference=RoleInt(rec.operation_reference, ("opref",)),
        )
        from zeebe_tpu.logstreams import LoggedRecord

        wrapped = LoggedRecord(
            record=wrapped_rec,
            position=RoleInt(cmd.position, ("source_position",)),
            source_position=cmd.source_position,
            processed=cmd.processed,
        )
        return role_map, wrapped

    def _resolver(self, adm: _Admitted, mints: list[int],
                  clock_values: dict[int, int] | None = None):
        """``clock_values`` (delta → value) is passed on capture-validation
        and audit runs so ("clock", delta) roles resolve to the exact values
        the slow path just wrote; live instantiation recomputes them from
        the engine clock."""
        cmd = adm.cmd
        inst = adm.inst
        toks = inst.tokens
        fp_values = adm.fp_values or ()
        wait_keys = adm.wait_keys or ()
        # one clock snapshot per resolver: a burst's payload, state rows, and
        # responses must all carry the SAME dueDate for one logical timer
        # even if the wall clock ticks mid-instantiation
        clock_base = (self.engine.clock_millis() if clock_values is None
                      else None)

        def resolve(role: tuple) -> int:
            kind = role[0]
            if kind == "mint":
                return mints[role[1]]
            if kind == "fp":
                return fp_values[role[1]]
            if kind == "clock":
                delta = role[1]
                if clock_values is not None:
                    v = clock_values.get(delta)
                    if v is not None:
                        return v
                    return self.engine.clock_millis() + delta
                return clock_base + delta
            if kind == "wait":
                return wait_keys[role[1]]
            if kind == "source_position":
                return cmd.position
            if kind == "req_id":
                return cmd.record.request_id
            if kind == "req_stream":
                return cmd.record.request_stream_id
            if kind == "opref":
                return cmd.record.operation_reference
            if kind == "cmd_key":
                return cmd.record.key
            if kind == "pi":
                return inst.pi_key
            if kind == "tok":
                return toks[role[1]].key
            raise KeyError(role)

        return resolve

    def _instantiate(self, template, adm: _Admitted):
        from zeebe_tpu.engine.burst_templates import PreparedBurst

        state = self.engine.state
        mints = state.bulk_mint(template.mint_count)
        resolve = self._resolver(adm, mints)
        buf = template.instantiate_payload(resolve)
        txn = state.db.require_transaction()
        template.apply_state(txn, resolve)
        responses = template.build_responses(resolve)
        return PreparedBurst(
            buf=buf,
            pos_offsets=template.pos_offsets,
            ts_offsets=template.ts_offsets,
            count=template.count,
            responses=responses,
            has_pending_commands=template.has_pending_commands,
            job_types=template.job_types,
        )

    def _audit_template(self, template, adm: _Admitted, builder, cap_log,
                        mints, clock_values: dict[int, int]) -> None:
        """Shadow-check a template hit against the slow path just executed."""
        from zeebe_tpu.engine import burst_templates as bt
        from zeebe_tpu.state.db import ColumnFamilyCode
        import struct as _struct

        if len(mints) != template.mint_count:
            raise AssertionError(
                f"template audit: mint count {template.mint_count} != slow path {len(mints)}"
            )
        resolve = self._resolver(adm, mints, clock_values)
        bt.validate_template(template, builder, resolve)
        # state ops: template replay vs the slow path's capture log, collapsed
        # to the final op per key exactly as build_template does (minus the
        # KEY column family, which the template replaces with bulk mint)
        final: dict[bytes, tuple] = {}
        for op, key, value in cap_log:
            if _struct.unpack_from(">H", key, 0)[0] == int(ColumnFamilyCode.KEY):
                continue
            if key in final:
                del final[key]
            final[key] = (op, value)
        expected = [(op, key, value) for key, (op, value) in final.items()]

        class _Recorder:
            def __init__(self):
                self.ops = []

            def put(self, key, value):
                self.ops.append(("put", key, value))

            def delete(self, key):
                self.ops.append(("del", key, None))

        rec = _Recorder()
        template.apply_state(rec, resolve)
        if len(rec.ops) != len(expected):
            raise AssertionError(
                f"template audit: {len(rec.ops)} state ops vs slow path {len(expected)}"
            )
        for (op_a, key_a, val_a), (op_b, key_b, val_b) in zip(rec.ops, expected):
            if op_a != op_b or key_a != key_b or (op_a == "put" and val_a != val_b):
                raise AssertionError(
                    f"template audit: state op mismatch {op_a} {key_a!r} vs {op_b} {key_b!r}"
                )
        # responses
        got = template.build_responses(resolve)
        want = ([] if builder.response is None else [(False, builder.response)]) + [
            (True, r) for r in builder.extra_responses
        ]
        if len(got) != len(want):
            raise AssertionError("template audit: response count mismatch")
        for (extra_a, rec_a, stream_a, req_a), (extra_b, resp) in zip(got, want):
            if (extra_a != extra_b or stream_a != resp.request_stream_id
                    or req_a != resp.request_id or rec_a != resp.record):
                raise AssertionError("template audit: response mismatch")

    def _mark_last_command_processed(self, builder) -> None:
        for entry in reversed(builder.follow_ups):
            if entry.record.is_command:
                entry.processed = True
                return

    def _materialize_creation(self, cmd, adm: _Admitted, ops, writers, builder) -> None:
        from zeebe_tpu.engine.bpmn import _pi_value

        engine = self.engine
        state = engine.state
        inst = adm.inst
        exe = inst.info.exe
        # the sequential creation processor writes CREATED + response +
        # ACTIVATE(process) command + seed VARIABLE events — reuse it verbatim
        creation = engine._processors[
            (ValueType.PROCESS_INSTANCE_CREATION, int(ProcessInstanceCreationIntent.CREATE))
        ]
        mark = len(builder.follow_ups)
        creation(cmd, writers)
        # locate the minted instance key + the ACTIVATE(process) command
        activate_cmd = None
        for entry in builder.follow_ups[mark:]:
            if entry.record.is_command and entry.record.value_type == ValueType.PROCESS_INSTANCE:
                activate_cmd = entry
                break
        if activate_cmd is None:  # rejection (definition vanished mid-group)
            return
        activate_cmd.processed = True
        inst.pi_key = activate_cmd.record.key
        process_el = exe.root
        value = _pi_value(dict(activate_cmd.record.value), process_el)
        writers.append_event(inst.pi_key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATING, value)
        if inst.info.root_esp_start_idxs:
            # root event-sub-process start subscriptions open between
            # ACTIVATING and ACTIVATED — the sequential behavior runs
            # verbatim (byte parity by construction). A pre-validation
            # failure (admission raced a variable change — can't happen for
            # creations, defensive) leaves the root ACTIVATING with the
            # incident written, same as the sequential path.
            if not engine.bpmn._open_scope_event_subscriptions(
                    inst.pi_key, value, exe, process_el, writers):
                return
        writers.append_event(inst.pi_key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
        # ACTIVATE(start) — mirror BpmnProcessor._write_activate
        start = exe.elements[exe.none_start_of(0)]
        tok = inst.tokens[0]
        tok.key = state.next_key()
        tok.value = self._child_value(value, start, inst.pi_key)
        writers.append_command(tok.key, ValueType.PROCESS_INSTANCE,
                               PI.ACTIVATE_ELEMENT, tok.value)
        if self.registry.tables.kernel_op[inst.info.index, start.idx] == K_HOST:
            # host-escaped none start (e.g. output mappings): the device
            # token parks silently; _materialize's post-trace drain hands the
            # whole instance to the sequential engine
            return
        self._mark_last_command_processed(builder)
        self._emit_ops(inst, ops, writers, builder, cmd.position)

    _RESUME_HEADS = {
        "j": (ValueType.JOB, int(JobIntent.COMPLETE)),
        "t": (ValueType.TIMER, int(TimerIntent.TRIGGER)),
        "m": (ValueType.PROCESS_MESSAGE_SUBSCRIPTION,
              int(ProcessMessageSubscriptionIntent.CORRELATE)),
    }

    def _materialize_resume(self, cmd, adm: _Admitted, ops, writers, builder) -> None:
        """Resume commands (job complete / timer trigger / message correlate)
        share one shape: the sequential head processor writes its own events
        (JOB COMPLETED + variables, TIMER TRIGGERED, …SUBSCRIPTION CORRELATED
        + variables + ack side effect) and ends by routing a COMPLETE_ELEMENT
        command at the parked element; the cascade emits what processing that
        command would have."""
        engine = self.engine
        head = engine._processors[self._RESUME_HEADS[adm.kind]]
        head(cmd, writers)
        self._mark_last_command_processed(builder)  # the COMPLETE_ELEMENT cmd
        self._emit_ops(adm.inst, ops, writers, builder, cmd.position)

    @staticmethod
    def _child_value(scope_value: dict, element: ExecutableElement, scope_key: int) -> dict:
        """Mirror BpmnProcessor._write_activate's record value exactly."""
        return {
            "bpmnProcessId": scope_value["bpmnProcessId"],
            "version": scope_value["version"],
            "processDefinitionKey": scope_value["processDefinitionKey"],
            "processInstanceKey": scope_value["processInstanceKey"],
            "elementId": element.id,
            "flowScopeKey": scope_key,
            # an element with loop characteristics is entered through its
            # multi-instance body wrapper (host-escaped on device)
            "bpmnElementType": (
                BpmnElementType.MULTI_INSTANCE_BODY.name
                if element.multi_instance is not None
                else element.element_type.name
            ),
            "bpmnEventType": element.event_type.name,
        }

    # -- device-step decoding: trace extraction + emission -------------------
    #
    # The old single-pass cascade is split in two: _cascade_ops walks the
    # device steps once and produces a route trace over *logical* token ids
    # (slot- and key-free, so it doubles as the burst-template cache key);
    # _emit_ops interprets a trace through the Writers in exactly the order
    # the one-pass walk used to emit.

    def _cascade_ops(self, inst: _Inst, steps) -> list:
        """Trace one instance's route through the device steps.

        Ops (logical token ids; initial tokens are 0..len(tokens)-1, flow
        targets get ids in creation order):
          ("arrive", l, elem)      task activated, token parks
          ("done", l, elem)        parked task completes (job completed)
          ("pass", l, elem)        full activate+complete pass
          ("nomatch", l, elem)     exclusive gateway with no matching flow
          ("flow", l, elem, fo, new_l)  flow slot fo taken; new_l == -1 when
                                   no token was placed (join arrival merged)
          ("hostarr", l, elem)     token reached a host-escaped element: the
                                   emitter drains its ACTIVATE sequentially
                                   at exactly this FIFO position
          ("complete",)            the process instance completed
        """
        tables = self.registry.tables
        d = inst.info.index
        exe = inst.info.exe
        ops: list = []
        # live: [logical id, slot, elem_idx]
        live = [[l, t.slot, t.elem_idx] for l, t in enumerate(inst.tokens)]
        next_l = len(live)
        # logical id → step index at which a host-escaped token "arrives"
        # (the device parks it silently; the trace needs the position)
        host_arrive: dict[int, int] = {}
        done_emitted = False
        for si, ev in enumerate(steps):
            if done_emitted or not live:
                break
            T = ev["elem"].shape[0]
            additions: list = []
            for tok in list(live):
                l, s, e = tok
                if l in host_arrive:
                    if host_arrive[l] == si:
                        ops.append(("hostarr", l, e))
                        del host_arrive[l]
                        live.remove(tok)
                    continue
                if ev["inst"][s] != inst.idx or ev["elem"][s] != e:
                    continue  # slot reused after this token died (stale entry)
                if ev["task_arrive"][s]:
                    if tables.kernel_op[d, e] == K_SCOPE:
                        # scope arrival: the inner start token's placement
                        # rides flow slot 0 (see step()'s spawn channel); the
                        # scope token itself stays parked
                        dest = int(ev["dest"][s, 0])
                        nl = next_l
                        next_l += 1
                        start_idx = int(tables.scope_start[d, e])
                        additions.append([nl, dest, start_idx])
                        ops.append(("scopearr", l, e, nl))
                        if tables.kernel_op[d, start_idx] == K_HOST:
                            host_arrive[nl] = si + 1
                    elif tables.kernel_op[d, e] == K_MI:
                        # MI body arrival: the device spawns child tokens (one
                        # per step) purely for occupancy/drain tracking; their
                        # activation records ride the sequential FIFO drain
                        # (the body's _activate delegation queues the inner
                        # ACTIVATE commands unprocessed), so the spawned
                        # device tokens are NOT tracked here — only the body
                        ops.append(("miarr", l, e))
                    else:
                        ops.append(("arrive", l, e))
                elif ev["task_done"][s] or ev["full_pass"][s]:
                    ops.append(("done" if ev["task_done"][s] else "pass", l, e))
                    for fo in range(ev["take_mask"].shape[1]):
                        if not ev["take_mask"][s, fo]:
                            continue
                        dest = int(ev["dest"][s, fo])
                        if dest < T:
                            fid = int(tables.out_flow_idx[d, e, fo])
                            # fid < 0: synthetic link-jump edge — the target
                            # lives in out_target, no model flow exists
                            target_idx = (int(tables.out_target[d, e, fo])
                                          if fid < 0 else exe.flows[fid].target_idx)
                            nl = next_l
                            next_l += 1
                            additions.append([nl, dest, target_idx])
                            ops.append(("flow", l, e, fo, nl))
                            if tables.kernel_op[d, target_idx] == K_HOST:
                                host_arrive[nl] = si + 1
                        else:
                            ops.append(("flow", l, e, fo, -1))
                    live.remove(tok)
                elif ev["no_match"][s]:
                    ops.append(("nomatch", l, e))
                    live.remove(tok)
            live.extend(additions)
            if ev["newly_done"][inst.idx] and not done_emitted:
                ops.append(("complete",))
                done_emitted = True
        return ops

    def _emit_ops(self, inst: _Inst, ops: list, writers, builder,
                  source_position: int) -> None:
        """Interpret a trace, writing the instance's record burst in the
        sequential engine's FIFO follow-up order."""
        from zeebe_tpu.engine.bpmn import _pi_value

        state = self.engine.state
        tables = self.registry.tables
        exe = inst.info.exe
        d = inst.info.index
        toks: dict[int, _Token] = dict(enumerate(inst.tokens))
        mi_inner_rows = {v: k for k, v in inst.info.mi_inner.items()}
        # device MI body keys whose COMPLETE_ELEMENT commands the drain must
        # leave for the body's own "done" op (reconstructed bodies up front;
        # in-burst activations join at their miarr)
        reserved_keys: set[int] = {
            t.key for t in inst.tokens
            if tables.kernel_op[d, t.elem_idx] == K_MI and t.key >= 0
        }
        # pure-device traces (the common case) never need the FIFO drain —
        # skip its O(follow_ups) scans wholesale. MI traces always drain:
        # inner-child activations and respawns ride the sequential FIFO.
        has_escapes = any(
            o[0] in ("hostarr", "miarr")
            or (o[0] == "done" and o[2] in mi_inner_rows)
            for o in ops
        )
        for op in ops:
            kind = op[0]
            if kind == "complete":
                if has_escapes:
                    self._drain_host_escapes(source_position, builder,
                                             reserved_keys=reserved_keys)
                self._emit_process_completed(inst, writers, builder)
                continue
            if kind == "hostarr":
                # the escaped element's ACTIVATE is the first unprocessed
                # command (escapes drain in arrival order): hand it to the
                # sequential engine at exactly this FIFO position
                self._drain_host_escapes(source_position, builder, limit=1,
                                         reserved_keys=reserved_keys)
                continue
            l, e = op[1], op[2]
            tok = toks[l]
            element = exe.elements[e]
            value = _pi_value(tok.value, element)
            if has_escapes and kind in ("arrive", "pass", "scopearr", "miarr",
                                        "nomatch") and tok.act_idx >= 0:
                # FIFO: escape cascades whose commands were appended before
                # this token's ACTIVATE must emit first (the sequential batch
                # loop would have processed them before reaching it)
                self._drain_host_escapes(source_position, builder,
                                         end_idx=tok.act_idx,
                                         reserved_keys=reserved_keys)
            elif has_escapes and kind == "done":
                # a mid-trace completion (scope drain) appends its COMPLETE
                # command at the queue's end — everything pending goes first
                self._drain_host_escapes(source_position, builder,
                                         reserved_keys=reserved_keys)
            if kind == "miarr":
                # MI body activation: delegate to the sequential activation
                # wholesale (MultiInstanceBodyProcessor parity) — ACTIVATING,
                # collection evaluation, ACTIVATED, output-collection seed,
                # and the inner ACTIVATE commands, which stay UNPROCESSED:
                # the FIFO drain activates each child at its exact sequential
                # position while the device's spawned tokens (untracked here)
                # park at the inner row for drain accounting
                reserved_keys.add(tok.key)
                self.engine.bpmn._activate(tok.key, dict(tok.value), exe,
                                           element, writers)
                continue
            if kind == "arrive":
                if element.element_type == BpmnElementType.EVENT_BASED_GATEWAY:
                    # delegate to the sequential activation wholesale: its
                    # pre-validation/incident handling and subscribe-before-
                    # ACTIVATED ordering must match record for record
                    self.engine.bpmn._activate(tok.key, dict(tok.value), exe,
                                               element, writers)
                    continue
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_ACTIVATING, value)
                if element.inputs:
                    # input mappings create the element's local scope between
                    # ACTIVATING and the boundary subscriptions (mirror
                    # _activate's ordering; eligibility admits only safe
                    # expressions, so failure is unreachable — handled
                    # defensively by parking the element ACTIVATING exactly
                    # like the sequential incident path)
                    if not self.engine.bpmn._apply_input_mappings(
                            tok.key, value, element, writers,
                            context_key=value.get("flowScopeKey", -1)):
                        continue
                if element.boundary_idxs:
                    # boundary subscriptions attach between ACTIVATING and
                    # ACTIVATED (mirror BpmnProcessor._activate's ordering)
                    self.engine.bpmn._open_boundary_subscriptions(
                        tok.key, value, exe, element, writers
                    )
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_ACTIVATED, value)
                if element.element_type in (BpmnElementType.INTERMEDIATE_CATCH_EVENT,
                                            BpmnElementType.RECEIVE_TASK):
                    # mirror BpmnProcessor._activate's catch branch: open the
                    # wait state (timer / message subscription) on the host —
                    # expressions evaluate against live variable state, and a
                    # failure raises the same incident and parks the element
                    bpmn = self.engine.bpmn
                    if element.timer_duration is not None:
                        bpmn._create_timer(tok.key, value, element, element, writers)
                    elif element.signal_name is not None:
                        bpmn._open_signal_subscription(tok.key, value, element,
                                                       writers)
                    else:
                        bpmn._open_message_subscription(tok.key, value, element,
                                                        element, writers)
                else:
                    self._emit_job_created(inst, tok, element, writers)
            elif kind == "done":
                if e in mi_inner_rows:
                    # MI inner completion (job-complete resume): delegate to
                    # the sequential completion with the BODY element (it
                    # carries the loop characteristics) — COMPLETING, output
                    # collection element, sequential-collection validation,
                    # COMPLETED, and _on_mi_inner_completed's follow-up (the
                    # next inner ACTIVATE, or the body's COMPLETE_ELEMENT —
                    # both unprocessed: the respawn drains FIFO and the body
                    # command is reserved for the body's own "done" op)
                    body_el = exe.elements[mi_inner_rows[e]]
                    ei = state.element_instances.get(tok.key)
                    ivalue = dict(ei["value"]) if ei is not None else dict(tok.value)
                    self.engine.bpmn._complete(tok.key, ivalue, exe, body_el,
                                               writers)
                    continue
                if element.multi_instance is not None:
                    # MI body completion: the COMPLETE_ELEMENT command was
                    # appended by the last inner's completion cascade and
                    # reserved from the drain — pair with it here, then
                    # mirror _complete's is_mi_body tail (COMPLETING, output
                    # collection propagation, COMPLETED); the outgoing flows
                    # ride the device ("flow" ops)
                    for entry in builder.follow_ups:
                        if (entry.record.is_command and not entry.processed
                                and entry.record.value_type == ValueType.PROCESS_INSTANCE
                                and int(entry.record.intent) == int(PI.COMPLETE_ELEMENT)
                                and entry.record.key == tok.key):
                            entry.processed = True
                            break
                    else:
                        logger.error(
                            "MI body %s done on device without a pending "
                            "COMPLETE_ELEMENT — decode divergence", element.id)
                        continue
                    ei = state.element_instances.get(tok.key)
                    bvalue = _pi_value(
                        dict(ei["value"]) if ei is not None else dict(tok.value),
                        element)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_COMPLETING, bvalue)
                    mi = element.multi_instance
                    if mi.output_collection:
                        collection = state.variables.get_local(
                            tok.key, mi.output_collection)
                        if collection is not None:
                            self.engine.bpmn._write_variable(
                                writers, bvalue.get("flowScopeKey", -1),
                                bvalue, mi.output_collection, collection)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_COMPLETED, bvalue)
                    continue
                if element.element_type == BpmnElementType.PROCESS:
                    # child-root placeholder drained: the called process
                    # instance completes. Delegate to the sequential PROCESS
                    # completion wholesale — COMPLETING, subscription close,
                    # child locals, COMPLETED, then _on_process_completed's
                    # variable propagation into the caller plus the call
                    # activity's COMPLETE_ELEMENT command (which the call
                    # row's own "done" op pairs with one step later)
                    writers.append_command(tok.key, ValueType.PROCESS_INSTANCE,
                                           PI.COMPLETE_ELEMENT, {})
                    self._mark_last_command_processed(builder)
                    self.engine.bpmn._complete(tok.key, dict(tok.value), exe,
                                               element, writers)
                    self._mark_last_command_processed(builder)
                    continue
                if element.element_type == BpmnElementType.SUB_PROCESS:
                    # scope drain completes through an internal command, like
                    # the process root (mirror _check_scope_completion →
                    # COMPLETE_ELEMENT → _complete)
                    writers.append_command(tok.key, ValueType.PROCESS_INSTANCE,
                                           PI.COMPLETE_ELEMENT, {})
                    self._mark_last_command_processed(builder)
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_COMPLETING, value)
                if element.outputs:
                    # output mappings run between COMPLETING and the
                    # subscription close (mirror _complete's ordering).
                    # Eligibility admits only safe expressions, so failure
                    # is unreachable; if it ever happened the element stays
                    # COMPLETING with the incident, and the already-routed
                    # downstream tokens would diverge — log loudly.
                    if not self.engine.bpmn._apply_output_mappings(
                            tok.key, value, element, writers):
                        logger.error(
                            "output mapping failed on kernel path for %s — "
                            "routing already committed; incident raised",
                            element.id)
                        continue
                if element.boundary_idxs:
                    # mirror _complete: subscriptions close between COMPLETING
                    # and COMPLETED (TIMER CANCELED / subscription DELETED)
                    self.engine.bpmn._close_subscriptions(tok.key, value, writers)
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_COMPLETED, value)
            elif kind == "scopearr":
                seg = inst.info.call_segment(e)
                if seg is not None:
                    # call activity activation: delegate to the sequential
                    # CALL_ACTIVITY handler wholesale (ACTIVATING, ACTIVATED,
                    # the child root's ACTIVATE command, variable propagation
                    # events — CallActivityProcessor parity), then bind the
                    # spawned device token to the child-root command
                    mark = len(builder.follow_ups)
                    self.engine.bpmn._activate(tok.key, dict(tok.value), exe,
                                               element, writers)
                    child_entry = None
                    child_at = -1
                    for i in range(mark, len(builder.follow_ups)):
                        entry = builder.follow_ups[i]
                        if (entry.record.is_command
                                and entry.record.value_type == ValueType.PROCESS_INSTANCE):
                            child_entry, child_at = entry, i
                            break
                    if child_entry is None:
                        # incident (called definition vanished — admission
                        # freshness makes this unreachable): the device token
                        # parks forever and the sequential path owns the call
                        continue
                    child_entry.processed = True
                    toks[op[3]] = _Token(slot=-1, elem_idx=seg.root_row,
                                         key=child_entry.record.key,
                                         value=dict(child_entry.record.value),
                                         act_idx=child_at)
                    continue
                # embedded sub-process activation: ACTIVATING/ACTIVATED, then
                # the inner none-start activates via an internal command with
                # the scope instance as its flow scope (mirror _activate's
                # SUB_PROCESS branch → _write_activate). Child-root
                # placeholder rows (non-root PROCESS elements) share this
                # path: their element copy stamps the child process shape
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_ACTIVATING, value)
                if element.idx in inst.info.scope_esp_waits:
                    # child-root placeholder with root ESPs: open the start
                    # subscriptions between ACTIVATING and ACTIVATED via the
                    # sequential behavior verbatim (inlining admits only
                    # expression-free/static starts, so failure is
                    # unreachable on state identical to the sequential run)
                    if not self.engine.bpmn._open_scope_event_subscriptions(
                            tok.key, value, exe, element, writers):
                        logger.error(
                            "inlined child ESP subscription open failed for "
                            "%s — instance %s left ACTIVATING",
                            element.id, tok.key)
                        continue
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_ACTIVATED, value)
                start = exe.elements[element.child_start_idx]
                child_key = state.next_key()
                child_value = self._child_value(value, start, tok.key)
                writers.append_command(child_key, ValueType.PROCESS_INSTANCE,
                                       PI.ACTIVATE_ELEMENT, child_value)
                if tables.kernel_op[d, start.idx] == K_HOST:
                    # escaped inner start: the spawned device token parks
                    # silently; the drain owns the scope's inside from here
                    continue
                self._mark_last_command_processed(builder)
                toks[op[3]] = _Token(slot=-1, elem_idx=start.idx,
                                     key=child_key, value=child_value,
                                     act_idx=len(builder.follow_ups) - 1)
            elif kind == "pass":
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_ACTIVATING, value)
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_ACTIVATED, value)
                if element.script_expression is not None:
                    # expression script task: evaluate + write the result
                    # between ACTIVATED and COMPLETING, mirroring
                    # BpmnProcessor._activate's script branch. Eligibility
                    # admits only never-raises expressions, so failure is
                    # unreachable; if it ever happened the sequential path
                    # would raise an incident and the element would stay
                    # ACTIVATED — log loudly, since downstream device ops
                    # would then diverge.
                    context = state.variables.collect(tok.key)
                    try:
                        result = element.script_expression.evaluate(
                            context, self.engine.clock_millis)
                    except FeelEvalError:
                        logger.error(
                            "safe script expression raised for %s — "
                            "instance %s left ACTIVATED", element.id, tok.key)
                        continue
                    if element.script_result_variable:
                        self.engine.bpmn._write_variable(
                            writers, value.get("flowScopeKey", -1), value,
                            element.script_result_variable, result)
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_COMPLETING, value)
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_COMPLETED, value)
            elif kind == "flow":
                fo, new_l = op[3], op[4]
                fid = int(tables.out_flow_idx[d, e, fo])
                if fid < 0:
                    # synthetic link-jump edge: no SEQUENCE_FLOW_TAKEN — the
                    # catch activates directly (engine _complete link branch)
                    target_idx = int(tables.out_target[d, e, fo])
                else:
                    flow = exe.flows[fid]
                    target_idx = flow.target_idx
                    flow_value = {
                        "bpmnProcessId": value["bpmnProcessId"],
                        "version": value["version"],
                        "processDefinitionKey": value["processDefinitionKey"],
                        "processInstanceKey": value["processInstanceKey"],
                        "elementId": flow.id,
                        "flowScopeKey": value.get("flowScopeKey", -1),
                        "bpmnElementType": BpmnElementType.SEQUENCE_FLOW.name,
                        "bpmnEventType": BpmnEventType.UNSPECIFIED.name,
                    }
                    flow_key = state.next_key()
                    writers.append_event(flow_key, ValueType.PROCESS_INSTANCE,
                                         PI.SEQUENCE_FLOW_TAKEN, flow_value)
                if new_l >= 0:
                    target = exe.elements[target_idx]
                    child_key = state.next_key()
                    child_value = self._child_value(value, target,
                                                    value.get("flowScopeKey", -1))
                    writers.append_command(child_key, ValueType.PROCESS_INSTANCE,
                                           PI.ACTIVATE_ELEMENT, child_value)
                    if tables.kernel_op[d, target.idx] == K_HOST:
                        # host escape: leave the ACTIVATE unprocessed — the
                        # post-trace drain hands it (and its whole follow-up
                        # chain) to the sequential engine
                        continue
                    self._mark_last_command_processed(builder)
                    toks[new_l] = _Token(slot=-1, elem_idx=target.idx,
                                         key=child_key, value=child_value,
                                         act_idx=len(builder.follow_ups) - 1)
            elif kind == "nomatch":
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_ACTIVATING, value)
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_ACTIVATED, value)
                writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                     PI.ELEMENT_COMPLETING, value)
                incident_key = state.next_key()
                writers.append_event(
                    incident_key, ValueType.INCIDENT, IncidentIntent.CREATED,
                    {
                        "errorType": ErrorType.CONDITION_ERROR.name,
                        "errorMessage": (
                            "Expected at least one condition to evaluate to true, "
                            f"or to have a default flow at gateway '{element.id}'"
                        ),
                        "bpmnProcessId": value.get("bpmnProcessId", ""),
                        "processDefinitionKey": value.get("processDefinitionKey", -1),
                        "processInstanceKey": value.get("processInstanceKey", -1),
                        "elementId": value.get("elementId", ""),
                        "elementInstanceKey": tok.key,
                        "jobKey": -1,
                        "variableScopeKey": tok.key,
                    },
                )

    def _emit_job_created(self, inst: _Inst, tok: _Token, element: ExecutableElement,
                          writers) -> None:
        """Mirror BpmnProcessor._activate's job-worker task branch."""
        state = self.engine.state
        value = tok.value
        job_key = state.next_key()
        writers.append_event(
            job_key, ValueType.JOB, JobIntent.CREATED,
            {
                "type": inst.info.job_types[element.idx],
                "retries": inst.info.job_retries[element.idx],
                "worker": "",
                "deadline": -1,
                "variables": {},
                "customHeaders": element.task_headers,
                "elementId": element.id,
                "elementInstanceKey": tok.key,
                "processInstanceKey": value["processInstanceKey"],
                "processDefinitionKey": value["processDefinitionKey"],
                "processDefinitionVersion": value["version"],
                "bpmnProcessId": value["bpmnProcessId"],
                "errorMessage": "",
            },
        )

    def _emit_process_completed(self, inst: _Inst, writers, builder) -> None:
        """Mirror _check_scope_completion → COMPLETE_ELEMENT(process) →
        _complete(process) → _on_process_completed."""
        from zeebe_tpu.engine.bpmn import _pi_value

        state = self.engine.state
        bpmn = self.engine.bpmn
        root = state.element_instances.get(inst.pi_key)
        if root is None:
            return
        writers.append_command(inst.pi_key, ValueType.PROCESS_INSTANCE,
                               PI.COMPLETE_ELEMENT, {})
        self._mark_last_command_processed(builder)
        process_el = inst.info.exe.root
        value = _pi_value(dict(root["value"]), process_el)
        writers.append_event(inst.pi_key, ValueType.PROCESS_INSTANCE,
                             PI.ELEMENT_COMPLETING, value)
        if inst.info.root_esp_start_idxs:
            # mirror _complete: root ESP start subscriptions close when the
            # process leaves ACTIVATED
            bpmn._close_subscriptions(inst.pi_key, value, writers)
        child_locals = state.variables.locals_of(inst.pi_key)
        writers.append_event(inst.pi_key, ValueType.PROCESS_INSTANCE,
                             PI.ELEMENT_COMPLETED, value)
        bpmn._on_process_completed(inst.pi_key, value, child_locals or {}, writers)
        inst.done_emitted = True
