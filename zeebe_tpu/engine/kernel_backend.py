"""The device-kernel execution backend: batched command processing.

This is the seam BASELINE.json names: the automaton kernel
(zeebe_tpu.ops.automaton) registered behind the stream platform's
RecordProcessor SPI as the partition's batched execution engine. The stream
processor collects a group of committed commands, this backend advances every
touched process instance lock-step on the device, and the decoded results are
materialized as the *identical* record stream the sequential engine would have
written — same events, same intermediate processed commands, same keys, same
values — through the normal Writers, so appliers, replay, exporters, and
snapshots see no difference.

Reference seams: stream-platform/src/main/java/io/camunda/zeebe/stream/api/
RecordProcessor.java (the SPI), engine/src/main/java/io/camunda/zeebe/engine/
Engine.java:40 (the sequential implementation this shadows), and the
batchProcessing loop in ProcessingStateMachine.java:328-374 whose FIFO
follow-up order the materializer reproduces exactly.

Eligibility: a process definition rides the kernel when it lowers to device
tables (flat graph of tasks / exclusive / parallel gateways / none events with
numeric FEEL conditions — zeebe_tpu.ops.tables) and none of its elements need
host-only behaviors (io mappings, boundary events, timers, messages, scripts).
Commands of other definitions — and commands whose instances are not in a
reconstructable state — fall back to the sequential engine, command by
command, preserving exact semantics.

Known float caveat: condition programs evaluate in float32 on device while the
host FEEL evaluator uses float64 — comparisons within ~1e-7 of the boundary
can diverge. The reference has no analogous dual path; boundary-exact process
conditions should use integers.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from zeebe_tpu.models.bpmn.executable import ExecutableElement, ExecutableProcess
from zeebe_tpu.ops.tables import (
    _KERNEL_OP,
    ConditionNotCompilable,
    K_JOIN,
    K_TASK,
    ProcessTables,
    compile_tables,
)
from zeebe_tpu.protocol import ValueType
from zeebe_tpu.protocol.enums import BpmnElementType, BpmnEventType, ErrorType
from zeebe_tpu.protocol.intent import (
    IncidentIntent,
    JobIntent,
    ProcessInstanceCreationIntent,
    ProcessInstanceIntent as PI,
)

logger = logging.getLogger("zeebe_tpu.kernel_backend")

# token phases (mirrors zeebe_tpu.ops.automaton)
_PHASE_AT = 0
_PHASE_WAIT = 1
_PHASE_DONE = 2

_CANDIDATE_COMMANDS = {
    (ValueType.PROCESS_INSTANCE_CREATION, int(ProcessInstanceCreationIntent.CREATE)),
    (ValueType.JOB, int(JobIntent.COMPLETE)),
}


def _is_numeric(v: Any) -> bool:
    return isinstance(v, (bool, int, float)) and not isinstance(v, str)


def check_element_eligibility(exe: ExecutableProcess, el: ExecutableElement) -> bool:
    """True when the sequential engine's behavior for this element is exactly
    the kernel's opcode behavior (engine/…/processing/bpmn element processors
    vs ops/automaton masks)."""
    op = _KERNEL_OP.get(el.element_type)
    if op is None:
        return False
    if el.event_type not in (BpmnEventType.NONE, BpmnEventType.UNSPECIFIED):
        return False
    if el.inputs or el.outputs or el.boundary_idxs or el.multi_instance is not None:
        return False
    if el.native_user_task or el.called_decision_id or el.script_expression is not None:
        return False
    if (
        el.timer_duration is not None
        or el.timer_cycle is not None
        or el.timer_date is not None
        or el.message_name is not None
        or el.signal_name is not None
    ):
        return False
    if op == K_TASK:
        # job-worker semantics only, with deploy-time-constant type/retries
        if el.job_type is None or not el.job_type.is_static:
            return False
        if el.job_retries is not None and not el.job_retries.is_static:
            return False
    return True


@dataclass
class _DefInfo:
    index: int
    key: int
    exe: ExecutableProcess
    cond_var_names: frozenset[str]
    job_types: dict[int, str]  # element idx → static job type
    job_retries: dict[int, int]
    join_idxs: list[int]  # element idxs of K_JOIN gateways


class KernelRegistry:
    """Per-partition registry of kernel-eligible definitions sharing one
    compiled table set (ops/tables.compile_tables). Grows as deployments are
    first touched; recompiles the shared tables on growth (deploys are rare)."""

    def __init__(self, max_definitions: int = 64) -> None:
        self.max_definitions = max_definitions
        self._by_key: dict[int, _DefInfo] = {}
        self._ineligible: set[int] = set()
        self._infos: list[_DefInfo] = []
        self._tables: ProcessTables | None = None
        self._device = None

    def lookup(self, definition_key: int, exe: ExecutableProcess | None) -> _DefInfo | None:
        info = self._by_key.get(definition_key)
        if info is not None:
            return info
        if definition_key in self._ineligible or exe is None:
            return None
        if len(self._infos) >= self.max_definitions:
            return None
        if not all(check_element_eligibility(exe, el) for el in exe.elements[1:]):
            self._ineligible.add(definition_key)
            return None
        try:
            solo = compile_tables([exe])
        except ConditionNotCompilable:
            self._ineligible.add(definition_key)
            return None
        clock = lambda: 0  # noqa: E731 — static expressions ignore the clock
        job_types: dict[int, str] = {}
        job_retries: dict[int, int] = {}
        join_idxs: list[int] = []
        for el in exe.elements[1:]:
            if solo.kernel_op[0, el.idx] == K_TASK:
                job_types[el.idx] = el.job_type.evaluate({}, clock)
                job_retries[el.idx] = (
                    int(el.job_retries.evaluate({}, clock)) if el.job_retries is not None else 3
                )
            if solo.kernel_op[0, el.idx] == K_JOIN:
                join_idxs.append(el.idx)
        info = _DefInfo(
            index=len(self._infos),
            key=definition_key,
            exe=exe,
            cond_var_names=frozenset(solo.slot_map.names),
            job_types=job_types,
            job_retries=job_retries,
            join_idxs=join_idxs,
        )
        self._infos.append(info)
        self._by_key[definition_key] = info
        self._tables = None  # recompile shared set lazily
        self._device = None
        return info

    @property
    def tables(self) -> ProcessTables:
        if self._tables is None:
            self._tables = compile_tables([i.exe for i in self._infos])
        return self._tables

    @property
    def device_tables(self):
        if self._device is None:
            from zeebe_tpu.ops.automaton import DeviceTables

            self._device = DeviceTables.from_tables(self.tables)
        return self._device


@dataclass
class _Token:
    slot: int
    elem_idx: int
    key: int  # element instance key (-1 until minted at materialization)
    value: dict  # the record value the ACTIVATE command carried
    phase: int = _PHASE_AT


@dataclass
class _Inst:
    idx: int  # row in the device batch
    info: _DefInfo
    new: bool  # created by this group (vs reconstructed)
    pi_key: int = -1
    meta: dict | None = None  # creation: resolved definition metadata
    tokens: list[_Token] = field(default_factory=list)
    join_counts: dict[int, int] = field(default_factory=dict)  # elem idx → arrivals
    slots: dict[str, float] = field(default_factory=dict)  # condition variables
    done_emitted: bool = False


@dataclass
class _Admitted:
    cmd: Any  # LoggedRecord
    inst: _Inst
    resume_token: _Token | None = None  # job complete: the PHASE_DONE token


class KernelBackend:
    """Admits groups of commands, runs the automaton kernel, materializes the
    sequential-equivalent record stream. One instance per partition."""

    def __init__(self, engine, max_group: int = 256, max_steps: int = 4096,
                 chunk_steps: int = 16) -> None:
        self.engine = engine
        self.registry = KernelRegistry()
        self.max_group = max_group
        self.max_steps = max_steps
        self.chunk_steps = chunk_steps
        # observability
        self.groups_processed = 0
        self.commands_processed = 0
        self.fallbacks = 0

    # -- candidate test (no state access) ----------------------------------

    def is_candidate(self, record) -> bool:
        return (record.value_type, int(record.intent)) in _CANDIDATE_COMMANDS

    # -- admission ----------------------------------------------------------

    def _admit(self, cmd, instances: dict[int, _Inst]) -> _Admitted | None:
        record = cmd.record
        kind = (record.value_type, int(record.intent))
        if kind == (ValueType.PROCESS_INSTANCE_CREATION, int(ProcessInstanceCreationIntent.CREATE)):
            return self._admit_creation(cmd, instances)
        if kind == (ValueType.JOB, int(JobIntent.COMPLETE)):
            return self._admit_job_complete(cmd, instances)
        return None

    def _admit_creation(self, cmd, instances) -> _Admitted | None:
        state = self.engine.state
        value = cmd.record.value
        if value.get("startInstructions"):
            return None
        bpmn_process_id = value.get("bpmnProcessId", "")
        definition_key = value.get("processDefinitionKey", -1)
        version = value.get("version", -1)
        if definition_key > 0:
            meta = state.processes.get_by_key(definition_key)
        elif version > 0:
            key = state.processes.get_key_by_id_version(bpmn_process_id, version)
            meta = None if key is None else state.processes.get_by_key(key)
        else:
            meta = state.processes.get_latest_by_id(bpmn_process_id)
        if meta is None or meta.get("deleted"):
            return None  # sequential path writes the NOT_FOUND rejection
        def_key = meta["processDefinitionKey"]
        info = self.registry.lookup(def_key, state.processes.executable(def_key))
        if info is None:
            return None
        variables = value.get("variables") or {}
        slots: dict[str, float] = {}
        for name in info.cond_var_names:
            v = variables.get(name)
            if not _is_numeric(v):
                # a condition could read this variable: the host FEEL path and
                # the device float path would disagree on null/strings
                return None
            slots[name] = float(v)
        inst = _Inst(idx=len(instances), info=info, new=True, meta=meta, slots=slots)
        return _Admitted(cmd=cmd, inst=inst)

    def _admit_job_complete(self, cmd, instances) -> _Admitted | None:
        state = self.engine.state
        job_key = cmd.record.key
        job = state.jobs.get(job_key)
        if job is None:
            return None  # sequential path writes the NOT_FOUND rejection
        pi_key = job.get("processInstanceKey", -1)
        if pi_key in (i.pi_key for i in instances.values()):
            return None  # same-instance conflict: next group
        def_key = job.get("processDefinitionKey", -1)
        info = self.registry.lookup(def_key, state.processes.executable(def_key))
        if info is None:
            return None
        root = state.element_instances.get(pi_key)
        from zeebe_tpu.engine.engine_state import EI_ACTIVATED

        if root is None or root["state"] != EI_ACTIVATED:
            return None
        # every live element instance must be a task parked on a job — any
        # other state (mid-transition, incident) is not reconstructable
        exe = info.exe
        tokens: list[_Token] = []
        resume: _Token | None = None
        for child_key in sorted(state.element_instances.children_keys(pi_key)):
            child = state.element_instances.get(child_key)
            if child is None or child["state"] != EI_ACTIVATED:
                return None
            elem_id = child["value"].get("elementId", "")
            if elem_id not in exe.by_id:
                return None
            el = exe.element(elem_id)
            if self.registry.tables.kernel_op[info.index, el.idx] != K_TASK:
                return None
            if child.get("jobKey", -1) < 0:
                return None
            tok = _Token(slot=-1, elem_idx=el.idx, key=child_key,
                         value=dict(child["value"]), phase=_PHASE_WAIT)
            if child_key == job.get("elementInstanceKey", -1):
                tok.phase = _PHASE_DONE
                resume = tok
            tokens.append(tok)
        if resume is None:
            return None
        # pending parallel-join arrivals → device join counters
        join_counts: dict[int, int] = {}
        for jidx in info.join_idxs:
            el = exe.elements[jidx]
            total = sum(
                state.element_instances.taken_flow_count(pi_key, jidx, f.idx)
                for f in exe.flows
                if f.target_idx == jidx
            )
            if total:
                join_counts[jidx] = total
        # condition variables: post-merge view (scope vars + completion vars)
        merged = state.variables.collect(pi_key)
        merged.update(cmd.record.value.get("variables") or {})
        slots: dict[str, float] = {}
        for name in info.cond_var_names:
            v = merged.get(name)
            if not _is_numeric(v):
                return None
            slots[name] = float(v)
        inst = _Inst(idx=len(instances), info=info, new=False, pi_key=pi_key,
                     tokens=tokens, join_counts=join_counts, slots=slots)
        return _Admitted(cmd=cmd, inst=inst, resume_token=resume)

    # -- device run ----------------------------------------------------------

    @staticmethod
    def _pow2(n: int) -> int:
        p = 8
        while p < n:
            p *= 2
        return p

    def _run_kernel(self, admitted: list[_Admitted]) -> list[dict] | None:
        """Build the group batch, step to quiescence, return per-step host
        events (None → caller must fall back)."""
        import jax
        import jax.numpy as jnp

        from zeebe_tpu.ops.automaton import run_collect, unpack_events

        tables = self.registry.tables
        insts = [a.inst for a in admitted]
        n_real = len(insts)
        n_tokens = sum(max(1, len(i.tokens)) for i in insts)
        I = self._pow2(n_real)
        T = self._pow2(max(16, 4 * n_tokens))
        E = tables.max_elements
        S = tables.num_slots

        elem = np.full(T, -1, np.int32)
        phase = np.zeros(T, np.int32)
        inst_arr = np.zeros(T, np.int32)
        def_of = np.zeros(I, np.int32)
        var_slots = np.zeros((I, S), np.float32)
        join_counts = np.zeros((I, E), np.int32)
        done = np.zeros(I, np.bool_)
        done[n_real:] = True  # padding rows must never report newly_done

        slot = 0
        for i in insts:
            def_of[i.idx] = i.info.index
            for name, v in i.slots.items():
                var_slots[i.idx, tables.slot_map.names[name]] = v
            for jidx, count in i.join_counts.items():
                join_counts[i.idx, jidx] = count
            if i.new:
                i.tokens = [_Token(slot=slot, elem_idx=int(tables.start_elem[i.info.index]),
                                   key=-1, value={})]
                elem[slot] = i.tokens[0].elem_idx
                phase[slot] = _PHASE_AT
                inst_arr[slot] = i.idx
                slot += 1
            else:
                for tok in i.tokens:
                    tok.slot = slot
                    elem[slot] = tok.elem_idx
                    phase[slot] = tok.phase
                    inst_arr[slot] = i.idx
                    slot += 1

        state = {
            "elem": jnp.asarray(elem),
            "phase": jnp.asarray(phase),
            "inst": jnp.asarray(inst_arr),
            "def_of": jnp.asarray(def_of),
            "var_slots": jnp.asarray(var_slots),
            "join_counts": jnp.asarray(join_counts),
            "done": jnp.asarray(done),
            "incident": jnp.zeros(I, jnp.bool_),
            "transitions": jnp.zeros((), jnp.int32),
            "jobs_created": jnp.zeros((), jnp.int32),
            "completed": jnp.zeros((), jnp.int32),
            "overflow": jnp.zeros((), jnp.bool_),
        }
        config = tables.kernel_config
        dt = self.registry.device_tables
        # chunked device loop: one dispatch + ONE host transfer per chunk of
        # lock-steps (vs two transfers per step) — over the TPU tunnel a
        # transfer costs ~30ms, so this is the difference between ~2s and
        # ~60ms per group. Quiesced states are fixed points of step(), so a
        # chunk may harmlessly over-run past quiescence.
        chunk = self.chunk_steps
        steps: list[dict] = []
        overflow = False
        for _ in range(max(1, self.max_steps // chunk)):
            state, packed = run_collect(dt, state, n_steps=chunk, config=config)
            packed_host = jax.device_get(packed)
            overflow = packed_host[-1, 1, 3]
            active = packed_host[:, 0, 3]
            # steps after quiescence emit nothing — truncate so the host
            # decoder never walks empty tail steps
            quiesced = np.flatnonzero(active == 0)
            keep = int(quiesced[0]) + 1 if quiesced.size else chunk
            for s in range(keep):
                steps.append(unpack_events(packed_host[s], I))
            if quiesced.size:
                break
        else:
            logger.warning("kernel group did not quiesce in %d steps; falling back", self.max_steps)
            return None
        if bool(overflow):
            logger.warning("kernel token pool overflow (T=%d); falling back", T)
            return None
        return steps

    # -- materialization ------------------------------------------------------

    def process_group(self, cmds, make_builder: Callable[[], Any]) -> tuple[list, list]:
        """Pull commands from the ``cmds`` iterator while they admit (lazy: a
        non-admittable head costs one log read, not a full peek), run the
        kernel, and materialize each admitted command's record burst into its
        own result builder. Returns (admitted_cmds, builders); an empty list
        means the caller should process the head command sequentially.

        Must run inside the partition's open db transaction."""
        instances: dict[int, _Inst] = {}
        admitted: list[_Admitted] = []
        for cmd in cmds:
            adm = self._admit(cmd, instances)
            if adm is None:
                break
            instances[adm.inst.idx] = adm.inst
            admitted.append(adm)
            if len(admitted) >= self.max_group:
                break
        if not admitted:
            self.fallbacks += 1
            return [], []
        steps = self._run_kernel(admitted)
        if steps is None:
            self.fallbacks += 1
            return [], []

        from zeebe_tpu.engine.writers import Writers

        builders = []
        for adm in admitted:
            builder = make_builder()
            writers = Writers(builder, self.engine.appliers)
            if adm.inst.new:
                self._materialize_creation(adm, steps, writers, builder)
            else:
                self._materialize_job_complete(adm, steps, writers, builder)
            builders.append(builder)
        self.groups_processed += 1
        self.commands_processed += len(admitted)
        return [a.cmd for a in admitted], builders

    def _mark_last_command_processed(self, builder) -> None:
        for entry in reversed(builder.follow_ups):
            if entry.record.is_command:
                entry.processed = True
                return

    def _materialize_creation(self, adm: _Admitted, steps, writers, builder) -> None:
        from zeebe_tpu.engine.bpmn import _pi_value

        engine = self.engine
        state = engine.state
        inst = adm.inst
        exe = inst.info.exe
        # the sequential creation processor writes CREATED + response +
        # ACTIVATE(process) command + seed VARIABLE events — reuse it verbatim
        creation = engine._processors[
            (ValueType.PROCESS_INSTANCE_CREATION, int(ProcessInstanceCreationIntent.CREATE))
        ]
        mark = len(builder.follow_ups)
        creation(adm.cmd, writers)
        # locate the minted instance key + the ACTIVATE(process) command
        activate_cmd = None
        for entry in builder.follow_ups[mark:]:
            if entry.record.is_command and entry.record.value_type == ValueType.PROCESS_INSTANCE:
                activate_cmd = entry
                break
        if activate_cmd is None:  # rejection (definition vanished mid-group)
            return
        activate_cmd.processed = True
        inst.pi_key = activate_cmd.record.key
        process_el = exe.root
        value = _pi_value(dict(activate_cmd.record.value), process_el)
        writers.append_event(inst.pi_key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATING, value)
        writers.append_event(inst.pi_key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
        # ACTIVATE(start) — mirror BpmnProcessor._write_activate
        start = exe.elements[exe.none_start_of(0)]
        tok = inst.tokens[0]
        tok.key = state.next_key()
        tok.value = self._child_value(value, start, inst.pi_key)
        writers.append_command(tok.key, ValueType.PROCESS_INSTANCE,
                               PI.ACTIVATE_ELEMENT, tok.value)
        self._mark_last_command_processed(builder)
        self._cascade(inst, steps, writers, builder)

    def _materialize_job_complete(self, adm: _Admitted, steps, writers, builder) -> None:
        engine = self.engine
        job_complete = engine._processors[(ValueType.JOB, int(JobIntent.COMPLETE))]
        job_complete(adm.cmd, writers)  # JOB COMPLETED + response + variables
        self._mark_last_command_processed(builder)  # the COMPLETE_ELEMENT cmd
        self._cascade(adm.inst, steps, writers, builder)

    @staticmethod
    def _child_value(scope_value: dict, element: ExecutableElement, scope_key: int) -> dict:
        """Mirror BpmnProcessor._write_activate's record value exactly."""
        return {
            "bpmnProcessId": scope_value["bpmnProcessId"],
            "version": scope_value["version"],
            "processDefinitionKey": scope_value["processDefinitionKey"],
            "processInstanceKey": scope_value["processInstanceKey"],
            "elementId": element.id,
            "flowScopeKey": scope_key,
            "bpmnElementType": element.element_type.name,
            "bpmnEventType": element.event_type.name,
        }

    def _cascade(self, inst: _Inst, steps, writers, builder) -> None:
        """Walk the device steps for one instance in the sequential engine's
        FIFO follow-up order, writing its record burst."""
        from zeebe_tpu.engine.bpmn import _pi_value

        state = self.engine.state
        exe = inst.info.exe
        order: list[_Token] = list(inst.tokens)

        for ev in steps:
            if inst.done_emitted or not order:
                break
            additions: list[_Token] = []
            for tok in list(order):
                s = tok.slot
                if ev["inst"][s] != inst.idx or ev["elem"][s] != tok.elem_idx:
                    continue  # slot reused after this token died (stale entry)
                element = exe.elements[tok.elem_idx]
                value = _pi_value(tok.value, element)
                if ev["task_arrive"][s]:
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_ACTIVATING, value)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_ACTIVATED, value)
                    self._emit_job_created(inst, tok, element, writers)
                    tok.phase = _PHASE_WAIT
                elif ev["task_done"][s]:
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_COMPLETING, value)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_COMPLETED, value)
                    self._emit_flows(inst, tok, value, ev, writers, builder, additions)
                    order.remove(tok)
                elif ev["full_pass"][s]:
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_ACTIVATING, value)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_ACTIVATED, value)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_COMPLETING, value)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_COMPLETED, value)
                    self._emit_flows(inst, tok, value, ev, writers, builder, additions)
                    order.remove(tok)
                elif ev["no_match"][s]:
                    # gateway with no true condition and no default: incident,
                    # element parks in COMPLETING (BpmnProcessor._complete →
                    # _choose_exclusive_flow → _raise_incident)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_ACTIVATING, value)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_ACTIVATED, value)
                    writers.append_event(tok.key, ValueType.PROCESS_INSTANCE,
                                         PI.ELEMENT_COMPLETING, value)
                    incident_key = state.next_key()
                    writers.append_event(
                        incident_key, ValueType.INCIDENT, IncidentIntent.CREATED,
                        {
                            "errorType": ErrorType.CONDITION_ERROR.name,
                            "errorMessage": (
                                "Expected at least one condition to evaluate to true, "
                                f"or to have a default flow at gateway '{element.id}'"
                            ),
                            "bpmnProcessId": value.get("bpmnProcessId", ""),
                            "processDefinitionKey": value.get("processDefinitionKey", -1),
                            "processInstanceKey": value.get("processInstanceKey", -1),
                            "elementId": value.get("elementId", ""),
                            "elementInstanceKey": tok.key,
                            "jobKey": -1,
                            "variableScopeKey": tok.key,
                        },
                    )
                    order.remove(tok)
            order.extend(additions)
            inst.tokens = order
            if ev["newly_done"][inst.idx] and not inst.done_emitted:
                self._emit_process_completed(inst, writers, builder)

    def _emit_flows(self, inst: _Inst, tok: _Token, value: dict, ev, writers,
                    builder, additions: list[_Token]) -> None:
        """SEQUENCE_FLOW_TAKEN + child ACTIVATE commands for one completing
        token, in flow-slot order (mirrors _complete → _take_flow)."""
        state = self.engine.state
        tables = self.registry.tables
        exe = inst.info.exe
        d = inst.info.index
        e = tok.elem_idx
        T = ev["elem"].shape[0]
        for fo in range(ev["take_mask"].shape[1]):
            if not ev["take_mask"][tok.slot, fo]:
                continue
            flow = exe.flows[int(tables.out_flow_idx[d, e, fo])]
            flow_value = {
                "bpmnProcessId": value["bpmnProcessId"],
                "version": value["version"],
                "processDefinitionKey": value["processDefinitionKey"],
                "processInstanceKey": value["processInstanceKey"],
                "elementId": flow.id,
                "flowScopeKey": value.get("flowScopeKey", -1),
                "bpmnElementType": BpmnElementType.SEQUENCE_FLOW.name,
                "bpmnEventType": BpmnEventType.UNSPECIFIED.name,
            }
            flow_key = state.next_key()
            writers.append_event(flow_key, ValueType.PROCESS_INSTANCE,
                                 PI.SEQUENCE_FLOW_TAKEN, flow_value)
            dest = int(ev["dest"][tok.slot, fo])
            if dest < T:
                target = exe.elements[flow.target_idx]
                child_key = state.next_key()
                child_value = self._child_value(value, target, value.get("flowScopeKey", -1))
                writers.append_command(child_key, ValueType.PROCESS_INSTANCE,
                                       PI.ACTIVATE_ELEMENT, child_value)
                self._mark_last_command_processed(builder)
                additions.append(_Token(slot=dest, elem_idx=target.idx,
                                        key=child_key, value=child_value))

    def _emit_job_created(self, inst: _Inst, tok: _Token, element: ExecutableElement,
                          writers) -> None:
        """Mirror BpmnProcessor._activate's job-worker task branch."""
        state = self.engine.state
        value = tok.value
        job_key = state.next_key()
        writers.append_event(
            job_key, ValueType.JOB, JobIntent.CREATED,
            {
                "type": inst.info.job_types[element.idx],
                "retries": inst.info.job_retries[element.idx],
                "worker": "",
                "deadline": -1,
                "variables": {},
                "customHeaders": element.task_headers,
                "elementId": element.id,
                "elementInstanceKey": tok.key,
                "processInstanceKey": value["processInstanceKey"],
                "processDefinitionKey": value["processDefinitionKey"],
                "processDefinitionVersion": value["version"],
                "bpmnProcessId": value["bpmnProcessId"],
                "errorMessage": "",
            },
        )

    def _emit_process_completed(self, inst: _Inst, writers, builder) -> None:
        """Mirror _check_scope_completion → COMPLETE_ELEMENT(process) →
        _complete(process) → _on_process_completed."""
        from zeebe_tpu.engine.bpmn import _pi_value

        state = self.engine.state
        bpmn = self.engine.bpmn
        root = state.element_instances.get(inst.pi_key)
        if root is None:
            return
        writers.append_command(inst.pi_key, ValueType.PROCESS_INSTANCE,
                               PI.COMPLETE_ELEMENT, {})
        self._mark_last_command_processed(builder)
        process_el = inst.info.exe.root
        value = _pi_value(dict(root["value"]), process_el)
        writers.append_event(inst.pi_key, ValueType.PROCESS_INSTANCE,
                             PI.ELEMENT_COMPLETING, value)
        child_locals = state.variables.locals_of(inst.pi_key)
        writers.append_event(inst.pi_key, ValueType.PROCESS_INSTANCE,
                             PI.ELEMENT_COMPLETED, value)
        bpmn._on_process_completed(inst.pi_key, value, child_locals or {}, writers)
        inst.done_emitted = True
